"""Compiled-signature cache for serving: warm executables per bucket.

Layered on :class:`~mxnet_tpu.cached_op.CachedOp` — the whole-graph XLA
compile-and-replay executor — with the serving-specific pieces on top:

- an **LRU bound** sized to the bucket set (the batcher guarantees a
  closed signature set, so the bound is a guard rail, not a working
  policy; see ``CachedOp(cache_size=...)``),
- **explicit warmup**: :meth:`SignatureCache.warmup` drives a zero batch
  through every (item shape, batch bucket) combination up front, so the
  first real request never pays a multi-second XLA compile,
- **hit/miss/evict counters** surfaced to the metrics plane via
  :meth:`cache_info` (a CachedOp miss == one trace + compile, which is how
  the serving tests count compiles).

A plain callable (no gluon Parameters) is accepted too and invoked
directly — useful for tests and for pre-jitted jax functions; counters
then track signatures seen rather than compiles.
"""
from __future__ import annotations

import itertools
import threading
from collections import OrderedDict
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError
from ..cached_op import CachedOp, CacheInfo
from ..telemetry import memory as _memory

__all__ = ["SignatureCache"]

_MEM_OWNERS = itertools.count(1)


class SignatureCache:
    """Executable cache keyed on (item shape, batch bucket, dtype)."""

    def __init__(self, model, cache_size: Optional[int] = None):
        self._lock = threading.Lock()
        # memory-ledger owner tag: every ledgered byte of this cache's
        # compiled programs carries it, so per-model bytes are queryable
        # (ServerMetrics polls it) and die with the cache
        self.mem_owner = f"sigcache{next(_MEM_OWNERS)}"
        self._is_block = hasattr(model, "collect_params")
        if self._is_block:
            self._op: Optional[CachedOp] = CachedOp(model,
                                                    cache_size=cache_size)
            self._fn: Callable = self._op.__call__
        else:
            if not callable(model):
                raise MXNetError(
                    f"SignatureCache needs a gluon Block or a callable, "
                    f"got {type(model).__name__}")
            self._op = None
            self._fn = model
            self._seen: "OrderedDict[Tuple, None]" = OrderedDict()
            self._plain_hits = 0
            self._plain_misses = 0

    # -----------------------------------------------------------------
    def __call__(self, batch_nd):
        """Run one padded batch (NDArray in, NDArray/tuple out)."""
        if self._op is None:
            key = (tuple(batch_nd.shape), str(batch_nd.dtype))
            with self._lock:
                if key in self._seen:
                    self._plain_hits += 1
                else:
                    self._seen[key] = None
                    self._plain_misses += 1
        return self._fn(batch_nd)

    def warmup(self, item_shapes: Sequence[Tuple[int, ...]],
               batch_sizes: Sequence[int],
               dtype: str = "float32") -> int:
        """Compile every (item shape, batch bucket) signature by running a
        zero batch through the model. Returns the number of executables
        compiled (signatures that were not already resident)."""
        from ..ndarray import ndarray as _nd
        before = self.cache_info().misses
        for shape in item_shapes:
            for b in batch_sizes:
                x = _nd.array(np.zeros((int(b),) + tuple(shape), np.dtype(dtype)))
                out = self(x)
                # force the compile + execution to finish now, not on the
                # first real request
                outs = out if isinstance(out, (list, tuple)) else (out,)
                for o in outs:
                    o.asnumpy()
        return self.cache_info().misses - before

    def cache_info(self) -> CacheInfo:
        if self._op is not None:
            return self._op.cache_info()
        with self._lock:
            return CacheInfo(self._plain_hits, self._plain_misses, 0,
                             len(self._seen), None)

    def program_memory(self, refresh: bool = False) -> dict:
        """Static memory footprint of every warm compiled signature
        (``CachedOp.memory_analysis``), registered in the live-byte
        ledger under ``serving_cache`` with this cache's owner tag —
        the per-model bytes ``ServerMetrics`` exposes. Bytes rise as
        signatures warm and fall when the cache is drained/undeployed
        (the ledger entries die with the CachedOp). Plain-callable
        models own no compiled programs and report {}."""
        if self._op is None:
            return {}
        stats = self._op.memory_analysis(refresh=refresh)
        _memory.register_cache_programs(self.mem_owner, self._op, stats)
        return stats

    def memory_bytes(self) -> int:
        """Ledgered bytes of this cache's recorded programs (0 until
        :meth:`program_memory` has run)."""
        return _memory.ledger().live_bytes(
            "serving_cache", owner_prefix=self.mem_owner + ":")
