"""Dynamic batching policy: shape buckets, batch padding, admission types.

The serving value proposition of the reference's model server (MMS) is
dynamic batching: concurrent single-example requests are coalesced into one
model dispatch so per-dispatch fixed costs (host relay, XLA dispatch,
kernel launch) amortize. On TPU there is a second, sharper reason: XLA
compiles one executable per input signature, so free-form request shapes
mean a compile per shape. The batcher therefore maps every request into a
small CLOSED set of signatures:

- **shape buckets**: a request's item shape (no batch dim) must match one
  of the configured ``bucket_shapes`` exactly (or, unconfigured, each
  distinct observed shape becomes its own bucket — convenient, but the
  signature set is then open). Requests that fit no bucket are rejected
  with :class:`NoBucket` at admission, not at dispatch.
- **batch buckets**: the real row count is padded up to the next power of
  two (capped by ``max_batch_size``) with zero rows. Total signatures =
  |shape buckets| x |batch buckets|, independent of traffic.

Padding rows are sliced back off before results are delivered, so a
row-independent model (anything in inference mode — BatchNorm uses moving
stats) returns bit-exact the same rows as the hybridized model called at
the same padded batch size (eager execution and other batch sizes can
differ in the last ulp — XLA fusion/tiling, not the batcher).

This module is the *policy* layer — pure, synchronous, unit-testable. The
threads that drive it live in :mod:`mxnet_tpu.serving.server`.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError

__all__ = ["ServingError", "QueueFull", "DeadlineExceeded", "NoBucket",
           "ServerClosed", "PredictionFuture", "Request", "Batch",
           "BucketTable", "batch_buckets", "pad_rows"]


class ServingError(MXNetError):
    """Base class for typed serving rejections."""


class QueueFull(ServingError):
    """Admission queue is at ``queue_depth``: load is shed at the door
    (backpressure) instead of buffering until OOM. Clients should retry
    with backoff or route elsewhere."""


class DeadlineExceeded(ServingError):
    """The request's deadline expired while it waited; it was dropped
    WITHOUT being dispatched — no model compute was spent on it."""


class NoBucket(ServingError):
    """The request's item shape matches none of the configured shape
    buckets (a closed signature set is the whole point — see module doc)."""


class ServerClosed(ServingError):
    """The server is draining (SIGTERM/stop); no new work is admitted."""


class PredictionFuture:
    """Write-once result slot handed back by ``ModelServer.submit``.

    After the batch is dispatched, ``version`` carries the tag of the
    model version that served it (None for registry-less servers) and
    ``dispatch_seq`` the server-wide dispatch sequence number — the pair
    is how hot-swap tests prove version flips are atomic (tags are
    monotone in ``dispatch_seq`` order)."""

    __slots__ = ("_event", "_result", "_error", "version", "dispatch_seq")

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None
        self.version: Optional[str] = None
        self.dispatch_seq: Optional[int] = None

    def set_result(self, value) -> None:
        self._result = value
        self._event.set()

    def set_exception(self, err: BaseException) -> None:
        self._error = err
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("prediction not ready")
        if self._error is not None:
            raise self._error
        return self._result


class Request:
    """One admitted example plus its timing/deadline bookkeeping."""

    __slots__ = ("payload", "key", "deadline", "t_submit", "t_formed",
                 "future")

    def __init__(self, payload: np.ndarray, key: Tuple,
                 deadline: Optional[float]):
        self.payload = payload
        self.key = key                      # (item_shape, dtype_str)
        self.deadline = deadline            # absolute monotonic, or None
        self.t_submit = time.perf_counter()
        self.t_formed: Optional[float] = None
        self.future = PredictionFuture()

    def expired(self, now: Optional[float] = None) -> bool:
        return self.deadline is not None and \
            (now if now is not None else time.perf_counter()) >= self.deadline


class Batch:
    """A flushed bucket: requests that will ride one model dispatch."""

    __slots__ = ("key", "requests", "t_formed")

    def __init__(self, key: Tuple, requests: List[Request]):
        self.key = key
        self.requests = requests
        self.t_formed = time.perf_counter()
        for r in requests:
            r.t_formed = self.t_formed


def batch_buckets(max_batch_size: int) -> Tuple[int, ...]:
    """The closed set of padded batch sizes: powers of two up to (and
    always including) ``max_batch_size``."""
    sizes = []
    b = 1
    while b < max_batch_size:
        sizes.append(b)
        b *= 2
    sizes.append(max_batch_size)
    return tuple(sizes)


def pad_rows(rows: List[np.ndarray], bucket: int) -> np.ndarray:
    """Stack item arrays into a (bucket, *item) batch, zero-padding the
    tail rows. The caller slices off everything past ``len(rows)``."""
    stacked = np.stack(rows)
    if len(rows) < bucket:
        pad = np.zeros((bucket - len(rows),) + stacked.shape[1:],
                       stacked.dtype)
        stacked = np.concatenate([stacked, pad])
    return stacked


class BucketTable:
    """Pending requests grouped by (item shape, dtype), with the flush
    policy: a bucket flushes when it reaches ``max_batch_size`` rows or
    when its oldest request has waited ``max_queue_latency_ms``.

    Not thread-safe by itself — the server's batcher thread is the only
    writer, under the server's admission lock.
    """

    def __init__(self, max_batch_size: int, max_queue_latency_ms: float,
                 bucket_shapes: Optional[Sequence[Tuple[int, ...]]] = None):
        if max_batch_size < 1:
            raise MXNetError("max_batch_size must be >= 1")
        self.max_batch_size = int(max_batch_size)
        self.max_latency_s = float(max_queue_latency_ms) / 1000.0
        self.bucket_shapes = (None if bucket_shapes is None else
                              {tuple(s) for s in bucket_shapes})
        self.batch_sizes = batch_buckets(self.max_batch_size)
        self._pending: Dict[Tuple, List[Request]] = {}
        self._first_at: Dict[Tuple, float] = {}

    def key_for(self, shape: Tuple[int, ...], dtype: str) -> Tuple:
        """Admission-time bucket resolution; raises :class:`NoBucket` for
        shapes outside the configured set."""
        shape = tuple(int(s) for s in shape)
        if self.bucket_shapes is not None and shape not in self.bucket_shapes:
            raise NoBucket(
                f"request item shape {shape} matches no configured bucket "
                f"(buckets: {sorted(self.bucket_shapes)})")
        return (shape, str(dtype))

    def pad_to(self, n: int) -> int:
        for b in self.batch_sizes:
            if n <= b:
                return b
        return self.batch_sizes[-1]

    @property
    def pending_count(self) -> int:
        return sum(len(v) for v in self._pending.values())

    def add(self, req: Request) -> Optional[Batch]:
        """File a request; returns a full Batch when the bucket hit
        ``max_batch_size``."""
        lst = self._pending.setdefault(req.key, [])
        if not lst:
            self._first_at[req.key] = time.perf_counter()
        lst.append(req)
        if len(lst) >= self.max_batch_size:
            return self._flush(req.key)
        return None

    def _flush(self, key: Tuple) -> Batch:
        reqs = self._pending.pop(key)
        self._first_at.pop(key, None)
        return Batch(key, reqs)

    def due(self, now: Optional[float] = None) -> List[Batch]:
        """Flush every bucket whose oldest request aged past the latency
        budget."""
        now = time.perf_counter() if now is None else now
        out = []
        for key, t0 in list(self._first_at.items()):
            if now - t0 >= self.max_latency_s:
                out.append(self._flush(key))
        return out

    def flush_all(self) -> List[Batch]:
        """Drain: flush every pending bucket regardless of age."""
        return [self._flush(k) for k in list(self._pending)]

    def next_deadline(self) -> Optional[float]:
        """Monotonic time of the earliest pending flush, or None."""
        if not self._first_at:
            return None
        return min(self._first_at.values()) + self.max_latency_s
