"""Zero-compile cold start: persistent compile cache, AOT bundles, replay.

PR 5's telemetry showed XLA compilation dominating replica cold start (the
``mxtpu_xla_compile_seconds_total`` counter); this module is the three-layer
answer, so a new replica of an already-published version reaches first byte
without compiling anything:

1. **Persistent compile cache** (:func:`enable_compile_cache`): jax's
   on-disk compilation cache, keyed by (program, jaxlib version, backend) —
   a recompile of a signature any previous process compiled is a disk read.
   The cache directory is namespaced by :func:`runtime_fingerprint` so a
   jaxlib upgrade starts a fresh cache instead of colliding.
2. **AOT executable bundles**: ``CachedOp.aot_export`` serializes the
   compiled executables of the closed ``bucket_shapes x batch-bucket``
   signature set (``jax.experimental.serialize_executable``); published
   alongside the version (``aot.bin``), ``CachedOp.aot_load`` installs them
   on a new replica with zero traces AND zero compiles. Fingerprint-gated:
   a mismatched runtime falls back to layer 1.
3. **Signature replay** (:class:`ReplayLog`): production shape traffic is
   recorded (one line per distinct signature) and new replicas prewarm
   from it — the signatures real traffic exercises, not just the
   configured closure.
"""
from __future__ import annotations

import json
import os
import threading
from typing import List, Optional, Sequence, Tuple

from ..base import env
from ..log import get_logger

__all__ = ["enable_compile_cache", "runtime_fingerprint", "ReplayLog",
           "warm_from_replay"]

_LOG = get_logger("mxnet_tpu.serving.aot")


def runtime_fingerprint() -> dict:
    """The runtime identity compiled artifacts are only valid within."""
    try:
        import jax
        import jaxlib
        backend = "unknown"
        try:
            backend = jax.default_backend()
        except Exception:
            pass
        return {"jax": jax.__version__, "jaxlib": jaxlib.__version__,
                "backend": backend}
    except Exception:
        return {"jax": "none", "jaxlib": "none", "backend": "none"}


def fingerprint_token(fp: Optional[dict] = None) -> str:
    """Filesystem-safe string form of the fingerprint (cache subdir key)."""
    fp = fp or runtime_fingerprint()
    return "-".join(str(fp.get(k, "none")).replace("/", "_")
                    for k in ("jaxlib", "backend"))


def enable_compile_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Wire jax's persistent on-disk compilation cache for serving.

    Resolution order: explicit ``cache_dir`` > ``MXTPU_COMPILE_CACHE`` env.
    Returns the effective cache directory (namespaced by the runtime
    fingerprint), or None when disabled (no dir configured, or an explicit
    ``0``/``off``). Every compile-time knob is forced to cache-everything
    (min compile time / entry size 0): a serving replica's goal is zero
    compile seconds on restart, not disk thrift. This is also the ONE
    wiring implementation: ``util.enable_compile_cache`` (bench/tools)
    delegates here after applying its own policy (default repo-wide dir,
    CPU skipped unless the variable is set explicitly); the serving path
    honors an explicitly configured cache on every backend — the
    cold-start contract must be testable on CPU CI.
    """
    if cache_dir is None:
        cache_dir = env.get("MXTPU_COMPILE_CACHE")
    if not cache_dir or str(cache_dir).lower() in ("0", "off", "disabled",
                                                   "none"):
        return None
    try:
        import jax
        effective = os.path.join(str(cache_dir), fingerprint_token())
        os.makedirs(effective, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", effective)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        except Exception:
            pass  # knob absent on older jaxlibs
        try:
            # jax latches the cache object at the FIRST compile of the
            # process; anything that compiled before this call (op
            # registry warmup during import, a publish step) initialized
            # it with no directory — leaving the cache silently disabled
            # for the replica's whole life. Un-latch so the next compile
            # re-initializes from the config we just set.
            from jax._src import compilation_cache as _cc
            if _cc._cache_initialized and _cc._cache is None:
                _cc.reset_cache()
        except Exception:
            pass
        _LOG.info("persistent compile cache at %s", effective)
        return effective
    except Exception as e:
        _LOG.warning("compile cache unavailable: %s", e)
        return None


class ReplayLog:
    """Append-only record of the serving signatures real traffic hit.

    One JSON line per *distinct* (item shape, dtype, padded batch)
    signature — the file is a set, not a stream, so it stays tiny and a
    prewarm replays each signature once. Thread-safe (serving workers
    record concurrently); recording an already-seen signature is one set
    lookup, no IO.
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._seen: set = set()
        # resume the dedup set from an existing file so restarts append
        # only genuinely new signatures
        for shape, dtype, batch in self.signatures(path):
            self._seen.add((shape, dtype, batch))

    def record(self, item_shape: Sequence[int], dtype: str,
               batch: int) -> bool:
        """Record one dispatched signature; returns True when it was new
        (and therefore appended to the file)."""
        key = (tuple(int(s) for s in item_shape), str(dtype), int(batch))
        with self._lock:
            if key in self._seen:
                return False
            self._seen.add(key)
            try:
                os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                            exist_ok=True)
                with open(self.path, "a") as f:
                    f.write(json.dumps({"shape": list(key[0]),
                                        "dtype": key[1],
                                        "batch": key[2]}) + "\n")
            except OSError as e:
                _LOG.warning("replay log %s unwritable: %s", self.path, e)
        return True

    @staticmethod
    def signatures(path: str) -> List[Tuple[Tuple[int, ...], str, int]]:
        """Parse a replay file into (item_shape, dtype, batch) tuples
        (deduplicated, file order). Unparseable lines are skipped — a
        torn tail write must not take down a prewarm."""
        out: List[Tuple[Tuple[int, ...], str, int]] = []
        seen = set()
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                        key = (tuple(int(s) for s in rec["shape"]),
                               str(rec["dtype"]), int(rec["batch"]))
                    except (ValueError, KeyError, TypeError):
                        continue
                    if key not in seen:
                        seen.add(key)
                        out.append(key)
        except OSError:
            pass
        return out


def warm_from_replay(cache, path: str, signatures=None) -> int:
    """Prewarm a :class:`~mxnet_tpu.serving.cache.SignatureCache` from a
    replay file: every recorded (shape, dtype, batch) signature is driven
    once. Returns the number of fresh compiles performed (0 when the AOT
    bundle / compile cache already covered the traffic). Pass
    ``signatures`` when the caller already parsed the file."""
    import numpy as np
    from ..ndarray import ndarray as _nd
    before = cache.cache_info().misses
    if signatures is None:
        signatures = ReplayLog.signatures(path)
    for shape, dtype, batch in signatures:
        x = _nd.array(np.zeros((batch,) + shape, np.dtype(dtype)))
        out = cache(x)
        outs = out if isinstance(out, (list, tuple)) else (out,)
        for o in outs:
            o.asnumpy()
    return cache.cache_info().misses - before
