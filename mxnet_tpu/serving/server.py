"""ModelServer: the serving runtime (threads, admission, deadlines, drain).

Request life cycle::

    submit() ── admission control ──> admit deque ──> batcher thread
      │   QueueFull / NoBucket / ServerClosed          │ shape buckets,
      │   shed HERE, typed, never queued               │ flush on size/age
      ▼                                                ▼
    PredictionFuture  <── worker threads <── dispatch queue (bounded)
                           │ deadline filter BEFORE dispatch
                           │ pad to batch bucket, one CachedOp replay
                           └ split rows back to futures + metrics

Design decisions, mirrored from the evidence in PRs 1-2:

- **Backpressure, not buffering**: the admitted-but-undispatched count is
  bounded by ``queue_depth``; excess load is rejected at ``submit`` with
  :class:`QueueFull`. Nothing in the server blocks a client thread.
- **Deadlines drop work before compute**: a request whose deadline expired
  while queued is rejected by the worker *before* the batch is padded and
  dispatched — expired work never occupies the accelerator.
- **Graceful drain**: SIGTERM/SIGINT (or ``stop(drain=True)``) stops
  admission, flushes every pending bucket, finishes in-flight batches,
  then ``serve_forever`` exits with the resumable code shared with
  ``fit.FitLoop`` so one relauncher policy covers training and serving.
- **Chaos-testable**: an installed ``contrib.chaos`` plan with a
  ``serve_slow:P@ms`` event delays batch compute deterministically, which
  is how the deadline/saturation behaviors are regression-tested.
"""
from __future__ import annotations

import queue
import signal
import sys
import threading
import time
from typing import Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError, env
from ..log import get_logger
from .batcher import (Batch, BucketTable, DeadlineExceeded, NoBucket,
                      PredictionFuture, QueueFull, Request, ServerClosed,
                      pad_rows)
from .cache import SignatureCache
from .metrics import ServerMetrics

__all__ = ["ModelServer", "ActiveModel"]

_LOG = get_logger("mxnet_tpu.serving")

_STOP = object()  # worker sentinel


class ActiveModel:
    """The unit of atomic hot-swap: ONE reference the workers read.

    Everything that must change together when a new version takes over —
    the warm :class:`SignatureCache` and the version tag stamped on every
    response — lives behind a single attribute (``ModelServer._active``),
    so the flip is one Python reference assignment: any batch observes
    either the old model or the new one, never a mix. ``inflight`` counts
    batches currently executing against THIS model so a deployer can
    drain the old version after the flip.
    """

    __slots__ = ("cache", "version", "inflight", "_lock", "_idle")

    def __init__(self, cache: SignatureCache, version: Optional[str] = None):
        self.cache = cache
        self.version = version
        self.inflight = 0
        self._lock = threading.Lock()
        self._idle = threading.Event()
        self._idle.set()

    def enter(self) -> None:
        with self._lock:
            self.inflight += 1
            self._idle.clear()

    def exit(self) -> None:
        with self._lock:
            self.inflight -= 1
            if self.inflight <= 0:
                self._idle.set()

    def drain(self, timeout: float = 30.0) -> bool:
        """Wait until no batch is executing against this model."""
        return self._idle.wait(timeout)


class ModelServer:
    """Dynamic-batching inference server over a gluon block (or callable).

    Parameters
    ----------
    model : gluon Block (compiled per signature through CachedOp) or any
        callable mapping a batched NDArray to an NDArray / tuple of them.
    bucket_shapes : closed set of admissible item shapes (no batch dim);
        None lets every observed shape open its own bucket (open signature
        set — fine for experiments, not for production compile budgets).
    max_batch_size / max_queue_latency_ms / queue_depth : batching policy
        knobs; default from MXTPU_SERVE_MAX_BATCH / _MAX_LATENCY_MS /
        _QUEUE_DEPTH.
    workers : worker threads running model dispatch (host-side pre/post
        overlap; XLA executions already queue device-side).
    default_deadline_ms : per-request deadline applied when ``submit``
        gets none; None = no deadline.
    dtype : the server's input dtype; every admitted payload is coerced
        to it (a python list would otherwise arrive float64 and open an
        unwarmed XLA signature on the hot path). Uncastable payloads are
        rejected with :class:`NoBucket`.
    """

    def __init__(self, model, bucket_shapes: Optional[Sequence] = None,
                 max_batch_size: Optional[int] = None,
                 max_queue_latency_ms: Optional[float] = None,
                 queue_depth: Optional[int] = None, workers: int = 1,
                 cache_size: Optional[int] = None,
                 default_deadline_ms: Optional[float] = None,
                 dtype: str = "float32", name: str = "model"):
        if max_batch_size is None:
            max_batch_size = int(env.get("MXTPU_SERVE_MAX_BATCH"))
        if max_queue_latency_ms is None:
            max_queue_latency_ms = float(env.get("MXTPU_SERVE_MAX_LATENCY_MS"))
        if queue_depth is None:
            queue_depth = int(env.get("MXTPU_SERVE_QUEUE_DEPTH"))
        if queue_depth < 1:
            raise MXNetError("queue_depth must be >= 1")
        if int(workers) < 1:
            raise MXNetError("workers must be >= 1 (0 workers would admit "
                             "requests whose futures never resolve)")
        self.name = name
        self.dtype = np.dtype(dtype)
        self._table = BucketTable(max_batch_size, max_queue_latency_ms,
                                  bucket_shapes)
        self.queue_depth = int(queue_depth)
        self._default_deadline_ms = default_deadline_ms
        self._cache_size = cache_size
        self._active = ActiveModel(
            SignatureCache(model, cache_size=cache_size))
        self.metrics = ServerMetrics(name)
        self.metrics.cache_info_fn = lambda: self._active.cache.cache_info()
        self.metrics.memory_fn = lambda: self._active.cache.memory_bytes()
        # replay recorder (serving/aot.py): every dispatched signature is
        # logged once so new replicas can prewarm from real traffic
        self._replay = None
        replay_path = env.get("MXTPU_SERVE_REPLAY")
        if replay_path:
            from .aot import ReplayLog
            self._replay = ReplayLog(replay_path)
        self._dispatch_seq = 0  # allocated under _cond with the version
        self._cond = threading.Condition()
        self._admit: "list[Request]" = []
        self._queued = 0            # admitted, not yet dispatched/rejected
        self._dispatch_q: "queue.Queue" = queue.Queue(
            maxsize=max(2, 2 * int(workers)))
        self._workers = int(workers)
        self._threads: "list[threading.Thread]" = []
        self._started = False
        self._closed = False        # no new admissions
        self._abort = False         # drop queued work instead of finishing
        self._report_written = False  # one serving run report per lifetime
        self._sig_event = threading.Event()
        self._signum: Optional[int] = None
        self._old_handlers: dict = {}

    @property
    def cache(self) -> SignatureCache:
        """The ACTIVE model's signature cache (changes on hot-swap)."""
        return self._active.cache

    @property
    def active_version(self) -> Optional[str]:
        """Version tag of the model currently serving (None when the
        server was built from a bare model instead of a registry)."""
        return self._active.version

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ModelServer":
        with self._cond:
            if self._started:
                return self
            self._started = True
        t = threading.Thread(target=self._batcher_loop,
                             name=f"serve-batcher[{self.name}]", daemon=True)
        t.start()
        self._threads.append(t)
        for i in range(self._workers):
            w = threading.Thread(target=self._worker_loop,
                                 name=f"serve-worker[{self.name}]-{i}",
                                 daemon=True)
            w.start()
            self._threads.append(w)
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop admission; ``drain=True`` finishes everything already
        admitted, ``drain=False`` rejects it with :class:`ServerClosed`."""
        with self._cond:
            self._closed = True
            if not drain:
                self._abort = True
            self._cond.notify_all()
        if not self._started:
            return
        deadline = time.perf_counter() + timeout
        for t in self._threads:
            t.join(max(0.0, deadline - time.perf_counter()))
        alive = [t.name for t in self._threads if t.is_alive()]
        if alive:
            raise MXNetError(f"serving drain timed out after {timeout}s "
                             f"(stuck threads: {alive})")
        if drain:
            # serving-mode run report: the drained metrics snapshot is
            # this replica's verdict (QPS, p50/p95/p99, sheds) — written
            # only when the report plane is on and traffic was served,
            # so a replica that dies before its first response leaves
            # the directory clean for run_compare
            self._maybe_write_run_report()

    def _maybe_write_run_report(self) -> None:
        from ..telemetry.run_report import report_dir
        if self._report_written or report_dir() is None:
            return
        m = self.metrics_json()
        if not m.get("responses_total"):
            return
        try:
            self.write_run_report(metrics_json=m)
        except Exception as e:
            _LOG.warning("serving run report failed: %s", e)

    def write_run_report(self, directory: Optional[str] = None,
                         extra: Optional[dict] = None,
                         metrics_json: Optional[dict] = None) -> str:
        """Write this server's serving-mode run report (see
        ``telemetry.run_report.write_serving_report``)."""
        from ..telemetry.run_report import write_serving_report
        path = write_serving_report(metrics_json or self.metrics_json(),
                                    directory=directory, extra=extra)
        self._report_written = True
        return path

    def install_signal_handlers(self) -> None:
        """Trap SIGTERM/SIGINT (main thread only) so ``serve_forever``
        drains and exits resumable instead of dying mid-batch."""
        if threading.current_thread() is not threading.main_thread():
            return
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                self._old_handlers[sig] = signal.signal(sig, self._on_signal)
            except (ValueError, OSError):
                pass

    def _on_signal(self, signum, frame) -> None:
        self._signum = signum
        self._sig_event.set()

    def serve_forever(self) -> None:
        """Run until SIGTERM/SIGINT, then drain in-flight work and exit
        with the resumable code shared with ``fit.FitLoop`` (the
        relauncher treats a preempted server like a preempted trainer)."""
        from ..fit import resumable_exit_code
        self.start()
        self.install_signal_handlers()
        # timed wait, not wait(): a signal raised on a non-main thread
        # only trips the C-level flag — the main thread must re-enter the
        # bytecode loop for the python handler (which sets this event) to
        # run at all
        while not self._sig_event.wait(0.2):
            pass
        _LOG.warning("signal %s: draining serving queues", self._signum)
        self.stop(drain=True)
        for sig, old in self._old_handlers.items():
            try:
                signal.signal(sig, old)
            except (ValueError, OSError):
                pass
        sys.exit(resumable_exit_code())

    # -- client surface ---------------------------------------------------
    def submit(self, x, deadline_ms: Optional[float] = None
               ) -> PredictionFuture:
        """Admit one example (item shape, no batch dim). Returns a
        :class:`PredictionFuture`; raises :class:`QueueFull`,
        :class:`NoBucket` or :class:`ServerClosed` when load is shed."""
        if not self._started:   # benign race: start() re-checks under
            self.start()        # the lock; avoids a hot-path acquisition
        self.metrics.record_request()
        if deadline_ms is None:
            deadline_ms = self._default_deadline_ms
        deadline = (None if deadline_ms is None
                    else time.perf_counter() + float(deadline_ms) / 1000.0)
        try:
            # own the bytes (a client reusing one preallocated buffer must
            # not mutate the queued request) AND coerce to the server
            # dtype — the signature set must stay closed on the dtype
            # axis, not just the shape axis
            if hasattr(x, "asnumpy"):
                payload = x.asnumpy().astype(self.dtype, copy=False)
            else:
                payload = np.array(x, dtype=self.dtype, copy=True)
        except (TypeError, ValueError) as e:
            self.metrics.record_rejection("no_bucket")
            raise NoBucket(f"payload is not castable to the server dtype "
                           f"{self.dtype}: {e}")
        try:
            key = self._table.key_for(payload.shape, payload.dtype)
        except NoBucket:
            self.metrics.record_rejection("no_bucket")
            raise
        with self._cond:
            if self._closed:
                self.metrics.record_rejection("closed")
                raise ServerClosed(
                    f"server {self.name!r} is draining; not admitting")
            if self._queued >= self.queue_depth:
                self.metrics.record_rejection("queue_full")
                raise QueueFull(
                    f"admission queue full ({self._queued}/"
                    f"{self.queue_depth} requests queued) — retry with "
                    "backoff or add capacity")
            req = Request(payload, key, deadline)
            self._queued += 1
            self.metrics.queue_depth.set(self._queued)
            self._admit.append(req)
            self._cond.notify()
        return req.future

    def predict(self, x, deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None):
        """Blocking convenience over :meth:`submit`."""
        return self.submit(x, deadline_ms=deadline_ms).result(timeout)

    def warmup(self, item_shapes: Optional[Sequence[Tuple[int, ...]]] = None,
               batch_sizes: Optional[Sequence[int]] = None,
               dtype: Optional[str] = None) -> int:
        """Precompile (item shape x batch bucket) signatures so first
        traffic replays instead of compiling. Returns compiles performed."""
        if dtype is None:
            dtype = str(self.dtype)  # warm what admission coerces to
        if item_shapes is None:
            if self._table.bucket_shapes is None:
                raise MXNetError("warmup needs item_shapes when no "
                                 "bucket_shapes were configured")
            item_shapes = sorted(self._table.bucket_shapes)
        if batch_sizes is None:
            batch_sizes = self._table.batch_sizes
        return self.cache.warmup(item_shapes, batch_sizes, dtype)

    @property
    def max_batch_size(self) -> int:
        """The resolved batching policy (public: bench/ops tooling reads
        these rather than reaching into the bucket table)."""
        return self._table.max_batch_size

    @property
    def max_queue_latency_ms(self) -> float:
        return self._table.max_latency_s * 1000.0

    # -- metrics export ---------------------------------------------------
    def reset_metrics(self) -> ServerMetrics:
        """Swap in a fresh metrics plane (warm executables untouched) —
        lets an offered-load sweep isolate per-load-point statistics."""
        self.metrics = ServerMetrics(self.name)
        self.metrics.cache_info_fn = lambda: self._active.cache.cache_info()
        self.metrics.memory_fn = lambda: self._active.cache.memory_bytes()
        return self.metrics

    def metrics_text(self) -> str:
        """Prometheus text exposition of the full metrics plane."""
        return self.metrics.render_prometheus()

    def metrics_json(self) -> dict:
        return self.metrics.render_json()

    @classmethod
    def load(cls, prefix: str, epoch: int = 0, input_names=("data",),
             ctx=None, **kwargs) -> "ModelServer":
        """Serve an exported checkpoint (``HybridBlock.export`` layout:
        ``prefix-symbol.json`` + ``prefix-NNNN.params``), loaded through
        ``gluon.SymbolBlock.imports``."""
        from ..gluon.block import SymbolBlock
        net = SymbolBlock.imports(f"{prefix}-symbol.json", list(input_names),
                                  f"{prefix}-{epoch:04d}.params", ctx=ctx)
        return cls(net, **kwargs)

    # -- internals --------------------------------------------------------
    def _reject(self, req: Request, reason: str, err: Exception) -> None:
        with self._cond:
            self._queued -= 1
            self.metrics.queue_depth.set(self._queued)
        self.metrics.record_rejection(reason)
        req.future.set_exception(err)

    def _put_batch(self, batch: Batch) -> None:
        while True:
            try:
                self._dispatch_q.put(batch, timeout=0.1)
                return
            except queue.Full:
                if self._abort:
                    for r in batch.requests:
                        self._reject(r, "closed",
                                     ServerClosed("server aborted"))
                    return

    def _batcher_loop(self) -> None:
        table = self._table
        while True:
            batches: "list[Batch]" = []
            with self._cond:
                while not self._admit:
                    if self._closed:
                        break
                    nxt = table.next_deadline()
                    now = time.perf_counter()
                    if nxt is not None and now >= nxt:
                        break
                    # untimed when nothing is aging: submit/stop notify
                    # the condvar, so an idle server takes zero wakeups
                    self._cond.wait(None if nxt is None
                                    else min(0.05, nxt - now))
                drained = list(self._admit)
                self._admit.clear()
                closed = self._closed
                abort = self._abort
            for req in drained:
                if abort:
                    self._reject(req, "closed", ServerClosed("server aborted"))
                    continue
                full = table.add(req)
                if full is not None:
                    batches.append(full)
            batches.extend(table.due())
            if closed:
                # drain: everything still bucketed goes out now (or is
                # rejected on abort), then the workers get their sentinels
                final = table.flush_all()
                if abort:
                    for b in final:
                        for r in b.requests:
                            self._reject(r, "closed",
                                         ServerClosed("server aborted"))
                else:
                    batches.extend(final)
            for b in batches:
                self._put_batch(b)
            if closed:
                with self._cond:
                    empty = not self._admit
                if empty and table.pending_count == 0:
                    for _ in range(self._workers):
                        self._dispatch_q.put(_STOP)
                    return

    def _worker_loop(self) -> None:
        from .. import profiler
        from ..contrib import chaos as _chaos
        from ..ndarray import ndarray as _nd
        while True:
            batch = self._dispatch_q.get()
            if batch is _STOP:
                return
            now = time.perf_counter()
            live: "list[Request]" = []
            for r in batch.requests:
                if self._abort:
                    self._reject(r, "closed", ServerClosed("server aborted"))
                elif r.expired(now):
                    # the whole point of deadlines: expired work is dropped
                    # BEFORE padding/dispatch — zero accelerator time spent
                    self._reject(r, "deadline", DeadlineExceeded(
                        f"deadline expired {1000 * (now - r.deadline):.1f}ms "
                        "ago while queued; request was never dispatched"))
                else:
                    live.append(r)
            if not live:
                continue
            t_dispatch = time.perf_counter()
            # capture ONE metrics plane per batch: reset_metrics() may
            # swap self.metrics mid-batch, and a split inc/dec pair would
            # wedge the fresh inflight gauge at -1
            metrics = self.metrics
            with self._cond:
                self._queued -= len(live)
                metrics.queue_depth.set(self._queued)
                # capture the active model AND allocate the dispatch
                # sequence number under the same lock a hot-swap flips
                # under: the (seq, version) stream is linearizable, so a
                # deploy's version tags are provably monotone in seq
                # order even with concurrent workers. enter() must happen
                # under the SAME lock: a deployer that flips and then
                # drains the old model must see this batch as in-flight,
                # not catch the gap between capture and enter
                active = self._active
                seq = self._dispatch_seq
                self._dispatch_seq += 1
                active.enter()
            metrics.inflight_batches.inc()
            try:
                padded_to = self._table.pad_to(len(live))
                for r in live:
                    r.future.version = active.version
                    r.future.dispatch_seq = seq
                if self._replay is not None:
                    shape, dtype = batch.key
                    self._replay.record(shape, dtype, padded_to)
                plan = _chaos.active()
                if plan is not None:
                    delay = plan.serve_delay_s()
                    if delay:
                        time.sleep(delay)
                x = pad_rows([r.payload for r in live], padded_to)
                out = active.cache(_nd.array(x))
                outs = tuple(out) if isinstance(out, (list, tuple)) \
                    else (out,)
                # asnumpy blocks until the device result is real — compute
                # time includes the sync, same as a client would see
                host = [o.asnumpy() for o in outs]
                t_done = time.perf_counter()
                for i, r in enumerate(live):
                    rows = [h[i] for h in host]
                    r.future.set_result(rows[0] if len(rows) == 1
                                        else tuple(rows))
                    metrics.record_response(r.t_submit, r.t_formed,
                                            t_dispatch, t_done)
                metrics.record_batch(len(live), padded_to, t_dispatch,
                                     t_done)
                profiler.record_span(
                    f"serve_batch[{self.name}]", "serving", t_dispatch,
                    t_done, args={"bucket": str(batch.key),
                                  "rows": len(live),
                                  "padded_to": padded_to,
                                  "version": active.version or ""})
            except Exception as e:  # model error: fail the batch, not the
                _LOG.exception("serving batch failed")        # server
                for r in live:
                    if not r.future.done():
                        metrics.record_rejection("error")
                        r.future.set_exception(e)
            finally:
                metrics.inflight_batches.dec()
                active.exit()
