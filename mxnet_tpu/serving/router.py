"""Process-level serving fleet: socket endpoint + least-loaded router.

PR 7's :class:`~mxnet_tpu.serving.fleet.Fleet` is N in-process replicas
behind one ``submit()`` — one Python process, one GIL, one failure
domain. This module is the cross-process half: replica workers are
separate processes each running a :class:`FleetServer` loaded from the
shared :class:`ModelRegistry` (zero-compile cold start via the published
AOT bundle + compile cache), and a :class:`FleetRouter` dispatches over
them.

Wire protocol (length-prefixed frames over TCP loopback)::

    frame   := header_len:u32be payload_len:u32be header payload
    header  := JSON object, always carrying {"op": ..., "id": ...}
    payload := raw little-endian ndarray bytes (shape/dtype in header)

Ops (client -> replica): ``predict`` (ndarray payload), ``metrics``,
``deploy`` (version), ``stop`` (drain), ``ping``. Replies
(replica -> client): ``result`` (ndarray payload, tagged with the
serving model ``version``), ``error`` (typed: etype + message),
``metrics`` / ``deployed`` / ``stopping`` / ``pong``.

Router contracts:

- **Least-loaded dispatch**: each pick minimizes router-side in-flight
  plus the replica's last-heartbeat queue depth (the PR 3/5 metrics
  plane exported over the ``metrics`` op), round-robin tie-break.
- **Typed shed**: when every live replica rejects with ``QueueFull``
  (or none is live), the router raises ``QueueFull`` to the client —
  never silent drops.
- **Zero dropped in-flight on replica death**: every un-acked request
  id of a dead replica is retried on a survivor. Replicas keep a
  bounded response cache by request id, so a retry that raced a
  delivered response is answered idempotently, not recomputed.
- **Version monotonicity**: response version tags are parsed and the
  router maintains a high-water *version floor*; picks prefer replicas
  whose heartbeat version has reached the floor, so a client that saw
  vN+1 during a rolling deploy is not routed back to a vN replica.
"""
from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
import uuid
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..base import MXNetError, check, env
from ..log import get_logger
from .batcher import (DeadlineExceeded, NoBucket, QueueFull, ServerClosed,
                      ServingError)

__all__ = ["FleetRouter", "ReplicaEndpoint", "ReplicaClient", "RouterFuture",
           "ReplicaDead", "send_frame", "recv_frame", "fleet_heartbeat_ms",
           "replica_main"]

# the parent logger: server.py owns "mxnet_tpu.serving" with a handler,
# and a handler-bearing child would double-emit through propagation
_LOG = get_logger("mxnet_tpu.serving")

# a frame larger than this is a protocol error, not a big request
_MAX_FRAME = 256 << 20


class ReplicaDead(ServingError):
    """The replica's socket is gone (process death or close)."""


def fleet_heartbeat_ms() -> float:
    """Router heartbeat poll interval (``MXTPU_FLEET_HEARTBEAT_MS``)."""
    try:
        v = float(env.get("MXTPU_FLEET_HEARTBEAT_MS"))
    except (TypeError, ValueError):
        raise MXNetError("MXTPU_FLEET_HEARTBEAT_MS: expected a number, got "
                         f"{env.raw('MXTPU_FLEET_HEARTBEAT_MS')!r}")
    check(v > 0, f"MXTPU_FLEET_HEARTBEAT_MS must be > 0, got {v}")
    return v


# -- wire protocol ----------------------------------------------------------

def send_frame(sock: socket.socket, header: dict, payload: bytes = b""
               ) -> None:
    hb = json.dumps(header, separators=(",", ":")).encode("utf-8")
    sock.sendall(struct.pack(">II", len(hb), len(payload)) + hb + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed"
                                  + (" mid-frame" if buf else ""))
        buf += chunk
    return bytes(buf)


def recv_frame(sock: socket.socket) -> Tuple[dict, bytes]:
    hlen, plen = struct.unpack(">II", _recv_exact(sock, 8))
    if hlen > _MAX_FRAME or plen > _MAX_FRAME:
        raise MXNetError(f"router frame too large ({hlen}+{plen} bytes): "
                         "corrupt stream or protocol mismatch")
    header = json.loads(_recv_exact(sock, hlen).decode("utf-8"))
    payload = _recv_exact(sock, plen) if plen else b""
    return header, payload


def _array_header(op: str, rid: str, arr: np.ndarray, **extra) -> dict:
    h = {"op": op, "id": rid, "shape": list(arr.shape),
         "dtype": str(arr.dtype)}
    h.update(extra)
    return h


def _array_of(header: dict, payload: bytes) -> np.ndarray:
    return np.frombuffer(payload, dtype=header["dtype"]).reshape(
        header["shape"])


_TYPED_ERRORS = {"QueueFull": QueueFull, "DeadlineExceeded": DeadlineExceeded,
                 "NoBucket": NoBucket, "ServerClosed": ServerClosed,
                 "ReplicaDead": ReplicaDead, "ServingError": ServingError}


def _typed_error(etype: str, message: str) -> Exception:
    return _TYPED_ERRORS.get(etype, MXNetError)(message)


def _version_num(tag) -> Optional[int]:
    """'v12' -> 12; None/unparsable -> None (excluded from floor logic)."""
    if not isinstance(tag, str):
        return None
    digits = "".join(c for c in tag if c.isdigit())
    return int(digits) if digits else None


# -- replica side -----------------------------------------------------------

class ReplicaEndpoint:
    """Socket front-end of one replica's :class:`ModelServer`.

    Accepts router connections, decodes ``predict`` frames into
    ``server.submit()`` calls, and streams results back as they resolve.
    Keeps a bounded response cache by request id so retried requests
    (the router re-sends a dead replica's un-acked ids to survivors, and
    a survivor may legitimately see a duplicate after reconnect) are
    answered from cache — **idempotent by request id**.
    """

    def __init__(self, server, host: str = "127.0.0.1", port: int = 0,
                 done_cache: int = 1024):
        self.server = server
        self._done_cache = int(done_cache)
        self._done: "OrderedDict[str, Tuple[dict, bytes]]" = OrderedDict()
        self._done_lock = threading.Lock()
        self._lock = threading.Lock()
        self._conns: List[socket.socket] = []
        self._threads: List[threading.Thread] = []
        self._closed = False
        self._stop_requested = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.addr: Tuple[str, int] = self._sock.getsockname()

    def start(self) -> "ReplicaEndpoint":
        self.server.start()
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"mxtpu-endpoint[{self.server.name}]")
        t.start()
        self._threads.append(t)
        return self

    # -- accept / per-connection loops ---------------------------------
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                if self._closed:
                    conn.close()
                    return
                self._conns.append(conn)
            t = threading.Thread(target=self._conn_loop, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _conn_loop(self, conn: socket.socket) -> None:
        # one writer lock per connection: worker threads resolving
        # futures and the reader answering metrics share the socket
        wlock = threading.Lock()
        try:
            while not self._closed:
                header, payload = recv_frame(conn)
                self._handle(conn, wlock, header, payload)
        except (ConnectionError, OSError, ValueError):
            pass  # router went away (or we are closing); server state is
        #         untouched — in-flight work still resolves and is cached
        finally:
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _reply(self, conn, wlock, header: dict, payload: bytes = b""
               ) -> None:
        try:
            with wlock:
                send_frame(conn, header, payload)
        except (ConnectionError, OSError):
            pass  # reply undeliverable; response cache still answers a retry

    def _handle(self, conn, wlock, header: dict, payload: bytes) -> None:
        op = header.get("op")
        rid = header.get("id") or uuid.uuid4().hex
        if op == "predict":
            self._handle_predict(conn, wlock, rid, header, payload)
        elif op == "metrics":
            m = self.server.metrics_json()
            self._reply(conn, wlock, {
                "op": "metrics", "id": rid,
                "version": self.server.active_version,
                "queue_depth": m.get("queue_depth", 0),
                "p95_ms": m.get("latency_ms", {}).get("total", {}).get(
                    "p95", 0.0),
                "metrics": m})
        elif op == "deploy":
            threading.Thread(
                target=self._handle_deploy,
                args=(conn, wlock, rid, header.get("version")),
                daemon=True).start()
        elif op == "stop":
            self._reply(conn, wlock, {"op": "stopping", "id": rid})
            self._stop_requested.set()
        elif op == "ping":
            self._reply(conn, wlock, {"op": "pong", "id": rid})
        else:
            self._reply(conn, wlock, {"op": "error", "id": rid,
                                      "etype": "MXNetError",
                                      "message": f"unknown op {op!r}"})

    def _handle_predict(self, conn, wlock, rid, header, payload) -> None:
        with self._done_lock:
            cached = self._done.get(rid)
        if cached is not None:  # duplicate id: answer, don't recompute
            self._reply(conn, wlock, cached[0], cached[1])
            return
        try:
            x = _array_of(header, payload)
            fut = self.server.submit(x, deadline_ms=header.get("deadline_ms"))
        except Exception as e:
            self._reply(conn, wlock, {"op": "error", "id": rid,
                                      "etype": type(e).__name__,
                                      "message": str(e)})
            return
        # resolve off-thread: the reader must keep draining frames (a
        # metrics heartbeat racing a slow batch must not block on it)
        threading.Thread(target=self._resolve, daemon=True,
                         args=(conn, wlock, rid, fut)).start()

    def _resolve(self, conn, wlock, rid, fut) -> None:
        try:
            out = fut.result(timeout=300)
        except Exception as e:
            self._reply(conn, wlock, {"op": "error", "id": rid,
                                      "etype": type(e).__name__,
                                      "message": str(e)})
            return
        arr = np.ascontiguousarray(
            out[0] if isinstance(out, (tuple, list)) else out)
        h = _array_header("result", rid, arr,
                          version=getattr(fut, "version", None))
        p = arr.tobytes()
        with self._done_lock:
            self._done[rid] = (h, p)
            while len(self._done) > self._done_cache:
                self._done.popitem(last=False)
        self._reply(conn, wlock, h, p)

    def _handle_deploy(self, conn, wlock, rid, version) -> None:
        try:
            if not hasattr(self.server, "deploy"):
                raise MXNetError("replica server is not registry-attached "
                                 "(no deploy); serve a FleetServer")
            report = self.server.deploy(version)
            self._reply(conn, wlock, {"op": "deployed", "id": rid,
                                      "report": dict(report)})
        except Exception as e:
            self._reply(conn, wlock, {"op": "error", "id": rid,
                                      "etype": type(e).__name__,
                                      "message": str(e)})

    # -- lifecycle -----------------------------------------------------
    def close(self, abort: bool = False) -> None:
        """Shut the endpoint down. ``abort=True`` slams every socket shut
        with no drain — the test double for a replica process dying."""
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns, self._conns = list(self._conns), []
        if abort:
            for c in conns:
                try:
                    c.close()
                except OSError:
                    pass
            self.server.stop(drain=False)
            return
        # graceful: drain the server first so every accepted request's
        # future resolves (and flushes through _resolve) before sockets go
        self.server.stop(drain=True)
        time.sleep(0.05)  # let resolver threads flush their last frames
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def serve_forever(self) -> None:
        """Run until SIGTERM/SIGINT or a ``stop`` op, drain, and exit
        with the resumable exit code (the PR 15/17 supervisor contract)."""
        import signal
        import sys
        from ..fit import resumable_exit_code
        signal.signal(signal.SIGTERM,
                      lambda *_: self._stop_requested.set())
        signal.signal(signal.SIGINT,
                      lambda *_: self._stop_requested.set())
        while not self._stop_requested.wait(0.2):
            pass
        self.close(abort=False)
        sys.exit(resumable_exit_code())


# -- router side ------------------------------------------------------------

class RouterFuture:
    """Client-side handle for one routed request. Carries everything the
    router needs to re-send it (header + payload + tried-replica set)."""

    def __init__(self, rid: str, header: dict, payload: bytes):
        self.id = rid
        self.version: Optional[str] = None
        self.replica: Optional[str] = None
        self.retries = 0
        self._header = header
        self._payload = payload
        self._tried: set = set()
        self._ev = threading.Event()
        self._result = None
        self._exc: Optional[Exception] = None

    def done(self) -> bool:
        return self._ev.is_set()

    def set_result(self, value) -> None:
        self._result = value
        self._ev.set()

    def set_exception(self, exc: Exception) -> None:
        self._exc = exc
        self._ev.set()

    def result(self, timeout: Optional[float] = None):
        if not self._ev.wait(timeout):
            raise TimeoutError(f"request {self.id} pending after "
                               f"{timeout}s")
        if self._exc is not None:
            raise self._exc
        return self._result


class _SyncCall:
    """Pending synchronous request (metrics/deploy/stop/ping)."""

    def __init__(self):
        self._ev = threading.Event()
        self.header: Optional[dict] = None
        self.payload: bytes = b""
        self.exc: Optional[Exception] = None

    def resolve(self, header, payload) -> None:
        self.header, self.payload = header, payload
        self._ev.set()

    def fail(self, exc) -> None:
        self.exc = exc
        self._ev.set()

    def wait(self, timeout):
        if not self._ev.wait(timeout):
            raise TimeoutError("replica call timed out")
        if self.exc is not None:
            raise self.exc
        return self.header, self.payload


class ReplicaClient:
    """Router-side handle: one multiplexed connection to one replica."""

    def __init__(self, name: str, addr: Tuple[str, int],
                 on_frame: Callable, on_death: Callable,
                 connect_timeout: float = 10.0, pid: Optional[int] = None):
        self.name = name
        self.addr = tuple(addr)
        self.pid = pid
        self.dead = threading.Event()
        self._on_frame = on_frame
        self._on_death = on_death
        self._wlock = threading.Lock()
        self._plock = threading.Lock()
        self._death_lock = threading.Lock()
        self._pending: Dict[str, object] = {}
        deadline = time.monotonic() + connect_timeout
        while True:  # the replica process may still be binding its port
            try:
                self._sock = socket.create_connection(self.addr, timeout=2.0)
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.05)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"mxtpu-router-reader[{name}]")
        self._reader.start()

    # -- pending registry ----------------------------------------------
    def register(self, rid: str, entry) -> None:
        with self._plock:
            self._pending[rid] = entry

    def pop_pending(self, rid: str):
        with self._plock:
            return self._pending.pop(rid, None)

    def pending_count(self) -> int:
        with self._plock:
            return len(self._pending)

    def send(self, header: dict, payload: bytes = b"") -> None:
        if self.dead.is_set():
            raise ReplicaDead(f"replica {self.name} is dead")
        try:
            with self._wlock:
                send_frame(self._sock, header, payload)
        except (ConnectionError, OSError) as e:
            self._mark_dead()
            raise ReplicaDead(f"replica {self.name}: {e}")

    def request(self, header: dict, payload: bytes = b"",
                timeout: float = 30.0) -> Tuple[dict, bytes]:
        """Send one op and wait for its reply (metrics/deploy/stop)."""
        rid = header.setdefault("id", uuid.uuid4().hex)
        call = _SyncCall()
        self.register(rid, call)
        try:
            self.send(header, payload)
        except ReplicaDead:
            self.pop_pending(rid)
            raise
        return call.wait(timeout)

    def _read_loop(self) -> None:
        try:
            while True:
                header, payload = recv_frame(self._sock)
                self._on_frame(self, header, payload)
        except (ConnectionError, OSError, ValueError):
            self._mark_dead()

    def _mark_dead(self) -> None:
        with self._death_lock:  # exactly one thread runs the death path
            if self.dead.is_set():
                return
            self.dead.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._plock:
            orphans = list(self._pending.items())
            self._pending.clear()
        self._on_death(self, orphans)

    def close(self) -> None:
        self.dead.set()  # suppress the death path: this is deliberate
        try:
            self._sock.close()
        except OSError:
            pass


def _router_metrics():
    from ..telemetry import default_registry
    reg = default_registry()
    return (reg.gauge("mxtpu_fleet_replicas",
                      "Live replica processes currently routable."),
            reg.counter("mxtpu_fleet_routed_total",
                        "Requests dispatched to a replica.",
                        label="replica"),
            reg.counter("mxtpu_fleet_retried_total",
                        "Requests re-dispatched after a replica died or "
                        "shed (each retry counts once)."),
            reg.counter("mxtpu_fleet_shed_total",
                        "Requests shed with QueueFull after every live "
                        "replica was saturated or dead."))


class FleetRouter:
    """Least-loaded request router over process replicas.

    ``add_replica(name, addr)`` connects; ``submit(x)`` returns a
    :class:`RouterFuture`. A heartbeat thread polls every replica's
    ``metrics`` op (queue depth / p95 / active version) at
    ``MXTPU_FLEET_HEARTBEAT_MS``; picks minimize router-side in-flight +
    heartbeat queue depth. Replica death retries its un-acked ids on
    survivors (zero dropped in-flight); saturation shed raises
    ``QueueFull``. ``rolling_deploy`` drains one replica at a time onto
    the target version while the version floor keeps client-visible tags
    monotone.
    """

    def __init__(self, heartbeat_ms: Optional[float] = None):
        self._heartbeat_s = (fleet_heartbeat_ms() if heartbeat_ms is None
                             else float(heartbeat_ms)) / 1000.0
        self._lock = threading.RLock()
        self._replicas: Dict[str, ReplicaClient] = {}
        self._state: Dict[str, dict] = {}
        self._inflight: Dict[str, int] = {}
        self._rr = 0
        self._routed = 0
        self._version_floor: Tuple[int, Optional[str]] = (-1, None)
        self._kill_hook: Optional[Callable[[str], None]] = None
        self._closed = False
        (self._g_replicas, self._c_routed, self._c_retried,
         self._c_shed) = _router_metrics()
        self._hb_thread: Optional[threading.Thread] = None

    # -- membership ----------------------------------------------------
    def add_replica(self, name: str, addr: Tuple[str, int],
                    pid: Optional[int] = None,
                    connect_timeout: float = 10.0) -> None:
        check(name not in self._replicas or
              self._replicas[name].dead.is_set(),
              f"replica {name!r} already routed")
        client = ReplicaClient(name, addr, self._on_frame,
                               self._on_replica_death,
                               connect_timeout=connect_timeout, pid=pid)
        with self._lock:
            self._replicas[name] = client
            self._inflight.setdefault(name, 0)
        try:  # prime the load/version state so the first pick is informed
            self._poll_one(name, client, timeout=5.0)
        except Exception:
            pass
        self._g_replicas.set(self.live_count())
        if self._hb_thread is None:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, daemon=True,
                name="mxtpu-router-heartbeat")
            self._hb_thread.start()

    def remove_replica(self, name: str, drain: bool = True,
                       timeout: float = 30.0) -> None:
        """Stop routing to ``name``; with ``drain`` wait for its pending
        requests and send a drain-stop (never drops in-flight)."""
        with self._lock:
            client = self._replicas.pop(name, None)
            self._state.pop(name, None)
        if client is None:
            return
        if drain and not client.dead.is_set():
            deadline = time.monotonic() + timeout
            while client.pending_count() and time.monotonic() < deadline:
                time.sleep(0.02)
            try:
                client.request({"op": "stop"}, timeout=5.0)
            except Exception:
                pass
        client.close()
        with self._lock:
            self._inflight.pop(name, None)
        self._g_replicas.set(self.live_count())

    def live_count(self) -> int:
        with self._lock:
            return sum(1 for c in self._replicas.values()
                       if not c.dead.is_set())

    def states(self) -> Dict[str, dict]:
        """Heartbeat snapshot per replica (the autoscaler's observation):
        name -> {queue_depth, p95_ms, version, healthy}."""
        with self._lock:
            out = {}
            for name, client in self._replicas.items():
                s = dict(self._state.get(name, {}))
                s.setdefault("queue_depth", 0)
                s.setdefault("p95_ms", 0.0)
                s.setdefault("version", None)
                s["inflight"] = self._inflight.get(name, 0)
                s["healthy"] = not client.dead.is_set()
                out[name] = s
            return out

    def set_kill_hook(self, fn: Optional[Callable[[str], None]]) -> None:
        """Install the chaos executor: called with a replica name when a
        ``replica_kill@N`` plan fires (tests/launchers kill the process
        or abort the endpoint)."""
        self._kill_hook = fn

    # -- dispatch ------------------------------------------------------
    def submit(self, x, deadline_ms: Optional[float] = None) -> RouterFuture:
        arr = np.ascontiguousarray(np.asarray(x))
        rid = uuid.uuid4().hex
        header = _array_header("predict", rid, arr, deadline_ms=deadline_ms)
        fut = RouterFuture(rid, header, arr.tobytes())
        self._dispatch(fut)
        self._maybe_chaos_kill()
        return fut

    def predict(self, x, deadline_ms: Optional[float] = None,
                timeout: float = 30.0):
        return self.submit(x, deadline_ms=deadline_ms).result(timeout)

    def _dispatch(self, fut: RouterFuture) -> None:
        while True:
            client = self._pick(fut._tried)
            if client is None:
                self._c_shed.inc()
                fut.set_exception(QueueFull(
                    f"request {fut.id}: every replica saturated or dead "
                    f"(tried {sorted(fut._tried) or 'none'})"))
                return
            fut._tried.add(client.name)
            client.register(fut.id, fut)
            with self._lock:
                self._inflight[client.name] = \
                    self._inflight.get(client.name, 0) + 1
            try:
                client.send(fut._header, fut._payload)
            except ReplicaDead:
                # death path already re-owned the pending set; if we got
                # the orphan back it is in fut._tried and loops to the
                # next candidate
                if client.pop_pending(fut.id) is not None:
                    with self._lock:
                        self._inflight[client.name] = max(
                            0, self._inflight.get(client.name, 1) - 1)
                    continue
                return  # _on_replica_death re-dispatched it already
            with self._lock:
                self._routed += 1
            fut.replica = client.name
            self._c_routed.inc(label_value=client.name)
            return

    def _pick(self, exclude) -> Optional[ReplicaClient]:
        with self._lock:
            cands = [(n, c) for n, c in self._replicas.items()
                     if n not in exclude and not c.dead.is_set()]
            floor = self._version_floor[0]
            if floor >= 0:
                # monotonicity: never route a client that has seen vN to
                # a replica still announcing vN-1 (unknown versions pass:
                # a fresh replica spawned from CURRENT is at least vN)
                ok = [(n, c) for n, c in cands
                      if (lambda v: v is None or v >= floor)(
                          _version_num(self._state.get(n, {})
                                       .get("version")))]
                if ok:
                    cands = ok
            if not cands:
                return None
            best, best_score = None, None
            n_c = len(cands)
            start = self._rr
            for i in range(n_c):
                name, client = cands[(start + i) % n_c]
                score = (self._inflight.get(name, 0)
                         + int(self._state.get(name, {})
                               .get("queue_depth", 0)))
                if best_score is None or score < best_score:
                    best, best_score = client, score
            self._rr = (self._rr + 1) % max(1, n_c)
            return best

    # -- response / death paths ----------------------------------------
    def _on_frame(self, client: ReplicaClient, header: dict,
                  payload: bytes) -> None:
        rid = header.get("id")
        entry = client.pop_pending(rid)
        if entry is None:
            return  # late duplicate (request was retried elsewhere)
        if isinstance(entry, _SyncCall):
            entry.resolve(header, payload)
            return
        fut: RouterFuture = entry
        with self._lock:
            self._inflight[client.name] = max(
                0, self._inflight.get(client.name, 1) - 1)
        op = header.get("op")
        if op == "result":
            version = header.get("version")
            num = _version_num(version)
            with self._lock:
                if num is not None and num > self._version_floor[0]:
                    self._version_floor = (num, version)
            fut.version = version
            fut.replica = client.name
            fut.set_result(_array_of(header, payload))
        elif op == "error" and header.get("etype") == "QueueFull":
            # saturated replica: fail over to the others before shedding
            self._c_retried.inc()
            fut.retries += 1
            self._dispatch(fut)
        else:
            fut.set_exception(_typed_error(header.get("etype", ""),
                                           header.get("message", "")))

    def _on_replica_death(self, client: ReplicaClient, orphans) -> None:
        with self._lock:
            self._state.pop(client.name, None)
            self._inflight[client.name] = 0
        self._g_replicas.set(self.live_count())
        retried = 0
        for rid, entry in orphans:
            if isinstance(entry, _SyncCall):
                entry.fail(ReplicaDead(f"replica {client.name} died"))
                continue
            # zero-dropped-in-flight: every un-acked predict goes to a
            # survivor; the dead name stays in _tried so we never
            # re-route to the corpse
            self._c_retried.inc()
            entry.retries += 1
            retried += 1
            self._dispatch(entry)
        if retried:
            _LOG.warning("router: replica %s died; retried %d in-flight "
                         "request(s) on survivors", client.name, retried)

    # -- heartbeats ----------------------------------------------------
    def _poll_one(self, name: str, client: ReplicaClient,
                  timeout: float = 2.0) -> None:
        header, _ = client.request({"op": "metrics"}, timeout=timeout)
        if header.get("op") != "metrics":
            return
        with self._lock:
            self._state[name] = {
                "queue_depth": int(header.get("queue_depth", 0)),
                "p95_ms": float(header.get("p95_ms") or 0.0),
                "version": header.get("version"),
                "t": time.monotonic(),
            }

    def _heartbeat_loop(self) -> None:
        while not self._closed:
            with self._lock:
                snapshot = list(self._replicas.items())
            for name, client in snapshot:
                if self._closed or client.dead.is_set():
                    continue
                try:
                    self._poll_one(name, client)
                except Exception:
                    pass  # socket death is surfaced by the reader thread
            self._g_replicas.set(self.live_count())
            time.sleep(self._heartbeat_s)

    # -- chaos ---------------------------------------------------------
    def _maybe_chaos_kill(self) -> None:
        if self._kill_hook is None:
            return
        from ..contrib import chaos
        plan = chaos.active()
        if plan is None:
            return
        with self._lock:
            routed = self._routed
        victim_idx = plan.replica_kill_due(routed)
        if victim_idx is None:
            return
        with self._lock:
            live = sorted(n for n, c in self._replicas.items()
                          if not c.dead.is_set())
            if not live:
                return
            if 0 <= victim_idx < len(live):
                victim = live[victim_idx]
            else:  # -1 / out of range: the busiest replica
                victim = max(live, key=lambda n: self._inflight.get(n, 0))
        _LOG.warning("chaos: replica_kill firing at routed=%d -> %s",
                     routed, victim)
        self._kill_hook(victim)

    # -- deploy / shutdown ---------------------------------------------
    def rolling_deploy(self, version: str, timeout: float = 300.0
                       ) -> List[dict]:
        """Deploy ``version`` replica by replica (each drains its old
        model internally — the FleetServer hot-swap), never taking two
        replicas out of full service at once."""
        reports = []
        with self._lock:
            names = sorted(self._replicas)
        for name in names:
            with self._lock:
                client = self._replicas.get(name)
            if client is None or client.dead.is_set():
                continue
            header, _ = client.request({"op": "deploy", "version": version},
                                       timeout=timeout)
            if header.get("op") == "error":
                raise MXNetError(f"rolling deploy to {version!r} failed at "
                                 f"{name}: {header.get('message')}")
            reports.append(header.get("report", {}))
            try:  # refresh so the floor/pick sees the new tag promptly
                self._poll_one(name, client)
            except Exception:
                pass
        return reports

    def stop_fleet(self, drain: bool = True) -> None:
        """Send every replica a stop op (drain by default)."""
        with self._lock:
            names = sorted(self._replicas)
        for name in names:
            self.remove_replica(name, drain=drain)

    def metrics_json(self) -> dict:
        states = self.states()
        return {
            "replicas": states,
            "live": sum(1 for s in states.values() if s["healthy"]),
            "routed_total": self._routed,
            "version_floor": self._version_floor[1],
        }

    def close(self) -> None:
        self._closed = True
        with self._lock:
            clients = list(self._replicas.values())
            self._replicas.clear()
            self._state.clear()
        for c in clients:
            c.close()
        self._g_replicas.set(0)


# -- replica process entry --------------------------------------------------

def replica_main(registry_root: str, model: str, host: str = "127.0.0.1",
                 port: int = 0, version: str = "current",
                 publish_aot: bool = False, ready_prefix: str =
                 "FLEET_REPLICA_READY", **server_kwargs) -> None:
    """Process entry of one fleet replica (tools/serve_fleet.py --replica
    and tests/dist/fleet_worker.py both land here).

    Builds a :class:`FleetServer` from the shared registry (AOT bundle /
    compile cache warm), binds a :class:`ReplicaEndpoint`, prints one
    ``FLEET_REPLICA_READY {json}`` line carrying the bound port plus the
    cold-start compile evidence (the scale-up 0-compile proof), then
    serves until SIGTERM / a ``stop`` op and exits resumable (75).
    """
    from ..telemetry import default_registry
    from .fleet import FleetServer
    from .registry import ModelRegistry
    reg = default_registry()  # XLA compile listeners BEFORE any compile
    t0 = time.perf_counter()
    server = FleetServer(ModelRegistry(registry_root), model,
                         version=version, **server_kwargs)
    aot_published = 0
    if publish_aot:
        aot_published = server.publish_aot()
    endpoint = ReplicaEndpoint(server, host=host, port=port).start()
    j = reg.render_json()
    print(ready_prefix + " " + json.dumps({
        "port": endpoint.addr[1],
        "pid": os.getpid(),
        "model": model,
        "version": server.active_version,
        "warm_s": round(time.perf_counter() - t0, 3),
        "warm": server.cold_start_stats,
        "aot_published": aot_published,
        "xla_compiles": j.get("mxtpu_xla_compile_total", 0),
        "xla_cache_hits": j.get("mxtpu_xla_cache_hits_total", 0),
    }), flush=True)
    endpoint.serve_forever()
