"""Serving metrics plane: latency histograms, counters, gauges.

The reference pairs MXNet with a model server (MMS) whose ops story is a
metrics sidecar (mms/metrics/*: request counts, latency, queue time,
worker memory — logged and scraped). Here the metrics plane is in-process
and first-class: every request is timed in three components,

- ``queue``    — admission to batch formation (waiting for peers),
- ``batch``    — batch formation to worker pickup (waiting for a worker),
- ``compute``  — model execution including device sync,

plus an end-to-end ``total``. Batch sizes, queue depth, shed load and
compiled-signature cache traffic are tracked alongside. Two export
formats: Prometheus text exposition (:meth:`ServerMetrics.render_prometheus`)
and JSON (:meth:`ServerMetrics.render_json`); batch dispatches are also
emitted as ``profiler.record_span`` events so chrome://tracing shows the
serving timeline next to op execution.

The metric primitives (Counter / Gauge / Histogram with percentile
reservoirs) live in :mod:`mxnet_tpu.telemetry.registry` — they started
here and were promoted to the shared telemetry layer; this module re-exports
them under their historical names and keeps :class:`ServerMetrics`'s
expositions byte-identical.
"""
from __future__ import annotations

import json
import time
from typing import Callable, List, Optional

from ..telemetry.registry import (Counter, Gauge, Histogram,
                                  LatencyHistogram,
                                  DEFAULT_LATENCY_BUCKETS_MS, _fmt)

__all__ = ["LatencyHistogram", "Counter", "Gauge", "ServerMetrics",
           "DEFAULT_LATENCY_BUCKETS_MS"]


class ServerMetrics:
    """The full serving metrics surface for one :class:`ModelServer`.

    ``cache_info_fn`` (set by the server) is polled at export time so cache
    hit/miss/evict counters always reflect the live signature cache.
    """

    #: batch-size histogram bounds: powers of two cover every batch bucket
    BATCH_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)

    def __init__(self, name: str = "model"):
        self.name = name
        self.started = time.time()
        self.requests_total = Counter()
        self.responses_total = Counter()
        self.rejected_total = Counter(label="reason")
        self.batches_total = Counter()
        self.dispatched_rows_total = Counter()
        self.padded_rows_total = Counter()
        self.queue_depth = Gauge()
        self.inflight_batches = Gauge()
        self.queue_ms = LatencyHistogram()
        self.batch_ms = LatencyHistogram()
        self.compute_ms = LatencyHistogram()
        self.total_ms = LatencyHistogram()
        self.batch_size = LatencyHistogram(buckets=self.BATCH_SIZE_BUCKETS)
        self.cache_info_fn: Optional[Callable] = None
        # per-model device bytes (set by the server): polls the memory
        # ledger's serving_cache bytes for the ACTIVE model's signature
        # cache, so a deploy/drain shows up as a rise/fall here
        self.memory_fn: Optional[Callable] = None

    # -- recording helpers (one call site each in the server) ------------
    def record_request(self) -> None:
        self.requests_total.inc()

    def record_rejection(self, reason: str) -> None:
        self.rejected_total.inc(label_value=reason)

    def record_batch(self, rows: int, padded_to: int, t_dispatch: float,
                     t_done: float) -> None:
        self.batches_total.inc()
        self.dispatched_rows_total.inc(rows)
        self.padded_rows_total.inc(padded_to - rows)
        self.batch_size.observe(rows)
        self.compute_ms.observe((t_done - t_dispatch) * 1000.0)

    def record_response(self, t_submit: float, t_formed: float,
                        t_dispatch: float, t_done: float) -> None:
        self.responses_total.inc()
        self.queue_ms.observe((t_formed - t_submit) * 1000.0)
        self.batch_ms.observe((t_dispatch - t_formed) * 1000.0)
        self.total_ms.observe((t_done - t_submit) * 1000.0)

    # -- export -----------------------------------------------------------
    def _cache_counts(self) -> dict:
        if self.cache_info_fn is None:
            return {}
        info = self.cache_info_fn()
        return {"hits": info.hits, "misses": info.misses,
                "evictions": info.evictions, "entries": info.currsize,
                "max_entries": info.maxsize}

    def _model_bytes(self) -> int:
        if self.memory_fn is None:
            return 0
        try:
            return int(self.memory_fn())
        except Exception:
            return 0

    def render_prometheus(self, prefix: str = "mxtpu_serve") -> str:
        up = time.time() - self.started
        lines: List[str] = []
        lines += self.requests_total.prometheus_lines(
            f"{prefix}_requests_total", "Requests admitted or rejected.")
        lines += self.responses_total.prometheus_lines(
            f"{prefix}_responses_total", "Requests answered successfully.")
        lines += self.rejected_total.prometheus_lines(
            f"{prefix}_rejected_total",
            "Requests shed, by reason (queue_full|deadline|no_bucket|closed).")
        lines += self.batches_total.prometheus_lines(
            f"{prefix}_batches_total", "Batches dispatched to the model.")
        lines += self.dispatched_rows_total.prometheus_lines(
            f"{prefix}_dispatched_rows_total",
            "Real (unpadded) rows dispatched.")
        lines += self.padded_rows_total.prometheus_lines(
            f"{prefix}_padded_rows_total",
            "Padding rows added to reach a batch bucket.")
        lines += self.queue_depth.prometheus_lines(
            f"{prefix}_queue_depth", "Admitted requests not yet dispatched.")
        lines += [f"# HELP {prefix}_queue_depth_peak "
                  "High-water mark of the admission queue.",
                  f"# TYPE {prefix}_queue_depth_peak gauge",
                  f"{prefix}_queue_depth_peak {_fmt(self.queue_depth.peak)}"]
        lines += self.inflight_batches.prometheus_lines(
            f"{prefix}_inflight_batches", "Batches currently executing.")
        lines += self.queue_ms.prometheus_lines(
            f"{prefix}_queue_latency_ms",
            "Admission to batch formation, milliseconds.")
        lines += self.batch_ms.prometheus_lines(
            f"{prefix}_batch_latency_ms",
            "Batch formation to worker pickup, milliseconds.")
        lines += self.compute_ms.prometheus_lines(
            f"{prefix}_compute_latency_ms",
            "Model execution including device sync, milliseconds.")
        lines += self.total_ms.prometheus_lines(
            f"{prefix}_total_latency_ms",
            "End-to-end request latency, milliseconds.")
        lines += self.batch_size.prometheus_lines(
            f"{prefix}_batch_size", "Real rows per dispatched batch.")
        cache = self._cache_counts()
        for key in ("hits", "misses", "evictions"):
            if key in cache:
                lines += [f"# HELP {prefix}_cache_{key}_total "
                          f"Compiled-signature cache {key}.",
                          f"# TYPE {prefix}_cache_{key}_total counter",
                          f"{prefix}_cache_{key}_total {cache[key]}"]
        if "entries" in cache:
            lines += [f"# HELP {prefix}_cache_entries "
                      "Resident compiled signatures.",
                      f"# TYPE {prefix}_cache_entries gauge",
                      f"{prefix}_cache_entries {cache['entries']}"]
        if self.memory_fn is not None:
            lines += [f"# HELP {prefix}_model_bytes Device bytes "
                      "attributed to the active model's compiled "
                      "signatures (memory ledger, serving_cache).",
                      f"# TYPE {prefix}_model_bytes gauge",
                      f"{prefix}_model_bytes {self._model_bytes()}"]
        lines += [f"# HELP {prefix}_uptime_seconds Server uptime.",
                  f"# TYPE {prefix}_uptime_seconds gauge",
                  f"{prefix}_uptime_seconds {_fmt(round(up, 3))}"]
        return "\n".join(lines) + "\n"

    def render_json(self) -> dict:
        up = max(time.time() - self.started, 1e-9)
        return {
            "model": self.name,
            "uptime_s": round(up, 3),
            "requests_total": self.requests_total.value,
            "responses_total": self.responses_total.value,
            "rejected": self.rejected_total.by_label(),
            "batches_total": self.batches_total.value,
            "dispatched_rows_total": self.dispatched_rows_total.value,
            "padded_rows_total": self.padded_rows_total.value,
            "queue_depth": self.queue_depth.value,
            "queue_depth_peak": self.queue_depth.peak,
            "throughput_rps": round(self.responses_total.value / up, 3),
            "latency_ms": {
                "queue": self.queue_ms.snapshot(),
                "batch": self.batch_ms.snapshot(),
                "compute": self.compute_ms.snapshot(),
                "total": self.total_ms.snapshot(),
            },
            "batch_size": self.batch_size.snapshot(),
            "cache": self._cache_counts(),
            "model_bytes": self._model_bytes(),
        }

    def render_json_text(self) -> str:
        return json.dumps(self.render_json())
