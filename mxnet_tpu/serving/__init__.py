"""mxnet_tpu.serving — the online inference subsystem.

The reference pairs MXNet with an external model server (MMS/multi-model-
server: dynamic batching, warm workers, a metrics sidecar). Here serving
is a first-class in-process subsystem, built for the TPU cost model where
the two dominant taxes are per-dispatch overhead (amortized by the dynamic
batcher) and per-signature XLA compiles (bounded by shape buckets + the
compiled-signature cache).

Pieces (one module each):

- :mod:`.batcher` — bucketing/padding policy + typed admission errors
  (``QueueFull``, ``DeadlineExceeded``, ``NoBucket``, ``ServerClosed``).
- :mod:`.cache` — ``SignatureCache``: warm CachedOp executables per
  (item shape, batch bucket), LRU-bounded, counted.
- :mod:`.server` — ``ModelServer``: worker threads, bounded admission,
  deadlines, SIGTERM drain with the resumable exit code.
- :mod:`.metrics` — ``ServerMetrics``: latency/batch/queue histograms,
  Prometheus text + JSON export, profiler spans per dispatch.
- :mod:`.registry` — ``ModelRegistry``: versioned on-disk artifact store
  (SHA-256 manifests, atomic CURRENT pointer, quarantine + fallback).
- :mod:`.fleet` — ``FleetServer``/``Fleet``: registry-driven replicas
  with atomic hot-swap deploys and rolling fleet-wide rollouts.
- :mod:`.aot` — zero-compile cold start: persistent compile cache, AOT
  executable bundles, signature-replay warmers.
- :mod:`.router` — the process-level fleet: ``ReplicaEndpoint`` (socket
  front-end of one replica process), ``FleetRouter`` (least-loaded
  dispatch, retry-on-death, rolling deploy over processes).
- :mod:`.autoscale` — pure ``decide()`` scaling ladder + the
  ``Autoscaler`` executor (``MXTPU_FLEET_MIN/MAX/TARGET_QUEUE``).

Quick start::

    server = serving.ModelServer(net, bucket_shapes=[(3, 224, 224)])
    server.warmup()
    fut = server.submit(image)          # -> PredictionFuture
    probs = fut.result(timeout=1.0)
    print(server.metrics_text())        # Prometheus exposition

Fleet quick start (registry-driven, hot-swappable)::

    reg = serving.ModelRegistry()           # MXTPU_SERVE_REGISTRY
    v1 = reg.publish("resnet", net=net,
                     signature={"bucket_shapes": [[3, 224, 224]]})
    server = serving.FleetServer(reg, "resnet").start()
    ...
    v2 = reg.publish("resnet", net=new_net, signature=...)
    server.publish_aot(version=v2)          # vN+1 deploys compile-free
    server.deploy(v2)                       # warm in bg, atomic flip
    server.rollback()                       # one-call escape hatch
"""
from .aot import (ReplayLog, enable_compile_cache,  # noqa: F401
                  runtime_fingerprint, warm_from_replay)
from .batcher import (Batch, BucketTable, DeadlineExceeded,  # noqa: F401
                      NoBucket, PredictionFuture, QueueFull, Request,
                      ServerClosed, ServingError, batch_buckets, pad_rows)
from .cache import SignatureCache  # noqa: F401
from .fleet import DeployReport, Fleet, FleetServer  # noqa: F401
from .metrics import ServerMetrics  # noqa: F401
from .autoscale import Autoscaler, decide  # noqa: F401
from .lookup import (LookupFleet, LookupReplica,  # noqa: F401
                     publish_embedding)
from .registry import (ModelRegistry, RegistryCorruptError,  # noqa: F401
                       ResolvedVersion)
from .router import (FleetRouter, ReplicaClient,  # noqa: F401
                     ReplicaDead, ReplicaEndpoint, RouterFuture,
                     replica_main)
from .server import ActiveModel, ModelServer  # noqa: F401

__all__ = ["ModelServer", "SignatureCache", "ServerMetrics", "ServingError",
           "QueueFull", "DeadlineExceeded", "NoBucket", "ServerClosed",
           "PredictionFuture", "BucketTable", "batch_buckets", "pad_rows",
           "ModelRegistry", "ResolvedVersion", "RegistryCorruptError",
           "FleetServer", "Fleet", "DeployReport", "ActiveModel",
           "ReplayLog", "enable_compile_cache", "runtime_fingerprint",
           "warm_from_replay", "FleetRouter", "ReplicaEndpoint",
           "ReplicaClient", "ReplicaDead", "RouterFuture", "replica_main",
           "Autoscaler", "decide", "LookupFleet", "LookupReplica",
           "publish_embedding"]
