"""mxnet_tpu.serving — the online inference subsystem.

The reference pairs MXNet with an external model server (MMS/multi-model-
server: dynamic batching, warm workers, a metrics sidecar). Here serving
is a first-class in-process subsystem, built for the TPU cost model where
the two dominant taxes are per-dispatch overhead (amortized by the dynamic
batcher) and per-signature XLA compiles (bounded by shape buckets + the
compiled-signature cache).

Pieces (one module each):

- :mod:`.batcher` — bucketing/padding policy + typed admission errors
  (``QueueFull``, ``DeadlineExceeded``, ``NoBucket``, ``ServerClosed``).
- :mod:`.cache` — ``SignatureCache``: warm CachedOp executables per
  (item shape, batch bucket), LRU-bounded, counted.
- :mod:`.server` — ``ModelServer``: worker threads, bounded admission,
  deadlines, SIGTERM drain with the resumable exit code.
- :mod:`.metrics` — ``ServerMetrics``: latency/batch/queue histograms,
  Prometheus text + JSON export, profiler spans per dispatch.

Quick start::

    server = serving.ModelServer(net, bucket_shapes=[(3, 224, 224)])
    server.warmup()
    fut = server.submit(image)          # -> PredictionFuture
    probs = fut.result(timeout=1.0)
    print(server.metrics_text())        # Prometheus exposition
"""
from .batcher import (Batch, BucketTable, DeadlineExceeded,  # noqa: F401
                      NoBucket, PredictionFuture, QueueFull, Request,
                      ServerClosed, ServingError, batch_buckets, pad_rows)
from .cache import SignatureCache  # noqa: F401
from .metrics import ServerMetrics  # noqa: F401
from .server import ModelServer  # noqa: F401

__all__ = ["ModelServer", "SignatureCache", "ServerMetrics", "ServingError",
           "QueueFull", "DeadlineExceeded", "NoBucket", "ServerClosed",
           "PredictionFuture", "BucketTable", "batch_buckets", "pad_rows"]
