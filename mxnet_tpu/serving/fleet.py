"""Fleet serving: registry-driven replicas with atomic hot-swap.

Layered on :class:`~mxnet_tpu.serving.server.ModelServer` (PR 3) and the
:class:`~mxnet_tpu.serving.registry.ModelRegistry`: a
:class:`FleetServer` is one replica that *deploys versions* instead of
holding a model forever, and a :class:`Fleet` is N replicas behind one
``submit()`` with rolling deploys.

Hot-swap protocol (``FleetServer.deploy``), the zero-downtime contract:

1. **Resolve + verify**: the requested version is content-verified
   against its SHA-256 manifest (corrupt -> quarantine + fallback when
   following CURRENT).
2. **Load + warm in the background**: the new version's SymbolBlock and
   :class:`SignatureCache` are built while the OLD version keeps serving
   every request. Warmup is layered cheapest-first: AOT executables
   published with the version (zero compiles, zero traces), then the
   persistent compile cache (compiles become disk reads), then real
   compiles for anything left; the published signature set and the
   version's replay file both drive it.
3. **Atomic flip**: one reference assignment under the server's admission
   lock. Every batch dispatch captures (active model, dispatch seq) under
   the same lock, so the stream of response version tags is monotone —
   no request is served by a half-warmed model, none by a mix.
4. **Drain**: the deployer waits for batches in flight against the old
   version to finish before declaring the deploy done (the old
   executables stay alive exactly as long as a worker still uses them).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

from ..base import MXNetError
from ..log import get_logger
from .aot import ReplayLog, enable_compile_cache, warm_from_replay
from .cache import SignatureCache
from .registry import (AOT_NAME, REPLAY_NAME, ModelRegistry,
                       ResolvedVersion)
from .server import ActiveModel, ModelServer

__all__ = ["FleetServer", "Fleet", "DeployReport"]

_LOG = get_logger("mxnet_tpu.serving.fleet")


def _metrics():
    from ..telemetry import default_registry
    reg = default_registry()
    return (reg.counter("mxtpu_serve_deploys_total",
                        "Completed FleetServer hot-swap deploys.",
                        label="model"),
            reg.counter("mxtpu_serve_deploy_compiles_total",
                        "Fresh XLA compiles paid during deploy warmups "
                        "(0 = the AOT bundle / compile cache covered the "
                        "whole signature set)."),
            reg.gauge("mxtpu_serve_warm_seconds",
                      "Background load+warm wall-clock of the most "
                      "recent deploy (the old version served "
                      "throughout)."),
            reg.gauge("mxtpu_serve_swap_drain_seconds",
                      "Old-version drain wall-clock of the most recent "
                      "deploy (in-flight batches finishing after the "
                      "flip)."))


class DeployReport(dict):
    """Dict-shaped deploy summary (keys: model, version, previous,
    compiles, aot_loaded, warmed_signatures, warm_s, drain_s)."""
    __getattr__ = dict.__getitem__


class FleetServer(ModelServer):
    """A registry-attached serving replica with atomic hot-swap.

    ``FleetServer(registry, "resnet")`` resolves the model's CURRENT
    version, warms it (AOT bundle / compile cache / replay) and serves
    it; ``deploy()`` later swaps any other version in with zero dropped
    and zero mixed-version requests. All ModelServer policy knobs pass
    through. ``bucket_shapes`` defaults to the published signature set.
    """

    def __init__(self, registry: Optional[ModelRegistry], model: str,
                 version: str = "current", warm: bool = True, **kwargs):
        self.registry = registry if registry is not None else ModelRegistry()
        self.model = model
        # the zero-compile cold-start contract is automatic for fleet
        # replicas: a configured MXTPU_COMPILE_CACHE is wired before the
        # first trace so every warmup compile is a cache write/read
        enable_compile_cache()
        resolved = self.registry.resolve(model, version)
        sig = resolved.signature
        if "bucket_shapes" not in kwargs:
            shapes = sig.get("bucket_shapes")
            kwargs["bucket_shapes"] = ([tuple(s) for s in shapes]
                                       if shapes else None)
        if "dtype" not in kwargs and sig.get("dtype"):
            kwargs["dtype"] = sig["dtype"]
        kwargs.setdefault("name", model)
        net = self._load_net(resolved)
        super().__init__(net, **kwargs)
        self._active.version = resolved.version
        # exposed so process-level replicas (serving/router.py) can report
        # their cold-start compile bill (the 0-compile scale-up proof)
        self.cold_start_stats: Dict[str, int] = {}
        if warm:
            t0 = time.perf_counter()
            stats = self._warm_active(self._active, resolved)
            self.cold_start_stats = dict(stats)
            _LOG.info("fleet: %s/%s cold start warmed in %.2fs (%s)",
                      model, resolved.version,
                      time.perf_counter() - t0, stats)

    # -- internals --------------------------------------------------------
    def _load_net(self, resolved: ResolvedVersion):
        from ..gluon.block import SymbolBlock
        names = resolved.manifest.get("input_names") or ["data"]
        return SymbolBlock.imports(f"{resolved.prefix}-symbol.json",
                                   list(names),
                                   f"{resolved.prefix}-0000.params")

    def _warm_active(self, active: ActiveModel, resolved: ResolvedVersion
                     ) -> Dict[str, int]:
        """Warm one ActiveModel from the version's artifacts: AOT bundle
        first (free), then drive every published + replayed signature
        through the cache (hits when AOT/compile cache covered them)."""
        from .. import profiler
        stats = {"aot_loaded": 0, "warmed_signatures": 0, "compiles": 0}
        t0 = time.perf_counter()
        aot = resolved.aot_path
        if aot and active.cache._op is not None:
            stats["aot_loaded"] = active.cache._op.aot_load(aot)
        before = active.cache.cache_info().misses
        sig = resolved.signature
        shapes = [tuple(s) for s in sig.get("bucket_shapes") or []]
        if shapes:
            # warm the REPLICA's batch buckets, not the published
            # batch_sizes: admission pads to this table's pow2 buckets,
            # so a published subset would leave hot-path signatures cold
            # after the flip (first coalesced batch pays a live compile)
            batch_sizes = self._table.batch_sizes
            dtype = sig.get("dtype", str(self.dtype))
            stats["warmed_signatures"] += len(shapes) * len(batch_sizes)
            active.cache.warmup(shapes, batch_sizes, dtype)
        replay = resolved.replay_path
        if replay:
            replay_sigs = ReplayLog.signatures(replay)
            stats["warmed_signatures"] += len(replay_sigs)
            warm_from_replay(active.cache, replay, signatures=replay_sigs)
        stats["compiles"] = active.cache.cache_info().misses - before
        profiler.record_span(
            f"deploy_warm[{self.model}]", "serving", t0,
            time.perf_counter(),
            args={"version": resolved.version or "", **stats})
        return stats

    # -- deploy / rollback ------------------------------------------------
    def deploy(self, version: str = "current", warm: bool = True,
               drain_timeout: float = 30.0) -> DeployReport:
        """Atomically swap the serving model to ``version``.

        Loads and warms the new version while the current one keeps
        serving, flips on one reference swap, then waits for in-flight
        batches against the old version to drain. Safe to call from any
        thread; concurrent deploys are serialized by last-flip-wins on
        the reference (run one deployer per replica)."""
        deploys, compile_ctr, warm_g, drain_g = _metrics()
        resolved = self.registry.resolve(self.model, version)
        old = self._active
        if resolved.version == old.version:
            _LOG.info("fleet: %s already serving %s — no-op deploy",
                      self.model, resolved.version)
            return DeployReport(model=self.model, version=resolved.version,
                                previous=old.version, compiles=0,
                                aot_loaded=0, warmed_signatures=0,
                                warm_s=0.0, drain_s=0.0)
        new_shapes = {tuple(s) for s in
                      resolved.signature.get("bucket_shapes") or []}
        if new_shapes and self._table.bucket_shapes is not None and \
                new_shapes != self._table.bucket_shapes:
            # admission policy (the bucket table) is fixed at replica
            # construction: a version that changes the shape closure
            # needs replica restarts (rolling), not a hot-swap
            _LOG.warning(
                "fleet: %s/%s publishes bucket_shapes %s but this replica "
                "admits %s — extra shapes will be warmed yet never "
                "admitted; restart replicas to change the closure",
                self.model, resolved.version, sorted(new_shapes),
                sorted(self._table.bucket_shapes))
        t0 = time.perf_counter()
        net = self._load_net(resolved)
        fresh = ActiveModel(SignatureCache(net, cache_size=self._cache_size),
                            resolved.version)
        stats = (self._warm_active(fresh, resolved) if warm
                 else {"aot_loaded": 0, "warmed_signatures": 0,
                       "compiles": 0})
        warm_s = time.perf_counter() - t0
        # THE flip: one reference assignment under the admission lock —
        # the same lock every dispatch captures (active, seq) under
        with self._cond:
            self._active = fresh
        t1 = time.perf_counter()
        drained = old.drain(drain_timeout)
        drain_s = time.perf_counter() - t1
        if not drained:
            _LOG.warning("fleet: %s: old version %s still has %d batches "
                         "in flight after %.1fs drain budget", self.model,
                         old.version, old.inflight, drain_timeout)
        deploys.inc(label_value=self.model)
        compile_ctr.inc(stats["compiles"])
        warm_g.set(warm_s)
        drain_g.set(drain_s)
        from .. import profiler
        profiler.record_span(
            f"deploy_swap[{self.model}]", "serving", t1,
            time.perf_counter(),
            args={"from": old.version or "", "to": resolved.version or "",
                  "drained": bool(drained)})
        _LOG.info("fleet: %s deployed %s -> %s (warm %.2fs, %d fresh "
                  "compiles, drain %.2fs)", self.model, old.version,
                  resolved.version, warm_s, stats["compiles"], drain_s)
        return DeployReport(model=self.model, version=resolved.version,
                            previous=old.version,
                            compiles=stats["compiles"],
                            aot_loaded=stats["aot_loaded"],
                            warmed_signatures=stats["warmed_signatures"],
                            warm_s=warm_s, drain_s=drain_s)

    def rollback(self, version: Optional[str] = None) -> DeployReport:
        """Repoint the registry's CURRENT (previous version by default)
        and deploy it — the operator's one-call bad-deploy escape."""
        target = self.registry.rollback(self.model, version)
        return self.deploy(target)

    def publish_aot(self, version: Optional[str] = None) -> int:
        """Export this replica's warm executables as the AOT bundle of
        ``version`` (default: the active version) — typically called once
        after the first replica warms, so every later replica cold-starts
        from the bundle. Returns the number of executables exported."""
        version = version or self._active.version
        if version is None:
            raise MXNetError("publish_aot: no version to attach to")
        op = self._active.cache._op
        if op is None:
            raise MXNetError("publish_aot: plain-callable models have no "
                             "compiled executables to export")
        import tempfile
        fd, tmp = tempfile.mkstemp(suffix=".aot.stage")
        os.close(fd)
        try:
            n = op.aot_export(tmp)
            self.registry.attach(self.model, version, AOT_NAME, tmp)
        finally:
            try:
                os.remove(tmp)
            except OSError:
                pass
        return n

    def publish_replay(self, version: Optional[str] = None) -> Optional[str]:
        """Attach this replica's live replay log (``MXTPU_SERVE_REPLAY``)
        to ``version`` so new replicas prewarm from real traffic."""
        version = version or self._active.version
        if self._replay is None or version is None:
            return None
        if not os.path.exists(self._replay.path):
            return None
        self.registry.attach(self.model, version, REPLAY_NAME,
                             self._replay.path)
        return os.path.join(self.registry._version_dir(self.model, version),
                            REPLAY_NAME)


class Fleet:
    """N in-process replicas behind one ``submit()``: round-robin with
    shed-failover, rolling deploys, aggregated metrics.

    The in-process fleet is the *protocol* tier — the routing, rolling-
    deploy and drain semantics a multi-host fleet needs, testable on one
    machine. Each replica is a full :class:`FleetServer` (own batcher,
    workers, admission bound), so saturation behavior composes: a replica
    that sheds with ``QueueFull`` fails the request over to the next.
    """

    def __init__(self, registry: Optional[ModelRegistry], model: str,
                 replicas: int = 2, version: str = "current", **kwargs):
        if int(replicas) < 1:
            raise MXNetError("Fleet needs at least 1 replica")
        self.model = model
        self.registry = registry if registry is not None else ModelRegistry()
        self.replicas: List[FleetServer] = []
        for i in range(int(replicas)):
            kw = dict(kwargs)
            kw["name"] = f"{model}-r{i}"
            self.replicas.append(
                FleetServer(self.registry, model, version=version, **kw))
        self._rr = 0
        self._rr_lock = threading.Lock()

    def start(self) -> "Fleet":
        for r in self.replicas:
            r.start()
        return self

    def submit(self, x, deadline_ms: Optional[float] = None):
        """Route to the next replica (round-robin); a replica shedding
        with QueueFull fails over to the others before giving up."""
        from .batcher import QueueFull
        with self._rr_lock:
            start = self._rr
            self._rr = (self._rr + 1) % len(self.replicas)
        last_err: Optional[Exception] = None
        for i in range(len(self.replicas)):
            r = self.replicas[(start + i) % len(self.replicas)]
            try:
                return r.submit(x, deadline_ms=deadline_ms)
            except QueueFull as e:
                last_err = e
        raise last_err  # every replica saturated: shed to the client

    def predict(self, x, deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None):
        return self.submit(x, deadline_ms=deadline_ms).result(timeout)

    def deploy(self, version: str = "current",
               drain_timeout: float = 30.0) -> List[DeployReport]:
        """Rolling deploy: replicas swap one at a time, each finishing
        its warm+flip+drain before the next starts — at most one replica
        is warming at any moment, the rest serve at full capacity."""
        return [r.deploy(version, drain_timeout=drain_timeout)
                for r in self.replicas]

    def versions(self) -> List[Optional[str]]:
        return [r.active_version for r in self.replicas]

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        for r in self.replicas:
            r.stop(drain=drain, timeout=timeout)

    def metrics_json(self) -> dict:
        return {r.name: r.metrics_json() for r in self.replicas}
