"""Versioned model registry: the fleet's source of truth for artifacts.

The reference framework's predict path (``src/c_predict_api.cc``) assumes a
fleet of stateless inference workers loading exported symbol+params
artifacts from shared storage. This module is that storage contract made
explicit — a directory layout plus the integrity and atomicity rules a
fleet needs so that N replicas and one publisher never observe a torn or
corrupt model:

    <root>/<model>/
        CURRENT                     # version name, atomically renamed in
        v1/
            model-symbol.json       # HybridBlock.export artifacts
            model-0000.params
            MANIFEST.json           # signature set + metadata + fingerprint
            manifest.json           # per-file SHA-256 (fault.write_manifest)
            DONE                    # completion marker, written last
            aot.bin                 # optional: serialized XLA executables
            replay.jsonl            # optional: recorded shape traffic
        v2/ ...

Rules, mirrored from ``fault.CheckpointManager`` (same failure model —
publish is a checkpoint of a model):

- **Atomic publish**: artifacts are staged in ``<version>.tmp`` and
  ``os.replace``d into place; ``DONE`` is written last inside the staging
  dir. A reader never sees a half-written version.
- **Atomic pointer**: ``CURRENT`` is a one-line file updated via
  tmp+rename; replicas resolving "current" either see the old version or
  the new one, never a torn read.
- **Verify on read**: ``resolve`` re-checks the SHA-256 manifest before
  handing a version to a server. Corrupt versions are quarantined
  (renamed ``<version>.bad``) and resolution falls back to the newest
  verified version, exactly like ``restore_latest``.
- **GC keeps serving safe**: ``gc(keep=N)`` never deletes the version
  ``CURRENT`` points at.
"""
from __future__ import annotations

import json
import os
import re
import shutil
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError, env
from ..fault import ManifestError, verify_manifest, write_manifest
from ..log import get_logger

__all__ = ["ModelRegistry", "ResolvedVersion", "RegistryCorruptError",
           "default_registry_root"]

_LOG = get_logger("mxnet_tpu.serving.registry")

#: artifact prefix inside a version dir — fixed so a resolver needs no
#: out-of-band knowledge to build the ``SymbolBlock.imports`` paths
ARTIFACT_PREFIX = "model"
MANIFEST_NAME = "MANIFEST.json"
CURRENT_NAME = "CURRENT"
DONE_NAME = "DONE"
AOT_NAME = "aot.bin"
REPLAY_NAME = "replay.jsonl"

_VERSION_RE = re.compile(r"^v(\d+)$")
_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]*$")


class RegistryCorruptError(ManifestError):
    """A registry version failed content verification (forged/missing
    manifest hash, truncated artifact, missing file). ``resolve``
    quarantines such versions and falls back to the newest verified one;
    a pinned ``resolve(model, version=...)`` surfaces it to the caller."""


def default_registry_root() -> str:
    """The registry root: ``MXTPU_SERVE_REGISTRY`` or ``<cwd>/registry``."""
    root = env.get("MXTPU_SERVE_REGISTRY")
    return root if root else os.path.join(os.getcwd(), "registry")


def _check_name(kind: str, name: str) -> str:
    if not _NAME_RE.match(name or ""):
        raise MXNetError(f"registry: invalid {kind} name {name!r} "
                         "(want [A-Za-z0-9][A-Za-z0-9._-]*)")
    return name


def _atomic_write(path: str, text: str) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


class ResolvedVersion:
    """One verified, loadable model version (what ``resolve`` returns)."""

    __slots__ = ("model", "version", "path", "manifest")

    def __init__(self, model: str, version: str, path: str, manifest: dict):
        self.model = model
        self.version = version
        self.path = path
        self.manifest = manifest          # parsed MANIFEST.json

    @property
    def prefix(self) -> str:
        """``SymbolBlock.imports``-style prefix of the artifacts."""
        return os.path.join(self.path, ARTIFACT_PREFIX)

    @property
    def signature(self) -> dict:
        """The closed signature set published with the version:
        ``{input_names, bucket_shapes, batch_sizes?, dtype}``."""
        return self.manifest.get("signature", {})

    @property
    def aot_path(self) -> Optional[str]:
        p = os.path.join(self.path, AOT_NAME)
        return p if os.path.exists(p) else None

    @property
    def replay_path(self) -> Optional[str]:
        p = os.path.join(self.path, REPLAY_NAME)
        return p if os.path.exists(p) else None

    def __repr__(self):
        return f"ResolvedVersion({self.model}/{self.version})"


class ModelRegistry:
    """On-disk versioned model registry with atomic publish / CURRENT
    flip / verified resolve / quarantine / gc.

    Thread/process safety model: many readers, one publisher per model
    (the usual CI/CD shape). All reader-visible transitions are single
    ``os.replace`` calls, so concurrent readers are safe against a
    publisher; two concurrent publishers to the same model may race
    version numbering (last CURRENT flip wins).
    """

    def __init__(self, root: Optional[str] = None):
        self.root = root if root else default_registry_root()
        os.makedirs(self.root, exist_ok=True)

    # -- layout helpers ---------------------------------------------------
    def _model_dir(self, model: str) -> str:
        return os.path.join(self.root, _check_name("model", model))

    def _version_dir(self, model: str, version: str) -> str:
        return os.path.join(self._model_dir(model),
                            _check_name("version", version))

    def models(self) -> List[str]:
        if not os.path.isdir(self.root):
            return []
        return sorted(n for n in os.listdir(self.root)
                      if os.path.isdir(os.path.join(self.root, n)))

    def versions(self, model: str) -> List[str]:
        """Complete (DONE-marked) versions, oldest first; quarantined
        ``.bad`` versions excluded."""
        mdir = self._model_dir(model)
        if not os.path.isdir(mdir):
            return []
        out: List[Tuple[int, str]] = []
        for name in os.listdir(mdir):
            m = _VERSION_RE.match(name)
            if m and os.path.exists(os.path.join(mdir, name, DONE_NAME)):
                out.append((int(m.group(1)), name))
        return [name for _, name in sorted(out)]

    def next_version(self, model: str) -> str:
        """The next monotone version name — counts quarantined and
        in-flight versions too, so a republish after quarantine never
        reuses a name a replica may have cached."""
        mdir = self._model_dir(model)
        top = 0
        if os.path.isdir(mdir):
            for name in os.listdir(mdir):
                m = _VERSION_RE.match(name.split(".", 1)[0])
                if m:
                    top = max(top, int(m.group(1)))
        return f"v{top + 1}"

    def current(self, model: str) -> Optional[str]:
        """The version ``CURRENT`` points at (no verification), or None."""
        try:
            with open(os.path.join(self._model_dir(model), CURRENT_NAME)) as f:
                v = f.read().strip()
            return v or None
        except OSError:
            return None

    def set_current(self, model: str, version: str) -> None:
        """Atomically repoint ``CURRENT``; the version must be complete."""
        vdir = self._version_dir(model, version)
        if not os.path.exists(os.path.join(vdir, DONE_NAME)):
            raise MXNetError(
                f"registry: cannot point CURRENT at incomplete version "
                f"{model}/{version}")
        _atomic_write(os.path.join(self._model_dir(model), CURRENT_NAME),
                      version + "\n")

    # -- publish ----------------------------------------------------------
    def publish(self, model: str, net=None, prefix: Optional[str] = None,
                signature: Optional[dict] = None,
                metadata: Optional[dict] = None,
                version: Optional[str] = None,
                set_current: bool = True,
                input_names: Sequence[str] = ("data",)) -> str:
        """Publish one model version; returns the version name.

        Pass ``net`` (a HybridBlock — exported via ``net.export``) or
        ``prefix`` (existing ``prefix-symbol.json`` + ``prefix-0000.params``
        artifacts, copied in). ``signature`` records the closed serving
        signature set (``bucket_shapes``, ``dtype``, optional
        ``batch_sizes``) that deploy-time warmup drives; ``metadata`` is
        free-form and lands in ``MANIFEST.json``.
        """
        if (net is None) == (prefix is None):
            raise MXNetError("registry.publish needs exactly one of "
                             "net= or prefix=")
        mdir = self._model_dir(model)
        os.makedirs(mdir, exist_ok=True)
        if version is None:
            version = self.next_version(model)
        if not _VERSION_RE.match(version):
            # only vN names: anything else collides with the CURRENT
            # pointer / quarantine namespaces and is invisible to
            # versions()/gc()/rollback()
            raise MXNetError(
                f"registry: version must match v<N> (got {version!r})")
        vdir = os.path.join(mdir, version)
        if os.path.exists(vdir):
            raise MXNetError(
                f"registry: version {model}/{version} already exists "
                "(versions are immutable — publish a new one)")
        tmp = f"{vdir}.tmp"
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            art = os.path.join(tmp, ARTIFACT_PREFIX)
            if net is not None:
                net.export(art, epoch=0, input_names=tuple(input_names))
            else:
                for suffix in ("-symbol.json", "-0000.params"):
                    src = f"{prefix}{suffix}"
                    if not os.path.exists(src):
                        raise MXNetError(
                            f"registry.publish: artifact {src} not found "
                            "(need the HybridBlock.export layout)")
                    shutil.copyfile(src, f"{art}{suffix}")
            manifest = {
                "model": model,
                "version": version,
                "created": time.time(),
                "input_names": list(input_names),
                "signature": dict(signature or {}),
                "metadata": dict(metadata or {}),
                "fingerprint": _runtime_fingerprint(),
            }
            with open(os.path.join(tmp, MANIFEST_NAME), "w") as f:
                json.dump(manifest, f, indent=1)
            # integrity proof over every artifact (incl. MANIFEST.json),
            # then the completion marker — same discipline as checkpoints
            write_manifest(tmp)
            with open(os.path.join(tmp, DONE_NAME), "w") as f:
                f.write("ok")
            os.replace(tmp, vdir)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        if set_current:
            self.set_current(model, version)
        _LOG.info("registry: published %s/%s%s", model, version,
                  " (current)" if set_current else "")
        from ..contrib import chaos
        plan = chaos.active()
        if plan is not None:
            plan.on_publish_complete(model, version, vdir)
        self._count("publish")
        return version

    def attach(self, model: str, version: str, name: str, src: str) -> None:
        """Attach a sidecar file (AOT bundle, replay log) to a published
        version. Sidecars are added to the integrity manifest so resolve
        verifies them too; the attach itself is atomic (tmp+rename)."""
        vdir = self._version_dir(model, version)
        if not os.path.exists(os.path.join(vdir, DONE_NAME)):
            raise MXNetError(f"registry: {model}/{version} is not complete")
        dst = os.path.join(vdir, name)
        tmp = f"{dst}.tmp.{os.getpid()}"
        shutil.copyfile(src, tmp)
        os.replace(tmp, dst)
        write_manifest(vdir, exclude=(DONE_NAME,))

    # -- resolve / verify -------------------------------------------------
    def verify(self, model: str, version: str) -> dict:
        """Content-verify one version; returns the parsed MANIFEST.json.
        Raises :class:`RegistryCorruptError` on any failure."""
        vdir = self._version_dir(model, version)
        label = f"registry {model}/{version}"
        if not os.path.exists(os.path.join(vdir, DONE_NAME)):
            raise RegistryCorruptError(
                f"{label} is missing or incomplete (no DONE)")
        # unlike legacy checkpoints, registry versions ALWAYS carry a
        # manifest — a missing one is corruption, not a legacy layout
        verify_manifest(vdir, label=label, error_cls=RegistryCorruptError,
                        required=True)
        try:
            with open(os.path.join(vdir, MANIFEST_NAME)) as f:
                return json.load(f)
        except (OSError, ValueError) as e:
            raise RegistryCorruptError(
                f"{label}: unreadable {MANIFEST_NAME}: {e}") from e

    def _quarantine(self, model: str, version: str, reason: str) -> str:
        vdir = self._version_dir(model, version)
        bad = f"{vdir}.bad"
        i = 0
        while os.path.exists(bad):
            i += 1
            bad = f"{vdir}.bad{i}"
        try:
            os.replace(vdir, bad)
        except FileNotFoundError:
            # another replica quarantined it first — same outcome
            return bad
        _LOG.warning("registry: quarantined corrupt version %s/%s -> %s "
                     "(%s)", model, version, os.path.basename(bad), reason)
        self._count("quarantine")
        return bad

    def resolve(self, model: str, version: str = "current"
                ) -> ResolvedVersion:
        """Resolve + verify a version for serving.

        ``version="current"`` follows the CURRENT pointer; a corrupt (or
        dangling) target is quarantined and resolution falls back to the
        newest verified version, repointing CURRENT at it — a fleet
        replica restarting against a rotted registry still comes up on
        the best available model. A pinned version raises instead (the
        caller asked for those exact bytes).
        """
        follow = version == "current"
        if follow:
            pinned = self.current(model)
            if pinned is None:
                # missing CURRENT pointer: fall back to the newest
                # verified version (and restore the pointer)
                _LOG.warning("registry: %s has no CURRENT pointer; "
                             "falling back to newest verified version",
                             model)
                return self._resolve_fallback(model, skip=None)
        else:
            pinned = version
        try:
            manifest = self.verify(model, pinned)
        except RegistryCorruptError as e:
            if os.path.isdir(self._version_dir(model, pinned)):
                self._quarantine(model, pinned, str(e))
            if not follow:
                raise
            _LOG.warning("registry: CURRENT %s/%s failed verification "
                         "(%s); falling back", model, pinned, e)
            return self._resolve_fallback(model, skip=pinned)
        return ResolvedVersion(model, pinned,
                               self._version_dir(model, pinned), manifest)

    def _resolve_fallback(self, model: str, skip: Optional[str]
                          ) -> ResolvedVersion:
        for v in reversed(self.versions(model)):
            if v == skip:
                continue
            try:
                manifest = self.verify(model, v)
            except RegistryCorruptError as e:
                self._quarantine(model, v, str(e))
                continue
            self.set_current(model, v)  # heal the pointer
            return ResolvedVersion(model, v, self._version_dir(model, v),
                                   manifest)
        raise MXNetError(
            f"registry: no verified version of {model!r} available "
            f"(known models: {self.models()})")

    # -- gc / rollback ----------------------------------------------------
    def gc(self, model: str, keep: int = 3) -> List[str]:
        """Delete all but the newest ``keep`` versions (the CURRENT target
        is always kept, even if older). Returns the deleted versions."""
        if keep < 1:
            raise MXNetError("registry.gc: keep must be >= 1")
        cur = self.current(model)
        versions = self.versions(model)
        deleted = []
        for v in versions[:-keep] if keep < len(versions) else []:
            if v == cur:
                continue
            shutil.rmtree(self._version_dir(model, v), ignore_errors=True)
            deleted.append(v)
        if deleted:
            _LOG.info("registry: gc %s: deleted %s", model, deleted)
        return deleted

    def rollback(self, model: str, version: Optional[str] = None) -> str:
        """Repoint CURRENT at ``version`` (default: the newest complete
        version older than the current one). Returns the new current."""
        if version is None:
            cur = self.current(model)
            versions = self.versions(model)
            older = [v for v in versions if cur is None or
                     _version_num(v) < _version_num(cur)]
            if not older:
                raise MXNetError(
                    f"registry: nothing to roll back to for {model!r} "
                    f"(current={cur}, versions={versions})")
            version = older[-1]
        self.verify(model, version)  # never roll back onto corrupt bytes
        self.set_current(model, version)
        _LOG.info("registry: rollback %s -> %s", model, version)
        self._count("rollback")
        return version

    @staticmethod
    def _count(event: str) -> None:
        try:
            from ..telemetry import default_registry
            default_registry().counter(
                "mxtpu_registry_ops_total",
                "Model-registry operations, by kind.",
                label="op").inc(label_value=event)
        except Exception:
            pass


def _version_num(version: str) -> int:
    m = _VERSION_RE.match(version)
    return int(m.group(1)) if m else -1


def _runtime_fingerprint() -> Dict[str, str]:
    """The (jaxlib, backend) identity AOT artifacts and the persistent
    compile cache are keyed by — a replica on a different runtime must
    recompile, not deserialize."""
    from .aot import runtime_fingerprint
    return runtime_fingerprint()
