"""Embedding-lookup serving: the sparse plane's registry-driven path.

The reference's recommender serving story is a kvstore row_sparse pull
against the server fleet (``KVStore::PullRowSparse``): inference workers
fetch only the touched rows of a server-sharded table, then run the small
dense tower locally. This module is that path on the fleet/registry
machinery: a trained :class:`~mxnet_tpu.parallel.embedding_plane.
EmbeddingPlane` publishes its shard set as a SIDECAR of the dense-tower
model version (the ``registry.attach`` integrity contract — the table is
manifest-hashed and verified on resolve like every artifact), and
replicas resolve the SAME version to answer both request kinds:

- **embedding-lookup**: ``lookup(ids) -> (batch, dim)`` rows, served
  through the plane's compiled masked-gather kernel over the PER-RANK
  shard arrays as published (the replica provably serves the sharded
  table, not a densified copy);
- **dense-tower**: the published HybridBlock, loaded via
  ``SymbolBlock.imports`` exactly like :class:`~mxnet_tpu.serving.fleet.
  FleetServer` replicas load theirs.

:class:`LookupFleet` is the protocol tier (the ``fleet.Fleet``
discipline): N in-process replicas behind one round-robin ``lookup()``,
each replica a full resolve-verify-load of the registry version, so a
corrupt sidecar quarantines before a replica ever serves from it. Heavy
dense-tower traffic with batching/deadlines/hot-swap rides the existing
``FleetServer`` against the same version — the lookup path adds the one
request kind dense serving had no answer for. The ``recsys`` bench row
measures this path's ``lookup_qps`` closed-loop.
"""
from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import List, Optional

import numpy as _np

from ..base import MXNetError, check
from .registry import ModelRegistry, ResolvedVersion

__all__ = ["EMBEDDING_SIDECAR", "publish_embedding", "LookupReplica",
           "LookupFleet"]

#: sidecar file name inside a version dir (manifest-verified on resolve)
EMBEDDING_SIDECAR = "embedding.npz"


def publish_embedding(registry: ModelRegistry, model: str, plane, net,
                      signature: Optional[dict] = None,
                      metadata: Optional[dict] = None,
                      input_names=("data",)) -> str:
    """Publish one (dense tower, embedding table) version: the tower via
    the normal ``registry.publish`` artifact path, the plane's shard set
    attached as the :data:`EMBEDDING_SIDECAR` sidecar — ONE version, one
    manifest, so a replica can never serve a tower against the wrong
    table generation. Returns the version name."""
    meta = dict(metadata or {})
    meta["embedding"] = plane.describe()
    version = registry.publish(model, net=net, signature=signature,
                               metadata=meta, input_names=input_names)
    fd, tmp = tempfile.mkstemp(suffix=".npz")
    os.close(fd)
    try:
        plane.save_npz(tmp)
        registry.attach(model, version, EMBEDDING_SIDECAR, tmp)
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass
    return version


class LookupReplica:
    """One resolved version serving embedding-lookup + dense-tower
    requests. Loads the sidecar's per-rank shard arrays verbatim and
    the tower via ``SymbolBlock.imports`` (the FleetServer loader)."""

    def __init__(self, registry: ModelRegistry, model: str,
                 version: str = "current", name: str = "lookup-r0"):
        self.name = name
        self.resolved: ResolvedVersion = registry.resolve(model, version)
        path = os.path.join(self.resolved.path, EMBEDDING_SIDECAR)
        if not os.path.exists(path):
            raise MXNetError(
                f"registry {model}/{self.resolved.version} has no "
                f"{EMBEDDING_SIDECAR} sidecar — publish the table with "
                "serving.lookup.publish_embedding")
        import jax.numpy as jnp
        with _np.load(path) as z:
            rows, dim, world = (int(v) for v in z["meta"])
            shards = [jnp.asarray(z[f"shard_{r}"]) for r in range(world)]
        check(len(shards) == world and
              all(s.shape == (rows // world, dim) for s in shards),
              f"embedding sidecar of {model}/{self.resolved.version} is "
              "inconsistent with its layout meta")
        self.rows, self.dim, self.world = rows, dim, world
        self._shards = tuple(shards)
        self._net = None
        self.requests = 0
        self._lock = threading.Lock()

    # -- request kinds --------------------------------------------------
    def lookup(self, ids) -> _np.ndarray:
        """Embedding-lookup request: the touched rows, gathered through
        the plane's compiled masked-gather over the published shards."""
        from ..parallel.embedding_plane import masked_gather
        with self._lock:
            self.requests += 1
        ids_np = _np.asarray(ids, _np.int64).ravel()
        check(ids_np.size == 0 or
              (int(ids_np.min()) >= 0 and int(ids_np.max()) < self.rows),
              f"lookup ids outside [0, {self.rows})")
        return _np.asarray(masked_gather(self._shards, ids_np))

    def dense_tower(self, x):
        """Dense-tower request: forward the published HybridBlock (lazy
        first load — lookup-only replicas never pay the import)."""
        from ..ndarray import NDArray
        if self._net is None:
            from ..gluon.block import SymbolBlock
            names = self.resolved.manifest.get("input_names") or ["data"]
            self._net = SymbolBlock.imports(
                f"{self.resolved.prefix}-symbol.json", list(names),
                f"{self.resolved.prefix}-0000.params")
        with self._lock:
            self.requests += 1
        data = x if isinstance(x, NDArray) else NDArray(_np.asarray(x))
        return self._net(data).asnumpy()

    def recommend(self, ids) -> _np.ndarray:
        """The combined recsys request: lookup, then tower, one hop."""
        return self.dense_tower(self.lookup(ids))


class LookupFleet:
    """N lookup replicas behind one round-robin ``lookup()`` — the
    ``Fleet`` routing discipline for the read-only lookup tier (no
    queues to shed: a lookup is one compiled gather, the balance knob is
    replica count)."""

    def __init__(self, registry: Optional[ModelRegistry], model: str,
                 replicas: int = 2, version: str = "current"):
        if int(replicas) < 1:
            raise MXNetError("LookupFleet needs at least 1 replica")
        registry = registry if registry is not None else ModelRegistry()
        self.model = model
        self.replicas: List[LookupReplica] = [
            LookupReplica(registry, model, version=version,
                          name=f"{model}-lookup-r{i}")
            for i in range(int(replicas))]
        self._rr = 0
        self._rr_lock = threading.Lock()
        self._t0 = time.perf_counter()

    def _next(self) -> LookupReplica:
        with self._rr_lock:
            r = self.replicas[self._rr % len(self.replicas)]
            self._rr += 1
        return r

    def lookup(self, ids) -> _np.ndarray:
        return self._next().lookup(ids)

    def recommend(self, ids) -> _np.ndarray:
        return self._next().recommend(ids)

    def metrics_json(self) -> dict:
        dt = max(time.perf_counter() - self._t0, 1e-9)
        total = sum(r.requests for r in self.replicas)
        return {"replicas": len(self.replicas),
                "requests": total,
                "lookup_qps": total / dt,
                "per_replica": {r.name: r.requests
                                for r in self.replicas}}
