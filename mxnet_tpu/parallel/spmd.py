"""SPMDTrainer: one fused, sharded XLA program per training step.

This is the TPU-native replacement for the reference's whole training-loop
machinery: DataParallelExecutorGroup batch slicing + per-device executors +
kvstore push/pull + per-param optimizer ops
(python/mxnet/module/executor_group.py, src/kvstore/comm.h) become ONE
jit-compiled step over a Mesh:

    loss+grads+optimizer-update = single HLO module,
    batch sharded on 'dp', params replicated (or sharded by a ShardingPlan),
    gradient reduction = the psum GSPMD inserts because the loss averages
    over a dp-sharded batch. Buffer donation recycles parameter memory.

Works with any Gluon HybridBlock + loss Block.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError, check

__all__ = ["SPMDTrainer"]


class SPMDTrainer:
    def __init__(self, block, loss_fn, mesh=None, optimizer: str = "sgd",
                 optimizer_params: Optional[dict] = None,
                 plan=None, dtype=None, remat: Optional[bool] = None):
        import jax
        self.block = block
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.plan = plan
        # remat=True (or MXNET_BACKWARD_DO_MIRROR) recomputes activations
        # in backward instead of storing them — the memory-for-compute
        # lever for big models / long sequences
        self.remat = remat
        opt_params = dict(optimizer_params or {})
        self.lr = float(opt_params.get("learning_rate", 0.01))
        self.momentum = float(opt_params.get("momentum", 0.0))
        self.wd = float(opt_params.get("wd", 0.0))
        self.optimizer = optimizer
        check(optimizer in ("sgd", "adam"),
              "SPMDTrainer supports sgd/adam (use gluon.Trainer otherwise)")
        self.beta1 = float(opt_params.get("beta1", 0.9))
        self.beta2 = float(opt_params.get("beta2", 0.999))
        self.epsilon = float(opt_params.get("epsilon", 1e-8))

        self._param_objs: Optional[list] = None
        self._trainable: list = []
        self._aux: list = []
        self._compute_dtype = dtype
        self._step_fns: Dict[Tuple, Any] = {}
        self._opt_state = None
        self._t = 0
        self._base_key = None

    def _collect(self, sample_data=None):
        """Resolve deferred-init params (probe forward) then place on mesh."""
        items = sorted(self.block.collect_params().items())
        if any(p._data is None for _, p in items) and sample_data is not None:
            from ..ndarray.ndarray import from_jax
            from .. import autograd
            import jax.numpy as jnp
            import numpy as _np
            # the probe runs EAGERLY against freshly initialized (default-
            # context) float32 parameters: detach the sample from any
            # device commitment (a staged accelerator batch would clash
            # with CPU-committed params) and cast low precision up so
            # conv dtype checks don't trip
            probe = jnp.asarray(_np.asarray(sample_data))
            if probe.dtype != jnp.float32:
                probe = probe.astype(jnp.float32)
            with autograd.pause():
                self.block._imperative_call(from_jax(probe))
            items = sorted(self.block.collect_params().items())
        self._param_objs = [p for _, p in items]
        self._trainable = [p for p in self._param_objs if p.grad_req != "null"]
        self._aux = [p for p in self._param_objs if p.grad_req == "null"]
        if self.mesh is not None:
            # _place_params shards directly onto the mesh; staging through
            # a single device first would double the transfer and could
            # OOM device 0 for models that only fit sharded
            self._place_params()
        else:
            self._consolidate_params()

    def _consolidate_params(self):
        """Move all parameter buffers onto the default (accelerator)
        backend before the training loop. Eager initialization places
        parameters on the default *context* (mx.cpu() -> the CPU backend
        device, committed); a jit whose arguments are committed to the
        CPU backend runs the whole step ON HOST CPU — measured 300x slower
        than the TPU for the ResNet-50 train step. One explicit
        device_put here pins everything to the accelerator; the step's
        own outputs then stay there."""
        import jax
        arrays = [p._data._data for p in self._param_objs]
        if not arrays:
            return
        dev = jax.devices()[0]
        if all(next(iter(a.devices())) == dev for a in arrays):
            return
        outs = jax.device_put(arrays, dev)
        for p, a in zip(self._param_objs, outs):
            p._data._rebind(a)

    # ------------------------------------------------------------------
    def _place_params(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        repl = NamedSharding(self.mesh, PartitionSpec())
        for p in self._param_objs:
            arr = p._data._data
            if self.plan is not None:
                spec = self.plan.spec_for(p.name, arr.shape)
                sh = NamedSharding(self.mesh, spec)
            else:
                sh = repl
            p._data._rebind(jax.device_put(arr, sh))

    def _init_opt_state(self, train_arrays):
        # one fused program for ALL state buffers (see _consolidate_params:
        # per-buffer eager executions are pathologically slow to re-use on
        # tunneled backends)
        import jax
        import jax.numpy as jnp
        if self.optimizer == "sgd":
            if self.momentum == 0.0:
                return ()
            return jax.jit(
                lambda *xs: tuple(jnp.zeros_like(a) for a in xs)
            )(*train_arrays)
        # adam: (means, vars)
        zeros2 = jax.jit(
            lambda *xs: (tuple(jnp.zeros_like(a) for a in xs),
                         tuple(jnp.zeros_like(a) for a in xs)))
        return zeros2(*train_arrays)

    def _build_step_fn(self):
        """The raw (un-jitted) single-step function
        (train, aux, opt, key, t, data, label) ->
        (loss, new_train, new_aux, new_opt)."""
        import jax
        import jax.numpy as jnp
        from ..ndarray.ndarray import NDArray, from_jax
        from .. import autograd, random as _random

        block = self.block
        loss_fn = self.loss_fn
        trainable = self._trainable
        aux = self._aux
        lr, momentum, wd = self.lr, self.momentum, self.wd
        optimizer = self.optimizer
        beta1, beta2, eps = self.beta1, self.beta2, self.epsilon
        compute_dtype = self._compute_dtype
        # remat knob read at build time, not trace time (graftcheck GC-T03)
        from ..util import mirror_wrapper
        mirror = mirror_wrapper(self.remat)

        def step(train_arrays, aux_arrays, opt_state, key, t, data, label):
            # per-step stream derived on-device from the trainer's base key:
            # fold_in(base, t) makes step() and run_steps() draw IDENTICAL
            # dropout masks for the same step index t
            step_key = jax.random.fold_in(key, t)

            def loss_of(params):
                originals = []
                for p, a in zip(trainable, params):
                    originals.append(p._data._data)
                    # mixed precision: master f32 weights, compute-dtype
                    # replicas inside the graph (grads come back f32)
                    if compute_dtype is not None and \
                            a.dtype == jnp.float32:
                        a = a.astype(compute_dtype)
                    p._data._data = a
                aux_orig = []
                for p, a in zip(aux, aux_arrays):
                    aux_orig.append(p._data._data)
                    p._data._data = a
                _random.push_trace_key(step_key)
                prev_r = autograd.set_recording(False)
                prev_t = autograd.set_training(True)
                try:
                    x = from_jax(data if compute_dtype is None
                                 else data.astype(compute_dtype))
                    out = block._imperative_call(x)
                    loss = loss_fn(out, from_jax(label))
                    loss_val = jnp.mean(loss._data.astype(jnp.float32))
                    # BatchNorm & friends rebind running stats during the
                    # forward; surface them as a has_aux output so the
                    # tracers stay inside the value_and_grad scope.
                    new_aux = tuple(p._data._data for p in aux)
                    return loss_val, new_aux
                finally:
                    autograd.set_training(prev_t)
                    autograd.set_recording(prev_r)
                    _random.pop_trace_key()
                    for p, o in zip(trainable, originals):
                        p._data._data = o
                    for p, o in zip(aux, aux_orig):
                        p._data._data = o

            (loss, new_aux), grads = jax.value_and_grad(
                mirror(loss_of),
                has_aux=True)(tuple(train_arrays))

            new_params = []
            if optimizer == "sgd":
                if momentum == 0.0:
                    for w, g in zip(train_arrays, grads):
                        gw = g.astype(w.dtype)
                        new_params.append(w - lr * (gw + wd * w))
                    new_opt = opt_state
                else:
                    new_mom = []
                    for w, g, m in zip(train_arrays, grads, opt_state):
                        gw = g.astype(w.dtype) + wd * w
                        nm = momentum * m - lr * gw
                        new_mom.append(nm)
                        new_params.append(w + nm)
                    new_opt = tuple(new_mom)
            else:  # adam
                means, vars_ = opt_state
                bc1 = 1 - beta1 ** t
                bc2 = 1 - beta2 ** t
                lr_t = lr * jnp.sqrt(bc2) / bc1
                new_m, new_v = [], []
                for w, g, m, v in zip(train_arrays, grads, means, vars_):
                    gw = g.astype(w.dtype) + wd * w
                    nm = beta1 * m + (1 - beta1) * gw
                    nv = beta2 * v + (1 - beta2) * jnp.square(gw)
                    new_m.append(nm)
                    new_v.append(nv)
                    new_params.append(w - lr_t * nm / (jnp.sqrt(nv) + eps))
                new_opt = (tuple(new_m), tuple(new_v))

            return loss, tuple(new_params), new_aux, new_opt

        return step

    def program_stats(self):
        """XLA cost-model stats of the most recently dispatched fused
        step program: ``{"flops", "bytes_accessed", "argument_bytes",
        "temp_bytes"}``.

        The compiler's own accounting of what the compiled program
        touches — the honest numerator/denominator pair for roofline
        analysis (tools/roofline_ledger.py): achieved FLOP/s vs achieved
        HBM bandwidth. Re-lowers from the recorded ABSTRACT signature
        (donated buffers die with each call), so with a persistent
        compile cache this costs one trace, not a recompile. Single-mesh
        programs only — shardings are not threaded through the abstract
        signature."""
        if getattr(self, "_last_program", None) is None:
            from ..base import MXNetError
            raise MXNetError(
                "program_stats: no fused step program dispatched yet — "
                "call run_steps() first")
        import hashlib

        from ..telemetry import memory as _memory
        from ..telemetry.efficiency import compiled_program_stats
        fn, abstract_args = self._last_program
        comp = fn.lower(*abstract_args).compile()
        # ONE shared cost/memory extraction (telemetry/efficiency.py) —
        # the same parser CachedOp and the grouped optimizer use; the
        # combined stats land in the program registry (kind "spmd") so
        # the fused step ranks in forensics and the cost gauges too
        stats = compiled_program_stats(comp) or {}
        if "flops" not in stats or "argument_bytes" not in stats:
            # the historical behavior failed LOUDLY when a backend
            # reported no analyses — a silent all-zero row would read
            # as "this program is free", the exact opposite of a
            # broken diagnostic
            from ..base import MXNetError
            raise MXNetError(
                "program_stats: this backend reports no "
                f"cost/memory analysis for the compiled step program "
                f"(got fields {sorted(stats)})")
        digest = hashlib.md5(repr(abstract_args).encode()).hexdigest()[:12]
        _memory.record_program(
            "spmd", f"{type(self.block).__name__}:{digest}", dict(stats))
        return {
            "flops": float(stats.get("flops", 0.0)),
            "bytes_accessed": float(stats.get("bytes_accessed", 0.0)),
            "argument_bytes": int(stats.get("argument_bytes", 0)),
            "temp_bytes": int(stats.get("temp_bytes", 0)),
        }

    def _make_step(self, treedef_key):
        import jax
        return jax.jit(self._build_step_fn(), donate_argnums=(0, 1, 2))

    def _make_multi_step(self, treedef_key):
        """K steps fused into ONE XLA program via lax.scan.

        One dispatch per K steps amortizes the per-execution host/relay
        overhead (~100 ms on a tunneled TPU — 27% of a batch-512 ResNet-50
        step) to noise, and lets XLA pipeline the weight-update of step i
        with the forward of step i+1. Each microstep folds the trainer's
        base key with its step index — the same stream step() uses, so the
        trajectories (dropout masks included) are identical."""
        import jax
        from jax import lax
        step = self._build_step_fn()

        def multi(train_arrays, aux_arrays, opt_state, key, t0, datas,
                  labels):
            def body(carry, xs):
                train, aux, opt, t = carry
                d, l = xs
                loss, ntrain, naux, nopt = step(train, aux, opt, key, t,
                                                d, l)
                return (ntrain, naux, nopt, t + 1), loss

            (train, aux, opt, _), losses = lax.scan(
                body, (train_arrays, aux_arrays, opt_state, t0),
                (datas, labels))
            return losses, train, aux, opt

        return jax.jit(multi, donate_argnums=(0, 1, 2))

    # ------------------------------------------------------------------
    def _prepare(self, data, label, batch_dim=0):
        """Shared step preamble: unwrap NDArrays, resolve deferred params,
        align device commitments, shard the batch, gather param/opt arrays
        and the base RNG key. Returns (data, label, train, aux, key)."""
        import jax
        import jax.numpy as jnp
        from .. import random as _random
        from ..ndarray.ndarray import NDArray

        data = data._data if isinstance(data, NDArray) else data
        label = label._data if isinstance(label, NDArray) else label
        if self._param_objs is None:
            self._collect(sample_data=data if batch_dim == 0 else data[0])
        if self.mesh is None:
            # NDArray inputs arrive committed to the default *context*
            # device (CPU); with parameters pinned to the accelerator
            # (_consolidate_params) mixed commitments would error — move
            # batch inputs to the same device. Raw numpy arrays have no
            # commitment yet and are accepted as-is (jit coerces them).
            dev = jax.devices()[0]
            if isinstance(data, jax.Array) and dev not in data.devices():
                data = jax.device_put(data, dev)
            if isinstance(label, jax.Array) and dev not in label.devices():
                label = jax.device_put(label, dev)
        else:
            from .sharding import shard_batch
            data = shard_batch(data, self.mesh, batch_dim=batch_dim)
            label = shard_batch(label, self.mesh, batch_dim=batch_dim)

        train_arrays = tuple(p._data._data for p in self._trainable)
        aux_arrays = tuple(p._data._data for p in self._aux)
        if self._opt_state is None:
            self._opt_state = self._init_opt_state(train_arrays)
        if self._base_key is None:
            # one base key per trainer; every step folds it with its step
            # index t on device. Fetched to host because the eager RNG
            # stream lives on the default *context* (CPU) — a
            # CPU-committed argument would drag the whole jit onto the
            # host backend (see _consolidate_params).
            key = _random.next_key()
            if isinstance(key, jax.Array):
                import numpy as _np
                key = jnp.asarray(_np.asarray(key))
            self._base_key = key
        return data, label, train_arrays, aux_arrays, self._base_key

    def _finish(self, new_params, new_aux, new_opt):
        for p, a in zip(self._trainable, new_params):
            p._data._rebind(a)
        for p, a in zip(self._aux, new_aux):
            p._data._rebind(a)
        self._opt_state = new_opt

    def step(self, data, label):
        """Run one training step; returns the (device) scalar loss."""
        import jax.numpy as jnp
        data, label, train_arrays, aux_arrays, key = self._prepare(
            data, label)
        self._t += 1
        sig = (tuple((a.shape, str(a.dtype)) for a in (data, label)),)
        fn = self._step_fns.get(sig)
        if fn is None:
            fn = self._step_fns[sig] = self._make_step(sig)
        loss, new_params, new_aux, new_opt = fn(
            train_arrays, aux_arrays, self._opt_state, key,
            jnp.asarray(self._t, jnp.int32), data, label)
        self._finish(new_params, new_aux, new_opt)
        return loss

    def run_steps(self, data, label):
        """Run ``K = data.shape[0]`` training steps in ONE fused XLA
        dispatch (lax.scan over microbatches).

        ``data``/``label`` carry a leading steps axis: ``(K, batch, ...)``.
        Returns the ``(K,)`` per-step loss array (still on device — only
        fetch it when you need the values). Produces the same trajectory
        as K calls to :meth:`step` (per-step RNG keys are fold_in(base, t)
        in both paths, so even dropout masks match). Use it when
        per-dispatch host overhead matters (tunneled or remote TPUs) or to
        let XLA overlap the optimizer update of step i with the forward
        of step i+1."""
        import jax.numpy as jnp
        data, label, train_arrays, aux_arrays, key = self._prepare(
            data, label, batch_dim=1)
        k_steps = data.shape[0]
        sig = ("multi", tuple((a.shape, str(a.dtype))
                              for a in (data, label)))
        fn = self._step_fns.get(sig)
        if fn is None:
            fn = self._step_fns[sig] = self._make_multi_step(sig)
        t0 = jnp.asarray(self._t + 1, jnp.int32)
        args = (train_arrays, aux_arrays, self._opt_state, key, t0, data,
                label)
        # abstract signature only (donated buffers die with the call) —
        # program_stats() re-lowers from this
        import jax
        self._last_program = (fn, jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), args))
        losses, new_params, new_aux, new_opt = fn(*args)
        self._t += int(k_steps)
        self._finish(new_params, new_aux, new_opt)
        return losses
