"""Elastic world-size training: preemption-safe resume on a different
topology (ROADMAP open item 1; ref: ps-lite's elastic worker membership,
PAPER.md §KVStore).

Production TPU fleets preempt and *resize*: a run that starts at N ranks
must be able to resume at M. PR 9 already made the hard STATE half
portable — ZeRO-1 checkpoints gather-on-save into the ordinary unsharded
dict and re-derive shards on restore, and ``zero.partition`` is a pure
function of (order, shapes, world) so every new rank re-derives identical
shards for free. This module owns the remaining RUNTIME half:

1. **Topology records** — every checkpoint's ``meta.json`` grows a
   ``topology`` record (:func:`topology_record`): collective world size +
   this rank, the data-shard layout (``num_parts``/``part_index``/
   per-rank batch size), the GLOBAL sample position of the run
   (world-independent: ``local batches × num_parts × batch_size``), and
   whether the trainer states on disk are in the topology-portable
   gather-on-save format.
2. **Detection** — on resume, ``fit.FitLoop`` compares the record
   against :func:`current_topology` *before* any state is loaded
   (``fault.CheckpointManager.restore(meta_check=...)``). A world-size
   change is only honored under ``MXTPU_ELASTIC=on`` (strict parse —
   a typo'd opt-in must not silently resume mis-split), and a
   NON-portable sharded artifact restoring at a different world raises
   :class:`TopologyMismatchError` — never a silent wrong-shard load.
3. **Group re-formation** — a distributed resume re-forms the collective
   group through the jax.distributed coordination-service KV-store path
   (``collectives.cross_process_reform``): every relaunched rank
   publishes a membership record, reads the full roster back, and the
   barrier is the rendezvous — a half-formed group fails loudly at
   resume instead of hanging at the first collective.
4. **Data re-split** — the seeded shuffle order is a pure function of
   (seed, epoch) and the per-rank stream is defined in terms of GLOBAL
   batch indices (``io.NDArrayIter(num_parts=, part_index=)``: local
   batch ``t`` of rank ``r`` is global batch ``t·P + r``), so the saved
   global sample position re-splits exactly across any new rank count:
   each new rank fast-forwards to its own slice with no overlap and no
   gap (:func:`resplit_batches`; union-equality is regression-tested for
   1→2, 2→3 and 4→2).
5. **Fresh comm state** — the resize resets the per-fit comm-health and
   clock-sync state (PR 12's skew tables must not blend topologies: a
   rank index means a different host after the resize).

The chaos grammar grows ``resize@N[:M]`` (contrib/chaos.py): at step N
the run writes a final verified checkpoint whose topology record carries
``resize_to`` and exits with the resumable code — the relaunch harness
resumes it at world M. Acceptance (tests/test_elastic.py +
tests/dist/elastic_worker.py): after the resize point the loss
trajectory matches an always-at-new-size run — bitwise in-process where
the ZeRO parity discipline holds, allclose across real process groups —
with zero duplicated and zero dropped samples across the resize.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ..base import MXNetError, env

__all__ = ["TopologyMismatchError", "elastic_enabled", "current_topology",
           "topology_record", "check_restore", "resplit_batches",
           "reform_group", "reset_comm_state", "world_for_fingerprint",
           "resize_request"]


class TopologyMismatchError(MXNetError):
    """A checkpoint's recorded topology is incompatible with the resuming
    process: the trainer states on disk are rank-sharded (not the
    gather-on-save portable format) and the world size changed, the
    world changed without ``MXTPU_ELASTIC=on``, or the recorded data
    position cannot be re-split across the new rank count. Raised BEFORE
    any parameter or optimizer state is loaded — a topology-incompatible
    artifact must never be silently loaded as the wrong shard."""


def elastic_enabled() -> bool:
    """Strict ``MXTPU_ELASTIC`` parse — a typo'd opt-in must not silently
    resume a resized run mis-split (the MXTPU_ZERO discipline)."""
    raw = str(env.get("MXTPU_ELASTIC") or "").strip().lower()
    if raw in ("", "0", "off", "false"):
        return False
    if raw in ("1", "on", "true"):
        return True
    raise MXNetError(
        f"MXTPU_ELASTIC: unknown value {raw!r} (known: on, off)")


@functools.lru_cache(maxsize=1)
def _resize_counter():
    from ..telemetry import default_registry
    return default_registry().counter(
        "mxtpu_elastic_resizes_total",
        "Elastic resumes honored across a world-size change.")


def _shard_source(data_iter):
    """The iterator actually carrying the shard layout: unwrap the
    common single-base wrapper chains (``DeviceStagingIter._base``,
    ``ResizeIter.data_iter``) until an object exposing ``num_parts`` is
    found — a staged sharded NDArrayIter must not record num_parts=1
    and silently skip the elastic re-split."""
    it, hops = data_iter, 0
    while it is not None and hops < 8:
        if hasattr(it, "num_parts"):
            return it
        it = getattr(it, "_base", None) or getattr(it, "data_iter", None)
        hops += 1
    return data_iter


def current_topology(trainer=None, data_iter=None) -> Dict[str, Any]:
    """The RESUMING process's topology: collective world/rank (a real
    group when the trainer's kvstore spans >1 worker, else the simulated
    ``MXTPU_ZERO_WORLD``, else 1) and the data-shard layout read off the
    iterator (``num_parts``/``part_index``/``batch_size``; 1/0/0 for
    iterators without sharding; wrappers are unwrapped to the sharded
    base). Forces the trainer's lazy kvstore up — a world-size
    comparison against an uninitialized store would read a multi-worker
    resume as world 1."""
    kv = getattr(trainer, "_kvstore", None) if trainer is not None else None
    if kv is None and trainer is not None and \
            getattr(trainer, "_kvstore_arg", None) is not None and \
            not getattr(trainer, "_kv_initialized", True):
        trainer._init_kvstore()
        kv = getattr(trainer, "_kvstore", None)
    world, rank, distributed = 1, 0, False
    nw = int(getattr(kv, "num_workers", 1) or 1)
    if nw > 1:
        world, rank, distributed = nw, int(kv.rank), True
    else:
        from . import zero as _zero
        world = _zero.simulated_world() or 1
    src = _shard_source(data_iter)
    return {
        "world": world,
        "rank": rank,
        "distributed": distributed,
        "num_parts": int(getattr(src, "num_parts", 1) or 1),
        "part_index": int(getattr(src, "part_index", 0) or 0),
        "batch_size": int(getattr(src, "batch_size", 0) or 0),
    }


def topology_record(trainer=None, data_iter=None, batches: int = 0,
                    resize_to: Optional[int] = None) -> Dict[str, Any]:
    """The ``meta.json`` topology record written with every checkpoint.
    ``batches`` is the LOCAL batch count consumed this epoch (FitLoop's
    per-rank counter); the record converts it to the world-independent
    global sample position ``batches × num_parts × batch_size`` — the
    number a resume at ANY rank count re-splits from. ``portable_states``
    marks whether the trainer serializes through the gather-on-save
    topology-portable format (``get_states_bytes``); a record without it
    pins the checkpoint to its birth world."""
    cur = current_topology(trainer, data_iter)
    rec: Dict[str, Any] = dict(cur)
    rec["global_samples"] = (int(batches) * cur["num_parts"] *
                             cur["batch_size"]) \
        if cur["batch_size"] else None
    rec["batches"] = int(batches)
    # True for every checkpoint this framework's Trainer writes (its
    # serialization IS gather-on-save); the guard exists for artifacts
    # from other writers — forged/legacy meta carrying False, or
    # rank-local dumps a foreign tool stamped as sharded. No trainer =
    # no trainer states on disk = nothing shard-shaped to mis-load.
    rec["portable_states"] = bool(
        trainer is None or
        getattr(trainer, "get_states_bytes", None) is not None)
    if resize_to is not None:
        rec["resize_to"] = int(resize_to)
    return rec


def resize_request(meta: Optional[Dict[str, Any]]) -> Optional[int]:
    """The world size a checkpoint ASKS to be resumed at, or None.

    A chaos/operator ``resize@N:M`` run exits resumably after stamping
    ``resize_to: M`` into its final checkpoint's topology record — this
    is the supervisor-facing read of that request (it relaunches the
    group at M instead of the old world). A record without a topology,
    or one whose ``resize_to`` is absent/unparseable, is 'no request'
    (resume at the surviving world) rather than an error: the supervisor
    consumes checkpoints it did not write."""
    if not isinstance(meta, dict):
        return None
    topo = meta.get("topology")
    if not isinstance(topo, dict):
        return None
    rz = topo.get("resize_to")
    try:
        rz = int(rz) if rz is not None else None
    except (TypeError, ValueError):
        return None
    return rz if rz and rz >= 1 else None


def check_restore(topo: Optional[Dict[str, Any]],
                  cur: Dict[str, Any]) -> bool:
    """The restore-time gate (``fault.CheckpointManager.restore``'s
    ``meta_check`` hook runs this BEFORE any state is loaded). Returns
    True when the checkpoint's world differs from the resuming world and
    the resume may proceed elastically; False when the topology is
    unchanged (or unrecorded — legacy checkpoints resume as before).
    Raises :class:`TopologyMismatchError` when the change is one this
    process must not silently honor."""
    if not topo:
        return False
    old_world = int(topo.get("world", cur["world"]))
    if old_world == int(cur["world"]):
        return False
    if not topo.get("portable_states", True):
        raise TopologyMismatchError(
            f"checkpoint was saved at world {old_world} with NON-portable "
            f"(rank-sharded) trainer states; restoring it at world "
            f"{cur['world']} would load the wrong shard. Re-save it "
            "through the gather-on-save path (Trainer.get_states_bytes) "
            "or resume at the original world size.")
    if not elastic_enabled():
        raise TopologyMismatchError(
            f"checkpoint topology is world {old_world} but this process "
            f"is world {cur['world']}; set MXTPU_ELASTIC=on to resume "
            "across a world-size change (or relaunch at the original "
            "size). Refusing to silently resume mis-split.")
    return True


def resplit_batches(topo: Dict[str, Any], cur: Dict[str, Any],
                    restored_batches: int) -> int:
    """LOCAL batches each new rank fast-forwards in the restored epoch.

    The per-rank stream is defined over GLOBAL batch indices (local
    batch ``t`` of rank ``r`` = global batch ``t·P + r``, ``P`` data
    shards), so the union of all ranks' streams is the plain seeded
    (seed, epoch) order whatever ``P`` is. When the shard layout is
    unchanged the restored local count is already correct; otherwise the
    recorded global sample position must split evenly over the new
    ``P × batch_size`` stride — a position mid-global-batch cannot be
    resumed without duplicating or dropping samples, so it raises."""
    old_parts = int(topo.get("num_parts", 1) or 1)
    old_bs = int(topo.get("batch_size", 0) or 0)
    if old_parts == cur["num_parts"] and \
            (not old_bs or old_bs == cur["batch_size"]):
        return int(restored_batches)
    gs = topo.get("global_samples")
    stride = cur["num_parts"] * cur["batch_size"]
    if gs is None or stride <= 0:
        raise TopologyMismatchError(
            "elastic resume: the checkpoint carries no global sample "
            "position (or the resuming iterator has no batch size) — "
            "the data stream cannot be re-split across "
            f"{cur['num_parts']} shard(s).")
    gs = int(gs)
    if gs % stride != 0:
        raise TopologyMismatchError(
            f"elastic resume: global sample position {gs} does not "
            f"split over the new stride {cur['num_parts']} shards x "
            f"{cur['batch_size']} samples = {stride}; resuming would "
            "duplicate or drop samples. Pick a per-rank batch size "
            "whose global batch divides the old one's positions.")
    return gs // stride


def reform_group(cur: Dict[str, Any], tag: str = "") -> Dict[str, Any]:
    """Re-form the collective group after a resize. A real multi-process
    group rendezvouses through the coordination-service KV store
    (``collectives.cross_process_reform``): every rank publishes its
    membership record and reads the roster back — the exchange IS the
    barrier, and a wrong-sized or non-contiguous roster raises here, at
    resume, instead of hanging the first training collective. Simulated
    worlds (one process playing every rank) re-form trivially."""
    if cur["distributed"]:
        from .collectives import cross_process_reform
        roster = cross_process_reform(tag or "elastic",
                                      expect=cur["world"])
        return {"reformed": True,
                "members": [int(m["rank"]) for m in roster]}
    return {"reformed": True, "members": list(range(cur["world"]))}


def reset_comm_state() -> None:
    """Drop the per-fit comm-health and clock-sync state across a resize:
    rank indices mean different hosts after the topology change, so a
    pre-resize skew table or clock offset blended into post-resize
    digests would fabricate stragglers. FitLoop re-runs the clock
    handshake for the new group at its usual fit-start point."""
    from ..telemetry import collective as _coll
    _coll.reset_health()
    _coll.ledger.clock_offset_ms = 0.0
    try:
        from ..telemetry.tracer import tracer as _tr
        _tr.clock_offset_ms = 0.0
    except Exception:
        pass


def begin_resize(topo: Dict[str, Any], cur: Dict[str, Any]) -> Dict[str, Any]:
    """Honor a detected world-size change (``check_restore`` returned
    True): re-form the group, reset the comm planes, count the resize.
    Returns the ``FitResult.elastic`` summary."""
    membership = reform_group(cur, tag=f"rz{topo.get('world')}")
    reset_comm_state()
    try:
        _resize_counter().inc()
    except Exception:
        pass
    return {
        "from_world": int(topo.get("world", 0)),
        "world": int(cur["world"]),
        "rank": int(cur["rank"]),
        "members": membership["members"],
        "resize_to": topo.get("resize_to"),
    }


def world_for_fingerprint() -> int:
    """The world size stamped into the run-report identity fingerprint
    (``telemetry/run_report.py``): the real process count when a
    distributed group exists, else the simulated ZeRO world, else 1 —
    so ``tools/run_compare.py`` can flag a cross-topology comparison
    instead of silently diffing N-rank vs M-rank runs."""
    try:
        import jax
        nproc = int(jax.process_count())
    except Exception:
        nproc = 1
    if nproc > 1:
        return nproc
    try:
        from . import zero as _zero
        return _zero.simulated_world() or 1
    except MXNetError:
        return 1
