"""Parallelism toolkit: mesh, collectives, shardings, SPMD training, ring
attention (SURVEY.md §2.3 — the TPU-native mapping of every reference
communication strategy)."""
from .mesh import (make_mesh, auto_mesh, local_devices, MeshScope,  # noqa
                   current_mesh, axis_size)
from .collectives import (allreduce, allgather, reduce_scatter,  # noqa
                          broadcast, ppermute_ring, all_to_all, barrier,
                          device_allreduce, measure_allreduce_bandwidth)
from .sharding import (P, named_sharding, shard_batch, replicate,  # noqa
                       ShardingPlan, MP_RULES_TRANSFORMER)
from .spmd import SPMDTrainer  # noqa: F401
from .ring_attention import attention, ring_attention  # noqa: F401
from .moe import init_moe_params, moe_param_specs, moe_ffn  # noqa: F401
from .pipeline import pipeline_apply  # noqa: F401
from .embedding_plane import (EmbeddingPlane, row_partition,  # noqa: F401
                              sparse_plane_requested, sparse_max_rows)
