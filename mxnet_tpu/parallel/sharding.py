"""Sharding rules: how logical tensors map onto the mesh.

Replaces the reference's frontend data-parallel plumbing
(_split_input_slice / DataParallelExecutorGroup batch slicing,
python/mxnet/module/executor_group.py:28-56) and the group2ctx model-parallel
placement (src/executor/graph_executor.cc:898-915): instead of slicing at
the python layer, arrays carry NamedShardings and GSPMD splits the program.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError

__all__ = ["P", "named_sharding", "shard_batch", "replicate",
           "ShardingPlan", "MP_RULES_TRANSFORMER"]


def P(*specs):
    from jax.sharding import PartitionSpec
    return PartitionSpec(*specs)


def named_sharding(mesh, *specs):
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec(*specs))


def shard_batch(x, mesh, axis: str = "dp", batch_dim: int = 0):
    """Place a host batch onto the mesh sharded along the batch axis."""
    import jax
    spec = [None] * getattr(x, "ndim", len(x.shape))
    spec[batch_dim] = axis
    data = x._data if hasattr(x, "_data") else x
    return jax.device_put(data, named_sharding(mesh, *spec))


def replicate(tree, mesh):
    """Replicate a pytree of arrays onto every device of the mesh."""
    import jax
    sh = named_sharding(mesh)
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), tree)


class ShardingPlan:
    """Regex name -> PartitionSpec rules (the group2ctx analog: declarative
    placement instead of per-node ctx assignment)."""

    def __init__(self, rules: Sequence[Tuple[str, Any]], default=None):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]
        from jax.sharding import PartitionSpec
        self.default = default if default is not None else PartitionSpec()

    def spec_for(self, name: str, shape: Tuple[int, ...]):
        for pat, spec in self.rules:
            if pat.search(name):
                if len(spec) > len(shape):
                    continue
                return spec
        return self.default

    def shard_params(self, named_arrays: Dict[str, Any], mesh):
        import jax
        from jax.sharding import NamedSharding
        out = {}
        for name, arr in named_arrays.items():
            spec = self.spec_for(name, arr.shape)
            out[name] = jax.device_put(arr, NamedSharding(mesh, spec))
        return out


# Megatron-style tensor-parallel rules for transformer weights:
# column-parallel qkv/up projections, row-parallel out/down projections.
MP_RULES_TRANSFORMER = [
    (r"(wq|wk|wv|w_qkv|query|key|value|up_proj|fc1|ffn_in)", P(None, "tp")),
    (r"(wo|out_proj|down_proj|fc2|ffn_out)", P("tp", None)),
    (r"(embed|embedding|lm_head)", P(None, "tp")),
]
