"""Mesh-sharded embedding tables (sparse/large-embedding parallelism).

Reference analog: the dist kvstore's server-side row_sparse path —
`DataHandleRowSparse` (src/kvstore/kvstore_dist_server.h:331) shards big
tables across server processes and workers pull only active rows
(example/sparse/linear_classification/train.py:32-34).

TPU-native redesign: the table is ONE jax.Array row-sharded over a mesh
axis (NamedSharding P(axis)); there are no server processes. Lookups and
sparse updates run inside the compiled program:

- `lookup` uses a shard_map psum-of-masked-gather: each device gathers the
  requested rows it owns locally and contributes zeros elsewhere; one psum
  over the shard axis assembles the result. Only `ids` (replicated ints)
  and the (batch, dim) result cross the interconnect — never the table.
- gradients: jax differentiates the shard_map, so the backward is the
  mirrored masked scatter-add, again local per shard + no table motion.
- `sgd_update_sparse` applies a row-sparse SGD step fully shard-locally.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

from ..base import MXNetError, check

__all__ = ["ShardedEmbedding", "shard_table", "sharded_lookup",
           "sharded_scatter_add"]


def shard_table(table, mesh, axis: str = "mp"):
    """Place a (rows, dim) table on the mesh, rows sharded over `axis`."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    check(axis in mesh.axis_names, f"mesh has no axis {axis!r}")
    return jax.device_put(table, NamedSharding(mesh, P(axis)))


def _axis_size(mesh, axis):
    return dict(zip(mesh.axis_names, mesh.devices.shape))[axis]


@functools.lru_cache(maxsize=None)
def _lookup_fn(mesh, axis, rows_per_shard):
    """Cached, jitted psum-of-masked-gather (jit identity is stable per
    (mesh, axis, rows/shard) so XLA compiles once per shape; shard_map
    must run under jit on multi-host meshes — see collectives.py)."""
    import jax
    import jax.numpy as jnp
    from .compat import shard_map
    from jax.sharding import PartitionSpec as P

    def body(local_table, ids):
        # local_table: (rows/n, dim) block of this shard; ids replicated
        shard = jax.lax.axis_index(axis)
        base = shard * rows_per_shard
        local = ids - base
        mine = (local >= 0) & (local < rows_per_shard)
        safe = jnp.clip(local, 0, rows_per_shard - 1)
        got = jnp.take(local_table, safe, axis=0)
        contrib = jnp.where(mine[..., None], got, 0)
        return jax.lax.psum(contrib, axis)

    return jax.jit(shard_map(body, mesh=mesh,
                             in_specs=(P(axis, None), P()),
                             out_specs=P(), check_vma=False))


def _replicate_ids(ids, mesh):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    ids = jnp.asarray(ids, jnp.int32)
    if not isinstance(ids, jax.core.Tracer):
        ids = jax.device_put(ids, NamedSharding(mesh, P()))
    return ids


def sharded_lookup(table, ids, mesh, axis: str = "mp"):
    """Gather rows `ids` from a row-sharded table; result replicated.

    Differentiable: the vjp is the mirrored shard-local scatter-add (the
    row-sparse gradient never leaves its shard)."""
    n = _axis_size(mesh, axis)
    check(table.shape[0] % n == 0,
          f"table rows {table.shape[0]} must divide the {axis} axis ({n})")
    return _lookup_fn(mesh, axis, table.shape[0] // n)(
        table, _replicate_ids(ids, mesh))


@functools.lru_cache(maxsize=None)
def _scatter_add_fn(mesh, axis, rows_per_shard):
    import jax
    import jax.numpy as jnp
    from .compat import shard_map
    from jax.sharding import PartitionSpec as P

    def body(local_table, ids, rows):
        shard = jax.lax.axis_index(axis)
        base = shard * rows_per_shard
        local = ids - base
        mine = (local >= 0) & (local < rows_per_shard)
        safe = jnp.where(mine, local, rows_per_shard)  # out-of-range drop
        padded = jnp.concatenate(
            [local_table, jnp.zeros((1,) + local_table.shape[1:],
                                    local_table.dtype)])
        updated = padded.at[safe].add(rows.astype(local_table.dtype))
        return updated[:rows_per_shard]

    return jax.jit(shard_map(body, mesh=mesh,
                             in_specs=(P(axis, None), P(), P()),
                             out_specs=P(axis, None), check_vma=False))


def sharded_scatter_add(table, ids, rows, mesh, axis: str = "mp"):
    """table[ids] += rows, each shard updating only the rows it owns;
    returns the updated (still sharded) table."""
    n = _axis_size(mesh, axis)
    return _scatter_add_fn(mesh, axis, table.shape[0] // n)(
        table, _replicate_ids(ids, mesh), rows)


class ShardedEmbedding:
    """An embedding table living row-sharded across the mesh.

    The TPU-native replacement for a kvstore-served big embedding: the
    table never moves; lookups/updates are compiled collectives.

    >>> emb = ShardedEmbedding(100000, 64, mesh, axis="mp")
    >>> vecs = emb(ids)                       # (batch, 64), differentiable
    >>> emb.sgd_update_sparse(ids, grads, lr) # row-sparse SGD step
    """

    def __init__(self, input_dim: int, output_dim: int, mesh,
                 axis: str = "mp", dtype=None, init_scale: float = 0.01,
                 seed: int = 0):
        import jax
        import jax.numpy as jnp
        n = _axis_size(mesh, axis)
        check(input_dim % n == 0,
              f"input_dim {input_dim} must be divisible by the {axis} "
              f"axis size {n} (pad the vocabulary)")
        self.mesh, self.axis = mesh, axis
        self.input_dim, self.output_dim = input_dim, output_dim
        dtype = dtype or jnp.float32
        w = jax.random.normal(jax.random.PRNGKey(seed),
                              (input_dim, output_dim), dtype) * init_scale
        self.weight = shard_table(w, mesh, axis)

    def __call__(self, ids):
        return sharded_lookup(self.weight, ids, self.mesh, self.axis)

    def lookup(self, ids):
        return self(ids)

    def sgd_update_sparse(self, ids, grad_rows, lr: float) -> None:
        """weight[ids] -= lr * grad_rows, shard-locally."""
        self.weight = sharded_scatter_add(self.weight, ids,
                                          -lr * grad_rows, self.mesh,
                                          self.axis)

    @property
    def shards(self):
        """Per-device addressable shards (proof the table is sharded)."""
        return self.weight.addressable_shards
