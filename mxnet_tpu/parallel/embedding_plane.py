"""Sparse embedding plane: sharded giant-embedding training (ROADMAP 4).

The reference framework treats sparse as first-class — row_sparse NDArray
storage, ``KVStore::PullRowSparse`` moving only touched rows, sparse-aware
optimizers lazily updating only the rows present in the gradient
(src/operator/optimizer_op.cc ``SGDUpdateRspImpl``/``AdamUpdateRspImpl``)
— and its canonical consumer is the giant-embedding recommender: a table
too big for one device, row-sharded across the server fleet
(src/kvstore/kvstore_dist_server.h ``DataHandleRowSparse``), with lookup
traffic at serve time.

This module is that capability rebuilt for the TPU cost model, as the
sparse analog of the ZeRO plane (``parallel/zero.py``):

- **Row-wise table sharding.** The table is partitioned row-wise across
  the world by a pure contiguous derivation (:func:`row_partition`, the
  ``zero.partition`` discipline: every rank and every restart derives
  identical shards from (rows, world) alone). In a real worker group each
  rank holds its shard; in a simulated world (the ``MXTPU_ZERO_WORLD``
  idiom) all shards live in-process, so the whole protocol — including
  the 1/world ledger bytes — is testable on one CPU.
- **Fixed-shape sparse gradients, end-to-end.** Touched ids are deduped
  host-side (``np.unique``), their gradient rows segment-summed on
  device, and the result mask-packed into a ``(max_rows, dim)`` bucket
  (next power of two, capped by ``MXTPU_SPARSE_MAX_ROWS``) with a
  validity mask — so warm steps never retrace on varying touched-row
  counts; the bucket IS the retrace contract. The packed buffer is the
  wire format too: :meth:`KVStoreBase.sparse_plane_exchange` replicates
  it under the same ``_traced_retry`` + ``_chaos_kv`` entry as every
  other collective, and because the exchange is a PURE read, a retried
  ``kv_flake`` replays a read — never a second apply.
- **Row-gathered grouped update.** Each rank's shard steps through
  ``optimizer.grouped.sparse_rows_update`` — the row-gathered variant of
  the fused dense buckets, tracing the SAME per-parameter rule kernels —
  with per-row optimizer state created lazily on the first step that
  touches the rank and co-located with the shard (the ZeRO analog:
  ``state:`` + ``params:`` ledger bytes land at exactly 1/world per
  rank, owners ``emb<r>/<N>:<table>`` / ``state:emb<r>/<N>:<table>``).
- **Sentinel + rollback.** An optional device all-finite verdict guards
  every row write (``where(ok & valid, new, old)``); a skipped step's
  host effects — the update-count bump and any state arrays it first
  materialized — are undone by :meth:`EmbeddingPlane.rollback_step`,
  exactly the ``Trainer.rollback_step`` contract.

The lookup kernel is the ``sharded_embedding`` mp-parity kernel's math
(psum-of-masked-gather) with the psum unrolled over simulated ranks:
each shard contributes its masked gather, the sum assembles the batch.
On a real mesh the table can be served through
``sharded_embedding.sharded_lookup`` unchanged — the shard layout is the
same contiguous row partition.

The plane deliberately lives OUTSIDE ``Trainer._params``: dense towers
train through the Trainer (ZeRO and all), the table trains through the
plane, and the two compose in one loop — the configuration
``parallel/zero.py``'s sparse check points at. Benched end-to-end by the
``recsys`` bench row (bench.py, gated by ``MXTPU_BENCH_RECSYS``) and the
two-tower recipe (``examples/recsys/two_tower.py``); served through
``serving/lookup.py`` from the model registry.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import numpy as _np

from ..base import MXNetError, check, env

__all__ = ["sparse_plane_requested", "sparse_max_rows", "row_partition",
           "row_bucket", "masked_gather", "EmbeddingPlane"]


def _jnp():
    import jax.numpy as jnp
    return jnp


def sparse_plane_requested() -> bool:
    """Strict ``MXTPU_SPARSE_PLANE`` parse — a typo'd opt-in must not
    silently fall back to the dense path (the MXTPU_ZERO discipline)."""
    raw = str(env.get("MXTPU_SPARSE_PLANE") or "").strip().lower()
    if raw in ("", "0", "off", "false"):
        return False
    if raw in ("1", "on", "true"):
        return True
    raise MXNetError(
        f"MXTPU_SPARSE_PLANE: unknown value {raw!r} (known: on, off)")


def sparse_max_rows() -> int:
    """``MXTPU_SPARSE_MAX_ROWS``: the fixed-shape bucket ceiling.
    Unparseable values raise — a typo'd cap silently defaulting would
    change which minibatches are admissible."""
    try:
        n = int(env.get("MXTPU_SPARSE_MAX_ROWS"))
    except (TypeError, ValueError) as e:
        raise MXNetError(
            f"MXTPU_SPARSE_MAX_ROWS: not an integer: "
            f"{env.raw('MXTPU_SPARSE_MAX_ROWS')!r}") from e
    if n < 1:
        raise MXNetError(f"MXTPU_SPARSE_MAX_ROWS must be >= 1, got {n}")
    return n


def row_partition(rows: int, world: int) -> List[Tuple[int, int]]:
    """Contiguous ``[start, stop)`` row range per rank — a pure function
    of (rows, world), the ``zero.partition`` invariant: every rank and
    every restart derives identical shards, so checkpoints and the
    serving artifact are topology-portable by construction."""
    check(world >= 1, "sparse plane world size must be >= 1")
    check(rows % world == 0,
          f"embedding rows {rows} must divide the world {world} "
          "(pad the vocabulary — the contiguous row partition is the "
          "shard-layout invariant)")
    per = rows // world
    return [(r * per, (r + 1) * per) for r in range(world)]


def row_bucket(n: int, cap: Optional[int] = None) -> int:
    """Next power of two >= ``n`` (min 8), capped at ``cap`` (default
    ``MXTPU_SPARSE_MAX_ROWS``) — the ``ops/sparse_ops._nnz_bucket``
    policy applied to touched-row counts. ``n`` above the cap raises:
    the cap IS the retrace contract, raising it recompiles."""
    cap = sparse_max_rows() if cap is None else int(cap)
    if n > cap:
        raise MXNetError(
            f"sparse plane: minibatch touches {n} unique rows, above the "
            f"MXTPU_SPARSE_MAX_ROWS bucket ceiling {cap}; raise the cap "
            "(one recompile per new bucket) or shrink the batch")
    b = 8
    while b < n:
        b <<= 1
    return min(b, cap)


# ---------------------------------------------------------------------------
# Compiled kernels, cached per static shape (the SignatureLRU discipline
# via lru_cache: jit identity stable per bucket, so warm steps replay).
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _gather_fn(world: int, rows_per: int, bucket: int):
    """Psum-of-masked-gather over the shard tuple: each simulated rank
    contributes the rows it owns and zeros elsewhere; the sum assembles
    the batch (``sharded_embedding._lookup_fn`` with the psum unrolled —
    one device, no shard_map needed)."""
    import jax
    jnp = _jnp()

    def fn(shards, ids):
        out = None
        for r, t in enumerate(shards):
            local = ids - r * rows_per
            mine = (local >= 0) & (local < rows_per)
            safe = jnp.clip(local, 0, rows_per - 1)
            got = jnp.take(t, safe, axis=0)
            contrib = jnp.where(mine[:, None], got, 0)
            out = contrib if out is None else out + contrib
        return out
    return jax.jit(fn)


def masked_gather(shards, ids_np, bucket: Optional[int] = None):
    """Gather rows ``ids_np`` from per-rank shard arrays (each
    ``(rows/world, dim)``), padding the id vector to a power-of-two
    bucket (pad id -1 gathers zeros) so lookups never retrace on batch
    size within a bucket. Returns a ``(len(ids), dim)`` jax array.
    Shared with the serving lookup path (``serving/lookup.py``)."""
    jnp = _jnp()
    ids_np = _np.asarray(ids_np, _np.int32).ravel()
    n = int(ids_np.shape[0])
    if bucket is None:
        b = 8
        while b < n:
            b <<= 1
    else:
        b = int(bucket)
        check(b >= n, f"lookup bucket {b} < batch {n}")
    padded = _np.full((b,), -1, _np.int32)
    padded[:n] = ids_np
    rows_per = int(shards[0].shape[0])
    out = _gather_fn(len(shards), rows_per, b)(
        tuple(shards), jnp.asarray(padded))
    return out[:n]


@functools.lru_cache(maxsize=None)
def _pack_fn(batch: int, bucket: int):
    """Segment-sum of ``(batch, dim)`` gradient rows into ``(bucket,
    dim)`` deduped slots (``inv`` from the host-side ``np.unique``):
    duplicate ids within a minibatch accumulate, the reference's
    row-sparse merge semantics. One program per (batch, bucket)."""
    import jax
    jnp = _jnp()

    def fn(grad_rows, inv):
        return jnp.zeros((bucket, grad_rows.shape[1]),
                         grad_rows.dtype).at[inv].add(grad_rows)
    return jax.jit(fn)


class EmbeddingPlane:
    """One row-sharded embedding table + its sharded training protocol.

    >>> opt = mx.optimizer.Adam(learning_rate=0.01)
    >>> plane = EmbeddingPlane("items", rows=4096, dim=32, world=4,
    ...                        optimizer=opt)
    >>> vecs = plane.lookup(ids)            # (batch, dim) NDArray
    >>> ...backward through the dense tower...
    >>> plane.step(ids, vecs.grad())        # sharded row-sparse update

    The optimizer instance must be plane-owned (its update counters and
    lr schedule drive THIS table's bias correction; sharing it with a
    Trainer would double-count steps). Creation raises unless
    ``MXTPU_SPARSE_PLANE=on`` — the grouped dense path's raise names the
    flag, and a typo must not half-opt-in.
    """

    def __init__(self, name: str, rows: int, dim: int, world: int,
                 optimizer, dtype="float32", seed: int = 0,
                 init_scale: float = 0.01, kvstore=None):
        check(sparse_plane_requested(),
              "EmbeddingPlane requires MXTPU_SPARSE_PLANE=on (the "
              "explicit opt-in the grouped dense path's sparse raise "
              "names); refusing to build a sharded table behind a "
              "disabled plane")
        from ..optimizer import grouped as _grouped
        check(_grouped._rule_for(optimizer) is not None,
              f"sparse plane: optimizer {type(optimizer).__name__} has "
              "no grouped-update rule (the plane steps shards through "
              "the row-gathered grouped path)")
        check(not getattr(optimizer, "multi_precision", False) or
              str(dtype) == "float32",
              "sparse plane: multi_precision only composes with an f32 "
              "table (per-row f32 masters are not sharded yet)")
        import jax
        jnp = _jnp()
        self.name = str(name)
        self.rows, self.dim, self.world = int(rows), int(dim), int(world)
        self.parts = row_partition(self.rows, self.world)
        self.rows_per = self.rows // self.world
        self.optimizer = optimizer
        self._opt_index = 0
        self._kv = kvstore
        self._dtype = jnp.dtype(dtype)
        # deterministic full-table init, then the pure contiguous split:
        # plane(world=N).todense() is bitwise plane(world=1).todense(),
        # and bitwise the dense-gather reference's start point
        full = jax.random.normal(
            jax.random.PRNGKey(seed), (self.rows, self.dim),
            self._dtype) * init_scale
        self._shards: List = [full[lo:hi] for lo, hi in self.parts]
        self._state: List[Optional[Tuple]] = [None] * self.world
        self._last_created: List[int] = []
        self._last_stepped = False
        from ..telemetry import memory as _memory
        self._memory = _memory
        for r, s in enumerate(self._shards):
            _memory.track_plane_shard(self.name, r, self.world, s)

    # -- lookup ---------------------------------------------------------
    def lookup(self, ids):
        """Gather the rows of ``ids`` into a ``(batch, dim)`` NDArray
        (attach_grad on it to collect the row-sparse gradient from the
        dense tower's backward)."""
        from ..ndarray import NDArray
        ids_np = _np.asarray(getattr(ids, "asnumpy", lambda: ids)(),
                             _np.int64).ravel()
        check(ids_np.size == 0 or
              (int(ids_np.min()) >= 0 and int(ids_np.max()) < self.rows),
              f"sparse plane {self.name!r}: lookup ids outside "
              f"[0, {self.rows})")
        return NDArray(masked_gather(self._shards, ids_np))

    def todense(self) -> _np.ndarray:
        """The assembled full table (parity tests, serving artifacts)."""
        return _np.concatenate([_np.asarray(s) for s in self._shards])

    # -- training -------------------------------------------------------
    def _ensure_kv(self):
        if self._kv is None:
            from .. import kvstore as _kvs
            self._kv = _kvs.create("device")
        return self._kv

    def _ensure_state(self, r: int) -> bool:
        """Lazily materialize rank ``r``'s row optimizer state (zeros per
        rule slot, shard-shaped: the per-rank state bytes ARE the shard's
        1/world share). Returns True when THIS call created it."""
        if self._state[r] is not None:
            return False
        from ..ndarray import NDArray
        opt = self.optimizer
        st = opt.create_state(self._opt_index, NDArray(self._shards[r]))
        from ..optimizer.grouped import _flatten_inner
        arrs = tuple(s._data for s in _flatten_inner(st))
        self._state[r] = arrs
        self._memory.track_plane_state(self.name, r, self.world, arrs)
        return True

    def step(self, ids, grad_rows, flag=None):
        """One sharded row-sparse update: dedup + pack + exchange, then
        the row-gathered grouped update on every rank whose shard owns a
        touched row. ``grad_rows`` is the ``(batch, dim)`` gradient of
        :meth:`lookup`'s output (NDArray or jax array); ``flag`` an
        optional device all-finite verdict — when it lands False the
        device state is bitwise untouched and the caller rolls the host
        half back with :meth:`rollback_step`."""
        from ..optimizer import grouped as _grouped
        jnp = _jnp()
        opt = self.optimizer
        g = getattr(grad_rows, "_data", grad_rows)
        ids_np = _np.asarray(getattr(ids, "asnumpy", lambda: ids)(),
                             _np.int64).ravel()
        check(g.shape[0] == ids_np.shape[0],
              f"sparse plane {self.name!r}: {ids_np.shape[0]} ids vs "
              f"{g.shape[0]} gradient rows")

        # host half: dedup into the fixed-shape bucket
        uids, inv = _np.unique(ids_np, return_inverse=True)
        bucket = row_bucket(int(uids.shape[0]))
        packed_ids = _np.full((bucket,), -1, _np.int64)
        packed_ids[:uids.shape[0]] = uids
        packed = _pack_fn(int(g.shape[0]), bucket)(
            g, jnp.asarray(inv.astype(_np.int32)))

        # the grad exchange: the union buffer every rank updates from,
        # through the retry/chaos/ledger entry point (PURE — see
        # kvstore.sparse_plane_exchange for the no-double-apply proof)
        packed_ids, packed = self._ensure_kv().sparse_plane_exchange(
            f"embplane:{self.name}", packed_ids, packed)

        # host bookkeeping before any device work, the prepare_update
        # order: count bump, then lr/wd resolution
        opt._update_count(self._opt_index)
        self._last_stepped = True
        lr = opt._get_lr(self._opt_index)
        wd = opt._get_wd(self._opt_index)
        rule = _grouped._rule_for(opt)
        if rule.name == "Adam":
            import math
            t = opt._index_update_count[self._opt_index]
            lr = lr * math.sqrt(1 - opt.beta2 ** t) / (1 - opt.beta1 ** t)

        self._last_created = []
        for r, (lo, hi) in enumerate(self.parts):
            mine = (packed_ids >= lo) & (packed_ids < hi)
            if not bool(mine.any()):
                continue  # lazy: an untouched shard costs nothing
            if self._ensure_state(r):
                self._last_created.append(r)
            local = _np.where(mine, packed_ids - lo, 0).astype(_np.int32)
            idx = jnp.asarray(local)
            valid = jnp.asarray(mine)
            nw, ns = _grouped.sparse_rows_update(
                opt, self._shards[r], self._state[r], packed, idx, valid,
                lr, wd, flag=flag)
            self._shards[r] = nw
            self._state[r] = ns
            self._memory.track_plane_shard(self.name, r, self.world, nw)
            self._memory.track_plane_state(self.name, r, self.world, ns)
        return flag

    def rollback_step(self):
        """Undo the host-side effects of the last (sentinel-skipped)
        step: the update-count bump, and any rank row state that step
        first materialized — with their ledger bytes — so a skipped step
        is indistinguishable from one that never ran (the
        ``Trainer.rollback_step`` contract)."""
        from ..optimizer import grouped as _grouped
        if self._last_stepped:
            _grouped.rollback_counts(self.optimizer, [self._opt_index])
            self._last_stepped = False
        for r in self._last_created:
            self._state[r] = None
            self._memory.drop_plane_state(self.name, r, self.world)
        self._last_created = []

    # -- accounting -----------------------------------------------------
    def rank_bytes(self, rank: int) -> int:
        """This rank's ``params:`` + ``state:`` ledger bytes — the number
        the 1/world acceptance bar pins, queried, not estimated."""
        led = self._memory.ledger()
        own = self._memory.plane_owner
        return (led.live_bytes("params",
                               owner_prefix=own(rank, self.world,
                                                self.name)) +
                led.live_bytes("optimizer",
                               owner_prefix=own(rank, self.world,
                                                self.name, state=True)))

    def describe(self) -> dict:
        return {"name": self.name, "rows": self.rows, "dim": self.dim,
                "world": self.world, "rows_per_rank": self.rows_per,
                "ranks_with_state":
                    sum(1 for s in self._state if s is not None)}

    # -- serving handoff ------------------------------------------------
    def save_npz(self, path: str) -> None:
        """Write the shard set + layout meta (the serving sidecar format
        ``serving/lookup.py`` loads — per-rank arrays, so a replica can
        prove the table it serves is the sharded one)."""
        arrays = {f"shard_{r}": _np.asarray(s)
                  for r, s in enumerate(self._shards)}
        _np.savez(path, meta=_np.array(
            [self.rows, self.dim, self.world], _np.int64), **arrays)

    def close(self) -> None:
        """Drop the plane's ledger entries (tests re-creating planes)."""
        self._memory.drop_plane(self.name)
