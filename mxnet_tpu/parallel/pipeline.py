"""Pipeline parallelism over the 'pp' mesh axis (GPipe microbatch schedule).

The reference has no first-class pipeline parallelism (SURVEY.md §2.3:
closest is PartialForward stepping + the dependency engine's DAG overlap,
include/mxnet/executor.h). The TPU-native design provides it as a real
strategy: layer stacks are sharded over 'pp' (each slice owns a stage) and
microbatches flow through stages via ``lax.ppermute`` inside a
partial-manual ``jax.shard_map`` — the rotation pattern rides neighbor ICI
links, while every other mesh axis (dp/tp/sp/ep) stays under automatic
GSPMD partitioning inside the stage body.

Schedule: classic GPipe fill-drain. With S stages and M microbatches the
scan runs M+S-1 ticks; bubble fraction = (S-1)/(M+S-1), so pick M >= S.
The whole schedule is one ``lax.scan`` => one XLA while-loop, fully
differentiable (ppermute/psum have transpose rules), so fwd+bwd+update
still compile into a single program.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

from ..base import check
from .mesh import axis_size

__all__ = ["pipeline_apply"]


def pipeline_apply(stage_fn: Callable, stage_params: Any, x, mesh,
                   axis: str = "pp", n_microbatches: Optional[int] = None):
    """Run ``x`` through S pipeline stages.

    stage_fn(local_stage_params, x_mb) -> y_mb, shape/dtype preserving.
    stage_params: pytree whose leaves have leading axis S (stage-stacked),
        placed with ``P('pp', ...)`` sharding.
    x: (B, ...) activations (replicated over 'pp'; may be sharded on other
        mesh axes — those stay automatic inside the stage body).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    S = axis_size(mesh, axis)
    if S == 1:
        local = jax.tree_util.tree_map(lambda a: a[0], stage_params)
        return stage_fn(local, x)

    M = int(n_microbatches or S)
    B = x.shape[0]
    check(B % M == 0, f"batch {B} not divisible by {M} microbatches")
    mb = x.reshape(M, B // M, *x.shape[1:])

    def local_fn(sp, mb):
        lp = jax.tree_util.tree_map(lambda a: a[0], sp)  # this stage's slice
        stage = jax.lax.axis_index(axis)
        zero = jnp.zeros_like(mb[0])
        in0 = jnp.where(stage == 0, mb[0], zero)
        outs0 = jnp.zeros_like(mb)
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            in_buf, outs = carry
            y = stage_fn(lp, in_buf)
            y_prev = jax.lax.ppermute(y, axis, perm)
            nxt = jax.lax.dynamic_index_in_dim(
                mb, jnp.clip(t + 1, 0, M - 1), 0, keepdims=False)
            in_next = jnp.where(stage == 0, nxt, y_prev)
            # last stage emits microbatch t-(S-1) at tick t
            oidx = jnp.clip(t - (S - 1), 0, M - 1)
            cur = jax.lax.dynamic_index_in_dim(outs, oidx, 0, keepdims=False)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(t >= S - 1, y, cur), oidx, 0)
            return (in_next, outs), None

        (_, outs), _ = jax.lax.scan(tick, (in0, outs0),
                                    jnp.arange(M + S - 1))
        # only the last stage's buffer is real; broadcast it to all stages
        outs = jnp.where(stage == S - 1, outs, jnp.zeros_like(outs))
        return jax.lax.psum(outs, axis)

    from .compat import shard_map
    f = shard_map(local_fn, mesh=mesh,
                  in_specs=(P(axis), P()), out_specs=P(),
                  axis_names={axis}, check_vma=False)
    y = f(stage_params, mb)
    return y.reshape(B, *y.shape[2:])
