"""jax API compatibility shims for the parallel subsystem.

``shard_map`` graduated from ``jax.experimental.shard_map`` to the
``jax.shard_map`` top-level alias (and renamed ``check_rep`` ->
``check_vma``, ``auto=frozenset`` -> ``axis_names=set``) across jax
releases; the container may carry either vintage. Every in-tree user goes
through :func:`shard_map` here, which presents the NEW calling convention
(``check_vma``/``axis_names``) and translates down when only the
experimental API exists.
"""
from __future__ import annotations

import inspect

from ..base import MXNetError

__all__ = ["shard_map", "HAVE_SHARD_MAP"]


def _resolve():
    import jax
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn, True
    try:
        from jax.experimental.shard_map import shard_map as fn
        return fn, False
    except ImportError:
        return None, False


_FN, _IS_MODERN = _resolve()
HAVE_SHARD_MAP = _FN is not None
# probe the rep-check kwarg name once: a per-call inspect.signature would
# tax every pipeline step for a property of the jax build that never changes
_MODERN_CHECK_KW = None
_MODERN_HAS_AXIS_NAMES = False
if _IS_MODERN:
    _params = inspect.signature(_FN).parameters
    _MODERN_CHECK_KW = ("check_vma" if "check_vma" in _params
                        else "check_rep")
    # the check_rep vintage of the top-level alias also predates
    # axis_names= (it takes auto=) — probe both independently
    _MODERN_HAS_AXIS_NAMES = "axis_names" in _params


def shard_map(f, mesh, in_specs, out_specs, check_vma=None, axis_names=None):
    """``jax.shard_map`` with graceful fallback to the experimental API.

    ``axis_names`` (modern): the subset of mesh axes mapped manually; the
    experimental equivalent is ``auto = all_axes - axis_names``.
    ``check_vma`` (modern) maps to the experimental ``check_rep``.
    """
    if _FN is None:
        raise MXNetError(
            "this jax build provides neither jax.shard_map nor "
            "jax.experimental.shard_map.shard_map — multi-device "
            "shard_map collectives are unavailable")
    kwargs = {}
    if _IS_MODERN:
        if check_vma is not None:
            kwargs[_MODERN_CHECK_KW] = check_vma
        if axis_names is not None:
            if _MODERN_HAS_AXIS_NAMES:
                kwargs["axis_names"] = set(axis_names)
            else:
                kwargs["auto"] = frozenset(mesh.axis_names) - set(axis_names)
        return _FN(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   **kwargs)
    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    if axis_names is not None:
        kwargs["auto"] = frozenset(mesh.axis_names) - set(axis_names)
    return _FN(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               **kwargs)
