"""Device-mesh management: the TPU replacement for device lists.

Reference counterpart: the reference enumerates GPUs into per-device
executors and reduces with kvstore comm trees
(src/kvstore/comm.h, gpu_topology.h). On TPU the topology is the ICI torus
and XLA's collectives already know it, so "topology-aware tree reduction"
(SURVEY.md §2.3) is subsumed: we just declare a ``jax.sharding.Mesh`` and
let GSPMD place collectives on ICI links.

Axis-name conventions used across the framework:
  dp = data parallel, tp = tensor parallel, pp = pipeline stage,
  sp = sequence/context parallel, ep = expert parallel.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError, check

__all__ = ["make_mesh", "auto_mesh", "local_devices", "MeshScope",
           "current_mesh", "axis_size"]

_CURRENT: list = []


def local_devices():
    import jax
    return jax.devices()


def make_mesh(axes: Dict[str, int], devices=None):
    """Build a Mesh from {axis_name: size}. Sizes must multiply to the
    device count (one axis may be -1 = infer)."""
    import jax
    from jax.sharding import Mesh
    devices = list(devices if devices is not None else jax.devices())
    names = list(axes.keys())
    sizes = list(axes.values())
    n = len(devices)
    if -1 in sizes:
        known = int(_np.prod([s for s in sizes if s != -1]))
        check(n % known == 0, f"cannot infer mesh axis: {n} devices, {axes}")
        sizes[sizes.index(-1)] = n // known
    total = int(_np.prod(sizes))
    check(total <= n,
          f"mesh {dict(zip(names, sizes))} needs {total} devices, have {n}")
    dev_array = _np.asarray(devices[:total]).reshape(sizes)
    return Mesh(dev_array, tuple(names))


def auto_mesh(n_devices: Optional[int] = None,
              prefer: Sequence[str] = ("dp", "tp")) -> "jax.sharding.Mesh":
    """Sensible default mesh: split devices between dp and tp (tp innermost
    so tensor-parallel collectives ride the fastest links)."""
    import jax
    devices = jax.devices()
    n = n_devices or len(devices)
    devices = devices[:n]
    tp = 1
    for cand in (4, 2):
        if n % cand == 0 and n // cand >= 1 and len(prefer) > 1:
            tp = cand
            break
    dp = n // tp
    axes = {prefer[0]: dp}
    if len(prefer) > 1:
        axes[prefer[1]] = tp
    return make_mesh(axes, devices)


class MeshScope:
    """Context manager installing a mesh as current."""

    def __init__(self, mesh):
        self.mesh = mesh

    def __enter__(self):
        _CURRENT.append(self.mesh)
        return self.mesh

    def __exit__(self, *a):
        _CURRENT.pop()


def current_mesh():
    return _CURRENT[-1] if _CURRENT else None


def axis_size(mesh, axis: str) -> int:
    return dict(zip(mesh.axis_names, mesh.devices.shape)).get(axis, 1)
