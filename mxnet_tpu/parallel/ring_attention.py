"""Ring attention: sequence/context parallelism over the mesh.

Absent in the reference (SURVEY.md §2.3 marks sequence parallelism as a gap
the TPU design fills "for free"); this provides it as a first-class op: the
sequence axis is sharded across the 'sp' mesh axis, K/V blocks rotate around
the ring with ``lax.ppermute`` while each device accumulates its queries'
attention with a numerically-stable online softmax (blockwise attention, cf.
Liu et al. 2310.01889). Communication overlaps compute: each step's ppermute
rides ICI while the current block's QK^T occupies the MXU.

Also provides all_to_all "Ulysses-style" sequence parallelism
(see collectives.all_to_all) and a plain jax attention for single-device.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

from ..base import MXNetError, check

__all__ = ["attention", "ring_attention"]


def attention(q, k, v, causal: bool = False, scale: Optional[float] = None):
    """Plain softmax attention. q,k,v: (B, T, H, D)."""
    import jax
    import jax.numpy as jnp
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bthd,bshd->bhts", q, k) * scale
    if causal:
        t, s = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((t, s), bool))
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhts,bshd->bthd", probs.astype(v.dtype), v)


def ring_attention(q, k, v, mesh, axis: str = "sp", causal: bool = False,
                   scale: Optional[float] = None):
    """Ring attention over sequence-sharded q,k,v of shape (B, T, H, D).

    Inputs are globally-shaped arrays sharded along T on `axis`; the result
    has the same sharding. The per-device working set is T/n so sequences n×
    longer than single-chip memory fit.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from .compat import shard_map

    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    n = sizes[axis]
    if n == 1:
        return attention(q, k, v, causal=causal, scale=scale)
    sc = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    perm = [(i, (i + 1) % n) for i in range(n)]
    # (B, T, H, D): batch rides dp, sequence rides the ring axis, heads ride
    # tp when present — composes with tensor parallelism transparently.
    spec = P("dp" if "dp" in sizes else None, axis,
             "tp" if "tp" in sizes else None, None)

    def local(qb, kb, vb):
        b, t_loc, h, d = qb.shape
        my = jax.lax.axis_index(axis)
        q32 = qb.astype(jnp.float32)

        def body(i, carry):
            k_cur, v_cur, o, m, l = carry
            src = (my - i) % n  # who produced the block we currently hold
            logits = jnp.einsum("bthd,bshd->bhts", q32,
                                k_cur.astype(jnp.float32)) * sc
            mask = None
            if causal:
                qpos = my * t_loc + jnp.arange(t_loc)
                kpos = src * t_loc + jnp.arange(t_loc)
                mask = (qpos[:, None] >= kpos[None, :])[None, None]
                logits = jnp.where(mask, logits, -1e30)
            m_blk = jnp.max(logits, axis=-1)
            m_new = jnp.maximum(m, m_blk)
            p = jnp.exp(logits - m_new[..., None])
            if mask is not None:
                # kill the exp(0)=1 artifact on fully-masked rows
                p = p * mask.astype(p.dtype)
            corr = jnp.exp(m - m_new)
            l_new = l * corr + jnp.sum(p, axis=-1)
            o_new = o * corr[..., None] + jnp.einsum(
                "bhts,bshd->bthd", p, v_cur.astype(jnp.float32)
            ).transpose(0, 2, 1, 3)
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            return (k_nxt, v_nxt, o_new, m_new, l_new)

        o0 = jnp.zeros((b, h, t_loc, d), jnp.float32)
        m0 = jnp.full((b, h, t_loc), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((b, h, t_loc), jnp.float32)
        _, _, o, m, l = jax.lax.fori_loop(0, n, body, (kb, vb, o0, m0, l0))
        out = o / l[..., None]
        return out.transpose(0, 2, 1, 3).astype(qb.dtype)

    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)
