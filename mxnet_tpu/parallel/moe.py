"""Mixture-of-Experts with expert parallelism (the 'ep' mesh axis).

The reference has no MoE (SURVEY.md §2.3: expert parallelism **Absent**);
this is a capability the TPU-native design adds as a first-class
parallelism strategy. Design is the dense Switch/GShard formulation that
GSPMD shards well:

- expert weights are stacked on a leading E axis and sharded
  ``P('ep', ...)`` — each ep slice owns E/ep experts,
- token dispatch/combine are einsums against a (tokens, E, capacity)
  one-hot dispatch tensor, so the cross-expert exchange lowers to the
  all-to-all-style collectives GSPMD inserts on the ep axis,
- top-1 (Switch) or top-2 (GShard) routing with capacity dropping and the
  standard load-balancing auxiliary loss.

Everything is static-shaped (capacity fixes the per-expert token count) so
the whole layer stays MXU/XLA friendly — no dynamic gather loops.
"""
from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

__all__ = ["init_moe_params", "moe_param_specs", "moe_ffn"]


def init_moe_params(key, d_model: int, d_ff: int, n_experts: int,
                    dtype=None) -> Dict[str, Any]:
    """Stacked expert FFN weights: leading axis = expert."""
    import jax
    import jax.numpy as jnp
    dt = dtype or jnp.float32
    k = jax.random.split(key, 3)
    s = 0.02
    return {
        "gate": (jax.random.normal(k[0], (d_model, n_experts)) * s
                 ).astype(jnp.float32),
        "w_in": (jax.random.normal(k[1], (n_experts, d_model, d_ff)) * s
                 ).astype(dt),
        "b_in": jnp.zeros((n_experts, d_ff), dt),
        "w_out": (jax.random.normal(k[2], (n_experts, d_ff, d_model)) * s
                  ).astype(dt),
        "b_out": jnp.zeros((n_experts, d_model), dt),
    }


def moe_param_specs(mesh) -> Dict[str, Any]:
    """ep-sharded expert stacking; gate replicated. tp (if present) shards
    the expert hidden dim, composing ep x tp."""
    from jax.sharding import PartitionSpec as P
    names = mesh.axis_names if mesh is not None else ()
    ep = "ep" if "ep" in names else None
    tp = "tp" if "tp" in names else None
    return {
        "gate": P(),
        "w_in": P(ep, None, tp),
        "b_in": P(ep, tp),
        "w_out": P(ep, tp, None),
        "b_out": P(ep, None),
    }


def moe_ffn(x, params: Dict[str, Any], n_experts: int,
            capacity_factor: float = 1.25, k: int = 1,
            act=None) -> Tuple[Any, Any]:
    """Apply the expert-parallel FFN.

    x: (B, T, D) -> (out (B, T, D), aux_loss scalar).
    aux_loss is the Switch load-balance loss (mean over tokens of
    fraction_routed * mean_gate_prob, scaled by E); add it to the task
    loss with a small coefficient (~1e-2).
    """
    import jax
    import jax.numpy as jnp
    act = act or jax.nn.gelu
    b, t, d = x.shape
    n = b * t
    e = n_experts
    cap = max(1, int(math.ceil(n * capacity_factor * k / e)))

    xf = x.reshape(n, d)
    scores = xf.astype(jnp.float32) @ params["gate"]          # (N, E)
    probs = jax.nn.softmax(scores, axis=-1)

    dispatch = jnp.zeros((n, e), jnp.float32)
    combine_w = jnp.zeros((n, e), jnp.float32)
    remaining = probs
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)                  # (N,)
        oh = jax.nn.one_hot(idx, e, dtype=jnp.float32)        # (N, E)
        combine_w = combine_w + remaining * oh
        dispatch = dispatch + oh
        remaining = remaining * (1.0 - oh)

    # position of each token within its expert's buffer (per expert-slot)
    pos = jnp.cumsum(dispatch, axis=0) * dispatch             # (N, E), 1-based
    keep = (pos > 0) & (pos <= cap)
    pos0 = jnp.clip(pos - 1.0, 0, cap - 1).astype(jnp.int32)
    slot = jax.nn.one_hot(pos0, cap, dtype=jnp.float32)       # (N, E, C)
    disp = slot * keep[..., None]                             # (N, E, C)

    # load-balance aux loss (Switch eq. 4): E * sum_e f_e * P_e
    frac = jnp.mean(dispatch, axis=0)                         # (E,)
    mean_prob = jnp.mean(probs, axis=0)                       # (E,)
    aux = e * jnp.sum(frac / max(k, 1) * mean_prob)

    # dispatch -> expert compute -> combine (all einsums; ep collectives
    # are inserted by GSPMD from the P('ep',...) weight shardings)
    xe = jnp.einsum("nec,nd->ecd", disp.astype(x.dtype), xf)  # (E, C, D)
    h = act(jnp.einsum("ecd,edf->ecf", xe, params["w_in"])
            + params["b_in"][:, None, :])
    ye = jnp.einsum("ecf,efd->ecd", h, params["w_out"]) \
        + params["b_out"][:, None, :]                         # (E, C, D)
    comb = (disp * combine_w[..., None]).astype(x.dtype)      # (N, E, C)
    out = jnp.einsum("nec,ecd->nd", comb, ye)                 # (N, D)
    return out.reshape(b, t, d), aux
