"""Collective operations over the device mesh.

This is the communication backend that replaces the reference's entire
kvstore comm stack: CommCPU/CommDevice reduction (src/kvstore/comm.h),
NCCL reduce/broadcast (src/kvstore/kvstore_nccl.h), and the ps-lite
push/pull transport (src/kvstore/kvstore_dist.h) all map to XLA collectives
(psum / all_gather / reduce_scatter / ppermute / all_to_all) laid onto the
ICI mesh by GSPMD. DCN between slices is handled by the same primitives via
jax.distributed process groups — same API, different links.
"""
from __future__ import annotations

import functools
from typing import Optional, Sequence

from ..base import MXNetError, check

__all__ = ["allreduce", "allgather", "reduce_scatter", "broadcast",
           "ppermute_ring", "all_to_all", "barrier", "device_allreduce",
           "measure_allreduce_bandwidth", "cross_process_reduce_scatter",
           "cross_process_exchange_bytes", "cross_process_allgather_object",
           "cross_process_reform"]


def _jax():
    import jax
    return jax


# -- CPU-backend cross-process fallback ------------------------------------
# This jaxlib build cannot run multiprocess XLA computations on the CPU
# backend ("Multiprocess computations aren't implemented on the CPU
# backend"), which took out every dist_tpu_sync collective in CPU CI. The
# fallback rides the jax.distributed *coordination service* key-value store
# (the same service the processes already rendezvoused through): each rank
# publishes its buffer, reads its peers', reduces on host, and passes a
# barrier. Functional parity, not bandwidth — the XLA path stays the one
# and only transport on real accelerator backends.

import itertools as _itertools

_coord_seq = _itertools.count()


def _coord_timeout_ms() -> int:
    """``MXTPU_COORD_TIMEOUT_MS``: bound on each blocking coordination-
    service get/barrier hop. A rank whose peer died blocks at most this
    long before the hop raises — under the fleet supervisor this is what
    turns "survivor wedged behind a dead peer" into a bounded, visible
    failure it can act on. Strict parse: an unparseable bound must not
    silently become an unbounded wait."""
    from ..base import env
    try:
        t = int(env.get("MXTPU_COORD_TIMEOUT_MS"))
    except (TypeError, ValueError) as e:
        raise MXNetError(
            f"MXTPU_COORD_TIMEOUT_MS: not an integer: "
            f"{env.raw('MXTPU_COORD_TIMEOUT_MS')!r}") from e
    check(t > 0, f"MXTPU_COORD_TIMEOUT_MS must be > 0, got {t}")
    return t


def _coord_client():
    from jax._src import distributed
    client = distributed.global_state.client
    check(client is not None,
          "cross-process collective without jax.distributed initialized")
    return client


def _use_coord_fallback() -> bool:
    import jax
    return jax.process_count() > 1 and jax.default_backend() == "cpu"


def _coord_exchange(arr, tag: str):
    """Publish this rank's array under ``tag`` and fetch every rank's;
    returns the list indexed by rank. All ranks must call with the SAME
    tag sequence (the usual SPMD collective contract).

    Comm observability: the whole exchange is one collective-ledger
    record, and the peer rank each blocking get is waiting on is stamped
    into it (``note_waiting``) — when a peer never publishes, the hung-
    collective flight recorder names that rank as the absent one."""
    import jax
    import numpy as np
    from ..telemetry import collective as _coll
    client = _coord_client()
    rank, nproc = jax.process_index(), jax.process_count()
    prefix = f"mxtpu_coll/{tag}"
    arr = np.ascontiguousarray(arr)
    tok = _coll.enter("exchange", tag, arr.nbytes, rank) \
        if _coll.enabled() else None
    try:
        client.key_value_set_bytes(f"{prefix}/{rank}", arr.tobytes())
        parts = []
        for r in range(nproc):
            if r == rank:
                parts.append(arr)
                continue
            if tok is not None:
                _coll.note_waiting(tok, r)
            buf = client.blocking_key_value_get_bytes(f"{prefix}/{r}",
                                                      _coord_timeout_ms())
            parts.append(np.frombuffer(bytearray(buf),
                                       arr.dtype).reshape(arr.shape))
        if tok is not None:
            # still a hang point: a peer that dies between publishing
            # and the done-barrier strands us HERE — keep the record
            # truthful instead of clearing the waiting stamp
            _coll.note_waiting(tok, "barrier")
        # everyone has read everything before rank 0 garbage-collects
        # the keys
        client.wait_at_barrier(f"{prefix}/done", _coord_timeout_ms())
        if rank == 0:
            for r in range(nproc):
                try:
                    client.key_value_delete(f"{prefix}/{r}")
                except Exception:
                    pass
        return parts
    finally:
        if tok is not None:
            _coll.exit_(tok)


def allreduce(x, mesh, axis: str = "dp", op: str = "sum"):
    """AllReduce a replicated-per-shard array along a mesh axis using a
    shard_map psum (ref: the kvstore push+pull round trip)."""
    import jax
    from jax.sharding import PartitionSpec as P
    from .compat import shard_map

    def f(v):
        if op == "sum":
            return jax.lax.psum(v, axis)
        if op == "mean":
            return jax.lax.pmean(v, axis)
        if op == "max":
            return jax.lax.pmax(v, axis)
        raise MXNetError(f"unknown reduce op {op}")

    spec = P(*(None,) * x.ndim)
    return shard_map(f, mesh=mesh, in_specs=(spec,), out_specs=spec,
                     check_vma=False)(x)


def make_host_mesh():
    """A 1-D "hosts" mesh with exactly ONE device per process — the
    communication domain for per-process values (dist kvstore). Using all
    devices would make psum overcount by devices-per-process."""
    import jax
    import numpy as _np2
    from jax.sharding import Mesh
    per_proc = {}
    for d in jax.devices():
        per_proc.setdefault(d.process_index, d)
    devs = [per_proc[i] for i in sorted(per_proc)]
    return Mesh(_np2.asarray(devs), ("hosts",))


@functools.lru_cache(maxsize=None)
def _cross_process_fn(mesh, axis, op, ndim):
    """Compiled psum-over-hosts program, cached per (mesh, axis, op,
    rank) so the per-key, per-iteration kvstore push path does not
    re-trace (shapes vary per key but jit caches per shape under one
    function object)."""
    import jax
    from jax.sharding import PartitionSpec as P
    from .compat import shard_map

    def f(v):
        red = {"sum": jax.lax.psum, "mean": jax.lax.pmean,
               "max": jax.lax.pmax}[op]
        return red(v[0], axis)

    # multi-host shard_map must run under jit (eager mode tries to copy
    # the operand to non-addressable devices)
    return jax.jit(shard_map(f, mesh=mesh, in_specs=(P(axis),),
                             out_specs=P(*([None] * ndim)),
                             check_vma=False))


def cross_process_allreduce(local, mesh, axis: str = "hosts",
                            op: str = "sum"):
    """AllReduce of per-PROCESS local values over a one-device-per-process
    mesh (make_host_mesh): the dist kvstore push path — each worker holds
    its own merged gradient; the result is the sum, replicated to every
    worker.

    The local array is lifted into a global array with one shard per
    process on `axis` (jax.make_array_from_process_local_data), psum'd
    with shard_map, and the replicated result is returned as host numpy.
    """
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    nproc = mesh.devices.size
    check(nproc == jax.process_count(),
          f"cross_process_allreduce needs a one-device-per-process mesh "
          f"(make_host_mesh); got {nproc} devices for "
          f"{jax.process_count()} processes")
    if _use_coord_fallback():
        parts = _coord_exchange(np.asarray(local),
                                f"ar{next(_coord_seq)}")
        if op == "sum":
            return sum(parts[1:], parts[0].copy())
        if op == "mean":
            return sum(parts[1:], parts[0].copy()) / len(parts)
        if op == "max":
            return np.maximum.reduce(parts)
        raise MXNetError(f"unknown reduce op {op}")
    local = np.asarray(local)[None]
    gshape = (nproc,) + local.shape[1:]
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(axis)), local, gshape)
    out = _cross_process_fn(mesh, axis, op, local.ndim - 1)(garr)
    # fully replicated -> every process can materialize it
    return np.asarray(out)


def cross_process_allgather(local, mesh, axis: str = "hosts"):
    """AllGather of per-PROCESS local values over a one-device-per-process
    mesh: every worker receives the (nproc, ...) stack. This is the wire
    hop for compressed-gradient push — the payload that crosses DCN is
    whatever dtype/size `local` has (e.g. packed 2-bit codes)."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    nproc = mesh.devices.size
    check(nproc == jax.process_count(),
          f"cross_process_allgather needs a one-device-per-process mesh; "
          f"got {nproc} devices for {jax.process_count()} processes")
    if _use_coord_fallback():
        return np.stack(_coord_exchange(np.asarray(local),
                                        f"ag{next(_coord_seq)}"))
    local = np.asarray(local)[None]
    gshape = (nproc,) + local.shape[1:]
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(axis)), local, gshape)
    out = _cross_process_gather_fn(mesh, axis, local.ndim - 1)(garr)
    return np.asarray(out)


@functools.lru_cache(maxsize=None)
def _cross_process_gather_fn(mesh, axis, ndim):
    import jax
    from jax.sharding import PartitionSpec as P
    from .compat import shard_map

    def f(v):
        return jax.lax.all_gather(v[0], axis)

    return jax.jit(shard_map(f, mesh=mesh, in_specs=(P(axis),),
                             out_specs=P(*([None] * (ndim + 1))),
                             check_vma=False))


def _tile_layout(all_parts, n: int):
    """Rank-major tiled permutation for a ragged reduce-scatter.

    ``all_parts[r]`` is rank r's ``[lo, hi)`` segments of an ``n``-element
    flat buffer (parameter-granular, so per-rank totals differ). A tiled
    ``psum_scatter`` needs EQUAL tiles, so: tile size ``T`` is the max
    per-rank element count, and output slot ``r*T + k`` holds the k-th
    element of rank r's concatenated segments — pad slots point at index
    ``n``, a zero appended by the caller. Returns ``(counts, T, perm)``
    with ``perm`` an int64 index vector of length ``world*T``.

    The padding rule callers gate on: tiled wire cost is ``world*T``
    elements vs the allreduce fallback's ``~2n``; take the tiled path
    only when ``world*T < 2n`` (a single rank owning nearly everything
    would otherwise pad every other rank's tile up to its size and ship
    more bytes than the allreduce it replaces)."""
    import numpy as np
    counts = [sum(hi - lo for lo, hi in ap) for ap in all_parts]
    T = max(counts) if counts else 0
    perm = np.full(len(all_parts) * T, n, dtype=np.int64)
    for r, ap in enumerate(all_parts):
        off = r * T
        for lo, hi in ap:
            perm[off:off + (hi - lo)] = np.arange(lo, hi, dtype=np.int64)
            off += hi - lo
    return counts, T, perm


@functools.lru_cache(maxsize=None)
def _rs_tile_fn(mesh, axis):
    """Compiled tiled ``psum_scatter`` over the hosts mesh: every process
    contributes its rank-major padded wire buffer and keeps ONLY its own
    reduced tile. The input is DONATED — the padded wire buffer is
    transient by construction and dies inside the collective instead of
    living on until the caller's slicing (the buffer-lifetime discipline
    the one-program megastep will inherit)."""
    import jax
    from jax.sharding import PartitionSpec as P
    from .compat import shard_map

    def f(v):
        return jax.lax.psum_scatter(v[0], axis, scatter_dimension=0,
                                    tiled=True)

    return jax.jit(shard_map(f, mesh=mesh, in_specs=(P(axis),),
                             out_specs=P(axis), check_vma=False),
                   donate_argnums=(0,))


def loopback_psum(x, contributions=None):
    """In-graph ``psum`` for a loopback (single-process simulated) group.

    The one-program megastep traces the simulated world's grad reduction
    THROUGH this site so the collective lives structurally inside the
    step program — where a real mesh axis would run ``jax.lax.psum`` /
    ``psum_scatter`` (:func:`_rs_tile_fn`) and XLA would schedule it
    against compute — instead of as a host-driven kvstore transport
    between dispatches. A simulated world plays every rank over shared
    buffers, so there is exactly ONE local contribution and the sum over
    it is the identity: no arithmetic node is emitted (``-0.0 + 0.0``
    would flip sign bits and break the bitwise-parity contract).
    ``contributions`` lets a future multi-contribution loopback (e.g. a
    per-device split) reduce through the same site."""
    parts = [x] if contributions is None else list(contributions)
    out = parts[0]
    for p in parts[1:]:
        out = out + p
    return out


def _coord_segment_reduce(local, all_parts, tag: str):
    """Coordination-service reduce-scatter: each rank publishes, per
    PEER, only the segments that peer owns (one ``{src}to{dst}`` blob per
    pair), then sums the ``{peer}to{me}`` blobs with its own contribution
    — ``~n`` elements cross the wire per rank instead of the full-buffer
    exchange's ``world*n``. Ledger kind is ``reduce_scatter`` (this IS
    one, unlike the allreduce-shaped ``exchange``), with the same
    per-peer waiting stamps and done-barrier as ``_coord_exchange``.
    Returns rank's reduced segments in ``all_parts[rank]`` order."""
    import jax
    import numpy as np
    from ..telemetry import collective as _coll
    client = _coord_client()
    rank, nproc = jax.process_index(), jax.process_count()
    prefix = f"mxtpu_coll/{tag}"
    local = np.ascontiguousarray(local)
    blobs = {d: np.concatenate(
        [local[lo:hi] for lo, hi in all_parts[d]] or
        [local[:0]]) for d in range(nproc)}
    sent = sum(b.nbytes for d, b in blobs.items() if d != rank)
    tok = _coll.enter("reduce_scatter", tag, sent, rank) \
        if _coll.enabled() else None
    try:
        # a rank that owns NOTHING in this bucket has zero-length blobs
        # in both directions — never ship those: a zero-length value
        # through the coordination-service KV hard-crashes the client
        # (observed SIGSEGV in blocking get), and there is nothing to
        # sum anyway. The done-barrier below still syncs every rank.
        for d in range(nproc):
            if d != rank and blobs[d].size:
                client.key_value_set_bytes(f"{prefix}/{rank}to{d}",
                                           blobs[d].tobytes())
        total = blobs[rank].copy()
        if total.size:
            for s in range(nproc):
                if s == rank:
                    continue
                if tok is not None:
                    _coll.note_waiting(tok, s)
                buf = client.blocking_key_value_get_bytes(
                    f"{prefix}/{s}to{rank}", _coord_timeout_ms())
                total = total + np.frombuffer(bytearray(buf), local.dtype)
        if tok is not None:
            _coll.note_waiting(tok, "barrier")  # see _coord_exchange
        client.wait_at_barrier(f"{prefix}/done", _coord_timeout_ms())
        if rank == 0:
            for s in range(nproc):
                for d in range(nproc):
                    if s != d and blobs[d].size:
                        try:
                            client.key_value_delete(f"{prefix}/{s}to{d}")
                        except Exception:
                            pass
        out, off = [], 0
        for lo, hi in all_parts[rank]:
            out.append(total[off:off + (hi - lo)])
            off += hi - lo
        return out
    finally:
        if tok is not None:
            _coll.exit_(tok)


def cross_process_reduce_scatter(local, mesh, parts, axis: str = "hosts",
                                 op: str = "sum", all_parts=None):
    """Reduce per-PROCESS flat buffers element-wise and return only the
    ``[lo, hi)`` slices named by ``parts`` — the ZeRO-1 gradient plane:
    each rank keeps exactly the reduced segments its optimizer shard
    consumes. All ranks must call per the usual SPMD collective contract
    (same buffer shape, each with its own ``parts``).

    ``all_parts`` (rank-indexed list of every rank's segments, identical
    on all callers) unlocks the true reduce-scatter wire cost: the XLA
    path pads each rank's ragged segments to equal ``T``-element tiles
    (rank-major permutation, :func:`_tile_layout`) and runs one tiled
    ``psum_scatter`` whenever ``world*T < 2n`` — below that the padding
    would out-ship the psum+slice fallback, which then still applies.
    The coord fallback (multiprocess CPU) sends each peer only the
    segments it owns (:func:`_coord_segment_reduce`). Without
    ``all_parts`` both paths degrade to the full-buffer form:
    exchange+sum+slice on CPU, psum+slice on XLA."""
    import jax
    import numpy as np
    nproc = mesh.devices.size
    check(nproc == jax.process_count(),
          f"cross_process_reduce_scatter needs a one-device-per-process "
          f"mesh (make_host_mesh); got {nproc} devices for "
          f"{jax.process_count()} processes")
    check(op == "sum", f"unsupported reduce-scatter op {op!r}")
    local = np.asarray(local)
    n = int(local.size)
    if all_parts is not None:
        check(len(all_parts) == nproc,
              f"all_parts covers {len(all_parts)} ranks, world is {nproc}")
        rank = jax.process_index()
        check([tuple(p) for p in parts] ==
              [tuple(p) for p in all_parts[rank]],
              "cross_process_reduce_scatter: parts != all_parts[rank] — "
              "the caller's own segments must match the shared layout")
    if _use_coord_fallback():
        if all_parts is not None:
            return _coord_segment_reduce(local, all_parts,
                                         f"rs{next(_coord_seq)}")
        bufs = _coord_exchange(local, f"rs{next(_coord_seq)}")
        total = bufs[0].copy()
        for b in bufs[1:]:
            total = total + b
        return [total[lo:hi] for lo, hi in parts]
    if all_parts is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        counts, T, perm = _tile_layout(all_parts, n)
        if T > 0 and nproc * T < 2 * n:
            padded = np.concatenate([local, np.zeros(1, local.dtype)])
            wire = np.ascontiguousarray(padded[perm])[None]
            garr = jax.make_array_from_process_local_data(
                NamedSharding(mesh, P(axis)), wire, (nproc, nproc * T))
            out = _rs_tile_fn(mesh, axis)(garr)
            tile = np.asarray(out.addressable_shards[0].data)
            rank = jax.process_index()
            res, off = [], 0
            for lo, hi in parts:
                res.append(tile[off:off + (hi - lo)])
                off += hi - lo
            return res
    full = cross_process_allreduce(local, mesh, axis=axis, op=op)
    return [np.asarray(full[lo:hi]) for lo, hi in parts]


def cross_process_exchange_bytes(payload: bytes, tag: str):
    """Publish this rank's byte payload under ``tag`` and fetch every
    rank's (rank-indexed list). Rides the jax.distributed coordination-
    service KV store — the transport for RAGGED payloads (pickled
    optimizer-state shards, per-rank weight segments) that the
    fixed-shape array collectives cannot carry. Same contract as
    :func:`_coord_exchange`: all ranks call with the same tag sequence.
    Records into the collective ledger with per-peer waiting notes, like
    ``_coord_exchange`` — this hop is where a surviving rank blocks when
    a peer dies, so the flight recorder must see it."""
    import jax
    from ..telemetry import collective as _coll
    client = _coord_client()
    rank, nproc = jax.process_index(), jax.process_count()
    prefix = f"mxtpu_coll/{tag}"
    tok = _coll.enter("exchange_bytes", tag, len(payload), rank) \
        if _coll.enabled() else None
    try:
        client.key_value_set_bytes(f"{prefix}/{rank}", payload)
        outs = []
        for r in range(nproc):
            if r == rank:
                outs.append(payload)
                continue
            if tok is not None:
                _coll.note_waiting(tok, r)
            outs.append(bytes(client.blocking_key_value_get_bytes(
                f"{prefix}/{r}", _coord_timeout_ms())))
        if tok is not None:
            _coll.note_waiting(tok, "barrier")  # see _coord_exchange
        client.wait_at_barrier(f"{prefix}/done", _coord_timeout_ms())
        if rank == 0:
            for r in range(nproc):
                try:
                    client.key_value_delete(f"{prefix}/{r}")
                except Exception:
                    pass
        return outs
    finally:
        if tok is not None:
            _coll.exit_(tok)


def cross_process_allgather_object(obj, tag_prefix: str = "obj"):
    """Ragged allgather of one picklable object per rank (rank-indexed
    list) over the coordination-service byte channel — the ZeRO-1 weight
    allgather hop (per-rank segment sizes differ, so the tiled XLA
    all_gather cannot carry them)."""
    import pickle
    blobs = cross_process_exchange_bytes(
        pickle.dumps(obj), f"{tag_prefix}{next(_coord_seq)}")
    return [pickle.loads(b) for b in blobs]


def cross_process_reform(tag: str, expect: Optional[int] = None):
    """Membership rendezvous for elastic resume (``parallel/elastic.py``):
    every process publishes a ``{rank, pid, host}`` record through the
    jax.distributed coordination-service KV store and reads the full
    roster back — the exchange's barrier IS the group re-formation, the
    same KV-store path every CPU-backend collective already rides (and
    the ps-lite elastic-membership analog, PAPER.md §KVStore). Returns
    the roster sorted by rank. A member that never launched blocks the
    exchange until its bounded get times out — that is the transport's
    own failure mode, and ranks are ``jax.process_index()`` over
    ``process_count()``, so a completed exchange is contiguous by
    construction. What this call ADDS is the ``expect`` validation: a
    group re-formed at the wrong size (checkpoint world vs live process
    count drift) must fail loudly at resume, not at the first training
    collective."""
    import os
    import socket
    import jax
    rec = {"rank": int(jax.process_index()), "pid": os.getpid(),
           "host": socket.gethostname()}
    roster = cross_process_allgather_object(rec, tag_prefix=f"rf_{tag}_")
    if expect is not None:
        check(len(roster) == int(expect),
              f"cross_process_reform: {len(roster)} member(s) joined but "
              f"the resume expects world {expect}")
    return sorted(roster, key=lambda m: int(m["rank"]))


def device_allreduce(arrays, mesh, axis: str = "dp", op: str = "sum"):
    """Fused allreduce of a list of arrays (one compiled program for the
    whole gradient bucket, like the reference's grouped NCCL launches,
    kvstore_nccl.h:270-296)."""
    import jax
    from jax.sharding import PartitionSpec as P
    from .compat import shard_map

    specs = tuple(P(*(None,) * a.ndim) for a in arrays)

    def f(*vs):
        red = jax.lax.psum if op == "sum" else jax.lax.pmean
        return tuple(red(v, axis) for v in vs)

    return shard_map(f, mesh=mesh, in_specs=specs, out_specs=specs,
                     check_vma=False)(*arrays)


def allgather(x, mesh, axis: str = "dp", tiled_axis: int = 0):
    import jax
    from jax.sharding import PartitionSpec as P
    from .compat import shard_map

    in_spec = [None] * x.ndim
    in_spec[tiled_axis] = axis
    def f(v):
        return jax.lax.all_gather(v, axis, axis=tiled_axis, tiled=True)
    return shard_map(f, mesh=mesh, in_specs=(P(*in_spec),),
                     out_specs=P(*([None] * x.ndim)), check_vma=False)(x)


def reduce_scatter(x, mesh, axis: str = "dp", scatter_axis: int = 0):
    import jax
    from jax.sharding import PartitionSpec as P
    from .compat import shard_map

    out_spec = [None] * x.ndim
    out_spec[scatter_axis] = axis
    def f(v):
        return jax.lax.psum_scatter(v, axis, scatter_dimension=scatter_axis,
                                    tiled=True)
    return shard_map(f, mesh=mesh, in_specs=(P(*([None] * x.ndim)),),
                     out_specs=P(*out_spec), check_vma=False)(x)


def broadcast(x, mesh, axis: str = "dp", root: int = 0):
    """Broadcast shard `root`'s value to all (ref: kvstore pull)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from .compat import shard_map

    def f(v):
        idx = jax.lax.axis_index(axis)
        masked = jnp.where(idx == root, v, jnp.zeros_like(v))
        return jax.lax.psum(masked, axis)

    spec = P(*(None,) * x.ndim)
    return shard_map(f, mesh=mesh, in_specs=(spec,), out_specs=spec,
                     check_vma=False)(x)


def ppermute_ring(x, mesh, axis: str = "sp", shift: int = 1):
    """Ring rotation along an axis — the building block of ring attention."""
    import jax
    from jax.sharding import PartitionSpec as P
    from .compat import shard_map

    n = dict(zip(mesh.axis_names, mesh.devices.shape))[axis]
    perm = [(i, (i + shift) % n) for i in range(n)]
    in_spec = [axis] + [None] * (x.ndim - 1)

    def f(v):
        return jax.lax.ppermute(v, axis, perm)

    return shard_map(f, mesh=mesh, in_specs=(P(*in_spec),),
                     out_specs=P(*in_spec), check_vma=False)(x)


def all_to_all(x, mesh, axis: str = "sp", split_axis: int = 1,
               concat_axis: int = 0):
    """DeepSpeed-Ulysses style axis exchange for sequence parallelism."""
    import jax
    from jax.sharding import PartitionSpec as P
    from .compat import shard_map

    in_spec = [None] * x.ndim
    in_spec[concat_axis] = axis
    out_spec = [None] * x.ndim
    out_spec[split_axis] = axis

    def f(v):
        return jax.lax.all_to_all(v, axis, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)

    return shard_map(f, mesh=mesh, in_specs=(P(*in_spec),),
                     out_specs=P(*out_spec), check_vma=False)(x)


def barrier(mesh=None) -> None:
    """Global sync point (ref: ps::Postoffice::Barrier). Single-process:
    drain the dispatch queue."""
    import jax
    if mesh is None:
        (jax.device_put(0) + 0).block_until_ready()
        return
    if jax.process_count() > 1:
        if _use_coord_fallback():
            from ..telemetry import collective as _coll
            tag = f"bar{next(_coord_seq)}"
            tok = _coll.enter("barrier", tag, 0, jax.process_index()) \
                if _coll.enabled() else None
            try:
                if tok is not None:
                    _coll.note_waiting(tok, "all")
                _coord_client().wait_at_barrier(
                    f"mxtpu_coll/{tag}", _coord_timeout_ms())
            finally:
                if tok is not None:
                    _coll.exit_(tok)
            return
        import numpy as np
        # the collective itself is the rendezvous
        cross_process_allreduce(np.zeros((), np.float32), mesh,
                                axis=mesh.axis_names[0])
        return
    import jax.numpy as jnp
    allreduce(jnp.zeros(()), mesh, axis=mesh.axis_names[0]).block_until_ready()


def measure_allreduce_bandwidth(mesh, size_mb: float = 64.0,
                                axis: str = "dp", iters: int = 10,
                                shapes=None):
    """Allreduce bandwidth in GB/s/device with the reference's formula
    ``2(n-1)/n * size / t`` (ref: tools/bandwidth/measure.py:138).

    ``shapes``: allreduce one buffer per shape in a single fused program
    (the model-gradient-shaped workload of measure.py's real-model mode)
    instead of one flat ``size_mb`` tensor."""
    import time
    import jax
    import jax.numpy as jnp

    n = dict(zip(mesh.axis_names, mesh.devices.shape))[axis]
    if shapes is None:
        arrays = [jnp.ones((int(size_mb * 1e6 / 4),), jnp.float32)]
    else:
        arrays = [jnp.ones(s, jnp.float32) for s in shapes]
    total_bytes = sum(a.nbytes for a in arrays)
    f = jax.jit(lambda *vs: device_allreduce(list(vs), mesh, axis=axis))
    jax.block_until_ready(f(*arrays))  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f(*arrays)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    bw = 2 * (n - 1) / n * total_bytes / dt / 1e9
    return bw
