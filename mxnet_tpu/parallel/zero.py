"""ZeRO-1 sharded optimizer state (Rajbhandari et al., PAPERS.md).

Partition the optimizer state (momentum/mean/var and f32 ``multi_precision``
masters) across the data-parallel ranks so each rank materializes only its
~1/N slice — the memory lever that unlocks larger models per device for
Adam-class optimizers, with **no math change**. The reference framework
reaches the same end through the KVStore server owning the update
(PAPER.md §KVStore ``update_on_kvstore``: each server shard updates only
its keys); here the "server shard" is a rank of the collective group.

The plane rides the substrate earlier subsystems built, per step:

1. **reduce-scatter** — ``Trainer.allreduce_grads`` flattens dense
   gradients into the SAME forward-order ``_gbkt*`` flat wire buffers the
   bucketed allreduce uses (one layout whichever path runs), but issues
   ``KVStore.zero_reduce_scatter`` per bucket instead of push+pull: the
   reduced buffer lands only as the parameter-aligned slices this rank's
   shard consumes. Per-bucket retry/chaos hooks (``kv_flake``/``kv_slow``)
   wrap each call exactly like push/pull — the op is pure (no store
   mutation), so a retried flake can never double-apply a shard update.
2. **shard update** — the existing grouped one-program-per-bucket donated
   path (``optimizer/grouped.py``) steps ONLY the local shard's
   parameters, so optimizer state (and mp masters) is created 1/N per
   rank. The fused finiteness sentinel is made *globally* correct first:
   each rank reduces its shard's (already cross-rank-reduced) gradients
   to a local all-finite flag, the flags are AND-reduced across ranks
   (``KVStore.zero_all_finite``) BEFORE any shard applies, and the one
   global verdict where()-guards every rank's update — a NaN anywhere
   skips the step everywhere, and ``Trainer.rollback_step`` rolls back
   shard-local host state only.
3. **allgather** — each rank ships its shard's updated weight segments
   per bucket (``KVStore.zero_allgather``); every rank reassembles the
   full parameter set from the deterministic partition map.

**Partitioning** is parameter-granular and a pure function of
(parameter order, shapes, dtypes, world size): greedy byte-balancing in
index order, ties to the lowest rank. Every rank — and every restart —
derives the same shards, which is what keeps checkpoints
**topology-portable**: saves gather the shards back into the ordinary
unsharded state dict (``gather_states_bytes``), restores load the full
dict and re-derive the local shard view (``local_indices`` pruning). A
ZeRO checkpoint restores into an unsharded run and vice versa.

**World size**: a real collective group (``kvstore.num_workers > 1``)
shards across its ranks. A single-worker run can *simulate* N ranks with
``MXTPU_ZERO_WORLD=N``: this process plays every rank in sequence —
partitioning, shard-aware ledger attribution, the collective call
pattern and the trajectory are all exactly the N-rank protocol, so the
parity/chaos/memory suites run it tier-1 on one CPU process.

Deliberate non-compositions (raise, never silently degrade): gradient
compression (per-key error-feedback residuals assume the allreduce
layout; checked at plane creation AND per comm round), non-grouped
optimizers and sparse parameters (the shard update IS the grouped
path), aggregation off, and a bare ``update()`` with no preceding
reduce-scatter.

**Comm/compute overlap** (``MXTPU_COMM_OVERLAP=on``) composes with the
plane instead of being superseded: the backward half launches each
bucket's reduce-scatter at grad finality through the same autograd
callback the dense overlap scheduler uses (``Trainer.overlap_scope``;
grad rebinds deferred to finalize — autograd may still read the live
buffers), and the update half launches each bucket's weight allgather
the moment that bucket's shard updates land, while the tail buckets are
still updating (the ``DeviceStagingIter`` staging idiom applied to
weights). Same buckets, same sums, same collective count — only the
launch points move, and the moved time is charged to the
``comm_overlapped`` step-breakdown segment instead of exposed ``comm``.
Distributed runs defer the non-local weight rebinds of a prefetched
allgather: each in-flight parameter carries a pending-fetch hook that
the next ``Parameter.data()`` read completes (first touch completes the
whole bucket), with ``flush_pending`` as the barrier of last resort
before the next comm round.

**Tiled reduce-scatter padding rule** (the XLA transport,
``parallel/collectives.py``): buckets are parameter-granular and ragged
— per-rank segment totals differ — while ``psum_scatter`` needs equal
tiles. So the wire buffer is permuted rank-major and each rank's
segments padded to ``T = max`` per-rank element count; one tiled
``psum_scatter`` then delivers each rank exactly its (padded) tile, and
the pad tail is sliced off. The tiled path is taken only when
``world*T < 2n`` (n = bucket elements): beyond that the padding would
out-ship the allreduce+slice fallback it replaces — a bucket whose
bytes all belong to one rank pads every other rank's tile up to its
size. The multiprocess-CPU coord fallback sends each peer only the
segments it owns instead (per-pair blobs, ledger kind
``reduce_scatter``), never the full-buffer exchange.

Distributed-group contracts (simulated worlds are exempt — every grad
is fully reduced locally there):

- Between ``allreduce_grads()`` and ``update()``, only THIS rank's
  shard gradients hold globally-reduced values; code that reads or
  rescales the full gradient set in that window (global-norm clipping,
  custom grad hooks) would mix reduced and unreduced values and must
  run unsharded instead.
- Checkpoint saves are COLLECTIVE (gather-on-save): every rank must
  call ``save_states``/``CheckpointManager.save`` at the same step —
  ``fit.FitLoop`` already does; a rank-0-only save stalls waiting for
  shards that never arrive.

Observability: all three plane collectives (reduce-scatter, allgather,
the all-finite flag) record into the cross-rank collective ledger
(``telemetry/collective.py``) through the same kvstore entry points the
chaos/retry hooks ride — so ``MXTPU_COLL_HEALTH`` skew/desync detection
covers the sharded comm plane, a rank hung in ``zero_all_finite`` while
its peers block is named by the ``MXTPU_COLL_TIMEOUT_S`` flight
recorder, and the ``kv_hang`` chaos event drives both on CPU.
"""
from __future__ import annotations

import functools
import itertools
import pickle
from typing import Dict, List, Set

import numpy as _np

from ..base import MXNetError, check, env

__all__ = ["zero_requested", "simulated_world", "partition", "ZeroPlane"]

_save_seq = itertools.count()


def zero_requested() -> bool:
    """Strict ``MXTPU_ZERO`` parse — a typo'd request to shard must not
    silently train unsharded (the MXTPU_COMM_OVERLAP discipline)."""
    raw = str(env.get("MXTPU_ZERO") or "").strip().lower()
    if raw in ("", "0", "off", "false"):
        return False
    if raw in ("1", "on", "true"):
        return True
    raise MXNetError(
        f"MXTPU_ZERO: unknown value {raw!r} (known: on, off)")


def simulated_world() -> int:
    """``MXTPU_ZERO_WORLD``: simulated rank count for single-worker runs
    (0/1 = no simulation; ignored when a real multi-worker group exists).
    Unparseable values raise — a typo'd world size silently collapsing to
    1 would make the whole suite 'shard' across nothing."""
    try:
        n = int(env.get("MXTPU_ZERO_WORLD"))
    except TypeError:  # absent -> default 0
        n = 0
    except ValueError as e:  # declared int: env.get coerces and raises
        raise MXNetError(
            f"MXTPU_ZERO_WORLD: not an integer: "
            f"{env.raw('MXTPU_ZERO_WORLD')!r}") from e
    if n < 0:
        raise MXNetError(f"MXTPU_ZERO_WORLD must be >= 0, got {n}")
    return n


def _param_bytes(p) -> int:
    n = 1
    for s in (p.shape or ()):
        n *= int(s)
    try:
        itemsize = _np.dtype(p.dtype).itemsize
    except TypeError:
        itemsize = 4
    return n * itemsize


def partition(params, world: int) -> List[int]:
    """Owner rank per parameter index: greedy byte-balancing in parameter
    order (each param goes to the currently-lightest rank, ties to the
    lowest). A pure function of (order, shapes, dtypes, world), so every
    rank and every restart derives identical shards — the invariant
    topology-portable checkpoints and the allgather reassembly rely on."""
    check(world >= 1, "ZeRO world size must be >= 1")
    loads = [0] * world
    owners = []
    for p in params:
        r = min(range(world), key=lambda k: (loads[k], k))
        owners.append(r)
        loads[r] += _param_bytes(p)
    return owners


@functools.lru_cache(maxsize=1)
def _rs_counter():
    from ..telemetry import default_registry
    return default_registry().counter(
        "mxtpu_zero_reduce_scatter_collectives_total",
        "ZeRO-1 per-bucket gradient reduce-scatter collectives issued.")


@functools.lru_cache(maxsize=1)
def _ag_counter():
    from ..telemetry import default_registry
    return default_registry().counter(
        "mxtpu_zero_allgather_collectives_total",
        "ZeRO-1 per-bucket weight allgather collectives issued.")


class ZeroPlane:
    """The per-Trainer ZeRO-1 subsystem: partition map + the
    reduce-scatter / shard-update bookkeeping / allgather protocol.

    Created lazily by the Trainer at first use (``MXTPU_ZERO=1``); every
    non-composable configuration raises HERE, at creation, instead of
    training unsharded behind the operator's back.
    """

    def __init__(self, trainer):
        kv = trainer._kvstore
        check(kv is not None,
              "MXTPU_ZERO=1 requires a kvstore (pass an explicit store "
              "object — the default 'device' string degrades to no store "
              "on a 1-device host); refusing to silently train unsharded")
        check(getattr(kv, "_compressor", None) is None,
              "MXTPU_ZERO=1 does not compose with gradient compression: "
              "per-key error-feedback residuals assume the allreduce "
              "wire layout, not reduce-scatter slices")
        from ..optimizer import grouped as _grouped
        check(_grouped.aggregation_size() > 0,
              "MXTPU_ZERO=1 requires MXTPU_OPTIMIZER_AGGREGATION > 0: the "
              "shard update IS the grouped donated-buffer path")
        updater = trainer._updaters[0]
        check(_grouped._rule_for(updater.optimizer) is not None,
              f"MXTPU_ZERO=1: optimizer "
              f"{type(updater.optimizer).__name__} has no grouped-update "
              "rule (ZeRO-1 shards state through the grouped path)")
        for p in trainer._params:
            check(p.stype == "default" and
                  getattr(p, "grad_stype", "default") == "default",
                  f"MXTPU_ZERO=1 requires dense parameters/gradients; "
                  f"{p.name!r} is sparse. Sparse tables shard through "
                  "the row-wise embedding plane instead "
                  "(MXTPU_SPARSE_PLANE=on + parallel.embedding_plane."
                  "EmbeddingPlane, state co-located with each rank's "
                  "rows): keep the table OUT of the Trainer and the two "
                  "planes compose in one loop — dense params ZeRO-"
                  "sharded, embedding rows plane-sharded")
        self._kv = kv
        nw = int(kv.num_workers)
        if nw > 1:
            self.world, self.my_ranks = nw, (int(kv.rank),)
            self.distributed = True
        else:
            self.world = simulated_world() or 1
            self.my_ranks = tuple(range(self.world))
            self.distributed = False
        self.owners = partition(trainer._params, self.world)
        self._my_set: Set[int] = {i for i, r in enumerate(self.owners)
                                  if r in set(self.my_ranks)}
        # (key, bucket) layout of the current comm round, computed once
        # by reduce_scatter_grads and consumed by allgather_weights — the
        # two halves can never disagree on layout, and the hot path pays
        # the bucket walk + key digest once per step
        self._step_layout = None
        # prefetched-allgather completions still owed (distributed runs
        # only; simulation finishes inline) — drained by flush_pending
        # and lazily by Parameter.data()
        self._pending_ag: List = []
        # shard-aware ledger attribution: telemetry/memory tags this
        # updater's optimizer/masters entries with the owning rank
        # (owner 'state:zr<r>/<N>:<param>'), so per-rank bytes are a
        # queryable — and test-enforceable — number
        updater._zero_shard = {i: f"{r}/{self.world}"
                               for i, r in enumerate(self.owners)}
        try:
            from ..telemetry import default_registry
            reg = default_registry()
            reg.gauge("mxtpu_zero_world_size",
                      "ZeRO-1 world size (ranks the optimizer state is "
                      "sharded across; 0 = ZeRO off).").set(self.world)
            reg.gauge("mxtpu_zero_shard_params",
                      "Parameters owned by this rank's ZeRO-1 shard "
                      "(rank my_ranks[0]).").set(
                sum(1 for r in self.owners if r == self.my_ranks[0]))
        except Exception:
            pass

    # -- membership ------------------------------------------------------
    def owner(self, index: int) -> int:
        return self.owners[index]

    def local_indices(self) -> Set[int]:
        """Parameter indices whose optimizer state lives on this process
        (one rank's worth when distributed; every rank's in simulation)."""
        return self._my_set

    def describe(self) -> Dict:
        mine = sorted(self._my_set)
        return {"world": self.world,
                "ranks": list(self.my_ranks),
                "distributed": self.distributed,
                "params": len(self.owners),
                "shard_params": len(mine)}

    def _bucket_layout(self, trainer):
        """The comm round's (key, bucket) list: the SAME forward-order
        ``_gbkt*`` layout the allreduce path builds (``bucket_mb == 0``
        degrades to singleton buckets — the per-key scheduling analog)."""
        items = []
        for i, p in enumerate(trainer._params):
            if p.grad_req == "null" or p._grad is None:
                continue
            items.append((i, p.grad()))
        buckets = trainer._grad_buckets(items, trainer._bucket_mb()) \
            if items else []
        return [(trainer._bucket_sig_key(bid, b)[1], b)
                for bid, b in enumerate(buckets)]

    def check_comm_round(self) -> None:
        """Per-round composability re-check: compression can be enabled
        after the plane came up, and must fail the round loudly."""
        check(getattr(self._kv, "_compressor", None) is None,
              "MXTPU_ZERO=1 does not compose with gradient compression "
              "(enabled after the first step): per-key error-feedback "
              "residuals assume the allreduce wire layout")

    def overlap_active(self, trainer) -> bool:
        """Whether this step's comm should overlap compute — re-read from
        the env per step, like every trainer comm gate, so the autotuner
        can probe the knob live."""
        from ..gluon.trainer import _overlap_requested
        return _overlap_requested() and bool(trainer._kvstore_arg)

    def take_step_layout(self, trainer):
        """Consume the (key, bucket) layout the reduce-scatter half of
        this comm round computed (recompute if none — e.g. a restored
        step), so both halves always agree on layout."""
        layout = self._step_layout
        self._step_layout = None
        if layout is None:
            layout = self._bucket_layout(trainer)
        return layout

    # -- 1) per-bucket gradient reduce-scatter ---------------------------
    def _bucket_parts(self, bucket):
        """One bucket's segment map: ``parts`` — the LOCAL (i, grad, lo,
        hi) segments this process consumes — plus ``all_parts``, every
        rank's [lo, hi) list in bucket order (a pure function of the
        shared partition, identical on all callers: what lets the
        transport run a true tiled reduce-scatter)."""
        segs, off = [], 0
        for i, g in bucket:
            n = int(g.size)
            segs.append((i, g, off, off + n))
            off += n
        parts = [s for s in segs if s[0] in self._my_set]
        all_parts = [[(lo, hi) for i, _g, lo, hi in segs
                      if self.owners[i] == r] for r in range(self.world)]
        return parts, all_parts

    def launch_bucket_rs(self, trainer, key, bucket):
        """Issue ONE bucket's reduce-scatter collective (flatten + the
        kvstore call) and return ``(parts, slices)``, leaving the grad
        rebinds to :meth:`finish_bucket_rs`. The overlap scheduler calls
        this from the backward thread at grad finality, where autograd
        may still read the live grad buffers — the collective is pure,
        only the rebind must wait."""
        flat_nd = trainer._bucket_wire(key, bucket)
        parts, all_parts = self._bucket_parts(bucket)
        slices = self._kv.zero_reduce_scatter(
            key, flat_nd, [(lo, hi) for _, _, lo, hi in parts],
            all_parts=all_parts)
        return parts, slices

    @staticmethod
    def finish_bucket_rs(parts, slices) -> None:
        """Rebind the local params' grad buffers onto the reduced
        parameter-aligned slices a :meth:`launch_bucket_rs` returned."""
        for (i, g, _lo, _hi), arr in zip(parts, slices):
            g._rebind(arr._data.reshape(g.shape))

    def reduce_scatter_grads(self, trainer) -> None:
        """Reduce-scatter every dense gradient bucket: flatten with the
        stable ``_gbkt*`` layout (identical keys/contents to the
        allreduce path), issue ONE ``zero_reduce_scatter`` collective per
        bucket, and rebind this rank's parameters' grad buffers onto the
        reduced parameter-aligned slices. Non-local grads are left
        untouched — their updates happen on their owner rank and arrive
        back through the weight allgather (distributed runs: DON'T read
        or rescale the full grad set between this and the update; see
        the module docstring)."""
        self.check_comm_round()
        self.flush_pending()
        layout = self._bucket_layout(trainer)
        self._step_layout = layout
        if not layout:
            trainer.last_reduce_scatter_collectives = 0
            return
        n_coll = 0
        for key, bucket in layout:
            parts, slices = self.launch_bucket_rs(trainer, key, bucket)
            self.finish_bucket_rs(parts, slices)
            n_coll += 1
        trainer.last_reduce_scatter_collectives = n_coll
        if n_coll:
            _rs_counter().inc(n_coll)

    # -- 2) the global sentinel ------------------------------------------
    def global_finite_flag(self, live):
        """All-grads-finite verdict covering the WHOLE model: one fused
        reduction over this rank's shard of (cross-rank-reduced) grads —
        non-finite contributions survive summation, so the reduced shard
        carries every rank's poison — AND-reduced across ranks BEFORE any
        shard applies. Simulated worlds keep the flag on device (no extra
        host sync); a real group pays one tiny collective."""
        import jax
        import jax.numpy as jnp
        from ..optimizer import grouped as _grouped
        shard = tuple(p._grad._data for i, p in live
                      if i in self._my_set and p._grad is not None)
        flag = _grouped.global_finite_flag(shard) if shard \
            else jnp.asarray(True)
        if self.distributed:
            ok = self._kv.zero_all_finite(bool(jax.device_get(flag)))
            flag = jnp.asarray(bool(ok))
        return flag

    # -- 3) per-bucket weight allgather ----------------------------------
    def _launch_bucket_ag(self, trainer, key, bucket):
        """Issue ONE bucket's weight allgather (payload build + the
        kvstore call) and return the rank -> array result; the non-local
        rebinds are :meth:`_finish_bucket_ag`'s."""
        from ..ndarray import ndarray as _nd
        from ..gluon.trainer import _flatten_fn
        import jax.numpy as jnp
        payloads = {}
        for r in self.my_ranks:
            segs = [trainer._params[i]._data._data.ravel()
                    for i, _ in bucket if self.owners[i] == r]
            if len(segs) > 1:
                payloads[r] = _nd.NDArray(_flatten_fn()(*segs),
                                          ctx=bucket[0][1]._ctx)
            elif segs:
                payloads[r] = _nd.NDArray(segs[0],
                                          ctx=bucket[0][1]._ctx)
            else:
                # the collective contract: every rank contributes,
                # owner of zero params in this bucket included
                payloads[r] = _nd.NDArray(
                    jnp.zeros((0,), bucket[0][1]._data.dtype),
                    ctx=bucket[0][1]._ctx)
        return self._kv.zero_allgather(key, payloads)

    def _finish_bucket_ag(self, trainer, bucket, got) -> None:
        """Rebind every non-local parameter in ``bucket`` from its owner
        rank's payload (simulation: all params are local — no rebinds)."""
        import jax.numpy as jnp
        my = set(self.my_ranks)
        for r in range(self.world):
            if r in my:
                continue  # local shard already updated in place
            payload = jnp.asarray(got[r])
            off = 0
            for i, _g in bucket:
                if self.owners[i] != r:
                    continue
                w = trainer._params[i]._data
                n = int(w.size)
                w._rebind(payload[off:off + n].reshape(w.shape))
                off += n

    def launch_allgather_bucket(self, trainer, key, bucket) -> None:
        """Overlap mode: launch one bucket's weight allgather the moment
        its shard updates land — while the tail buckets still update
        (the ``DeviceStagingIter`` staging idiom applied to weights). In
        simulation every rank's update already ran in-process, so
        completion is immediate; a real group defers the non-local
        rebinds — every in-flight parameter carries a pending-fetch hook
        the next ``Parameter.data()`` read completes (first touch
        completes the whole bucket), with :meth:`flush_pending` as the
        barrier of last resort before the next comm round."""
        got = self._launch_bucket_ag(trainer, key, bucket)
        trainer.last_allgather_collectives += 1
        if not self.distributed:
            self._finish_bucket_ag(trainer, bucket, got)
            return
        done = [False]

        def finish():
            if done[0]:
                return
            done[0] = True
            for i, _g in bucket:
                trainer._params[i]._pending_fetch = None
            self._finish_bucket_ag(trainer, bucket, got)

        self._pending_ag.append(finish)
        my = set(self.my_ranks)
        for i, _g in bucket:
            if self.owners[i] not in my:
                trainer._params[i]._pending_fetch = finish

    def seal_allgather(self, trainer) -> None:
        """Close the overlapped allgather round: registry counter over
        the launches this step made."""
        if trainer.last_allgather_collectives:
            _ag_counter().inc(trainer.last_allgather_collectives)

    def flush_pending(self) -> None:
        """Complete every deferred allgather rebind (distributed runs;
        simulation never defers). Runs before the next comm round and
        lazily from ``Parameter.data()``."""
        pend, self._pending_ag = self._pending_ag, []
        for fin in pend:
            fin()

    def allgather_weights(self, trainer) -> None:
        """Ship this rank's updated weight segments per bucket (the same
        deterministic ``_gbkt`` layout) and rebind every non-local
        parameter from its owner's payload. In simulation every rank's
        update already ran in-process, so the call is a chaos/retry-
        covered identity echo and no rebinds happen — the collective
        count and fault surface still match the N-rank protocol."""
        # consume the layout the reduce-scatter half computed this round
        layout = self.take_step_layout(trainer)
        if not layout:
            trainer.last_allgather_collectives = 0
            return
        n_coll = 0
        for key, bucket in layout:
            got = self._launch_bucket_ag(trainer, key, bucket)
            self._finish_bucket_ag(trainer, bucket, got)
            n_coll += 1
        trainer.last_allgather_collectives = n_coll
        if n_coll:
            _ag_counter().inc(n_coll)

    # -- topology-portable checkpoints -----------------------------------
    def gather_states_bytes(self, updater) -> bytes:
        """Gather-on-save: every rank contributes its shard's state dict;
        the merged, ORDINARY unsharded pickle is what hits disk — a ZeRO
        checkpoint restores into an unsharded run (and any world size)
        unchanged. Simulated worlds already hold the full dict.

        Distributed runs: this is a COLLECTIVE — every rank must call it
        at the same step (FitLoop's checkpoint cadence does); a
        rank-0-only save blocks on peers' shards until the coordination
        timeout."""
        if not self.distributed:
            return updater.get_states(dump_optimizer=False)
        from .collectives import cross_process_exchange_bytes
        # indices=: ship ONLY this rank's shard into the merge — the
        # dict normally holds nothing else, but a stray non-local slot
        # (e.g. restored before the plane pruned) must not let rank r
        # overwrite rank q's fresher state in the merge
        local = updater.get_states(dump_optimizer=False,
                                   indices=self.local_indices())
        blobs = cross_process_exchange_bytes(local,
                                             f"zsv{next(_save_seq)}")
        from ..optimizer.optimizer import Updater
        merged: Dict = {}
        counts: Dict = {}
        num_update = 0
        for b in blobs:
            d = pickle.loads(b)
            # each rank's blob carries step counters for ITS indices in
            # the reserved keys — merge them like the state slots, or
            # the last rank's counters would clobber everyone else's and
            # Adam's bias correction would diverge on resume
            counts.update(d.pop(Updater.COUNTS_KEY, {}))
            num_update = max(num_update,
                             int(d.pop(Updater.NUM_UPDATE_KEY, 0)))
            merged.update(d)
        merged[Updater.COUNTS_KEY] = counts
        merged[Updater.NUM_UPDATE_KEY] = num_update
        return pickle.dumps(merged)
