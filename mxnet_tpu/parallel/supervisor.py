"""Self-healing training fleet: a supervisor that turns failure
DETECTION into automatic resize-and-resume (ROADMAP robustness plane;
ref: MXNet's ps-lite scheduler restarting dead workers, PAPER.md
§KVStore fault model).

The framework already detects every failure mode it cares about — the
hung-collective watchdog dumps a flight record naming the absent rank
(telemetry/collective.py), SIGTERM preemption drains to a final verified
checkpoint and exits with the resumable code (fit.py), elastic resume
re-splits the data stream exactly at any world size (elastic.py). But
detection without REACTION still pages a human at 3am. This module is
the missing control loop: a per-job supervisor process (spawned by
``tools/launch.py --supervise``) that watches the worker group and the
watchdog's dump directory, and converts each detected failure into the
one mechanical response the lower layers already support:

* a rank exits with the resumable code (preemption drain, chaos
  ``resize@N``) → relaunch at the checkpoint's requested world;
* a rank dies with any other code or a signal → signal survivors to
  checkpoint-and-exit, relaunch at the surviving world under
  ``MXTPU_ELASTIC=on``;
* a hung collective → the watchdog flight record names the absent rank;
  same shrink path (survivors are SIGTERMed out of the wedged
  collective — the drain-to-checkpoint flag is step-boundary safe);
* capacity returns (pluggable :class:`CapacityModel`; the stock one
  models spot/preemption recovery) → grow back toward the target world.

The escalation ladder is BOUNDED and is factored out as the pure
function :func:`decide` so every rung is table-testable without a
process tree:

1. transient coordination-service flake → the existing retry/backoff in
   the transport already absorbed it; the supervisor only logs;
2. hung collective / rank death → shrink to survivors and resume;
3. repeated crash of the SAME rank slot within
   ``MXTPU_SUPERVISE_CRASH_WINDOW_S`` → exclude the slot (continue
   smaller) instead of relaunching into the same bad host forever;
4. restart budget ``MXTPU_SUPERVISE_MAX_RESTARTS`` exhausted → fail
   LOUDLY with a forensic bundle (merged fleet trace when traces exist,
   every flight record, the last run report, the full event history) —
   never an infinite relaunch loop.

Correctness contract (regression-tested by tests/test_supervisor.py's
chaos soak): across any sequence of kills, hangs and resizes the union
of trained samples equals the no-failure stream exactly — zero
duplicated, zero dropped — and the post-resize loss trajectory matches a
never-failed run at the same global batch size. The supervisor never
touches training state; it only decides WHO runs and WHEN, and the
PR 9/15 checkpoint+resplit machinery makes any world transition exact.
"""
from __future__ import annotations

import json
import os
import shutil
import signal
import subprocess
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError, check, env

__all__ = [
    "EVENT_KINDS", "classify_exit", "decide",
    "CapacityModel", "StaticCapacity", "SpotCapacityModel",
    "Supervisor", "write_forensic_bundle",
    "supervise_max_restarts", "supervise_crash_window_s",
    "supervise_crash_limit",
]

# Every failure event the supervisor reasons about, in escalation order.
# ``flake`` is observational (the transport's own retry/backoff already
# absorbed it); the other four terminate a fleet generation.
EVENT_KINDS = ("flake", "hang", "crash", "signal", "resumable")

# Kinds that consume the restart budget: each one forces a relaunch.
# Capacity-driven grows do NOT — growing back to target when a spot
# slot returns is the system working, not the system failing.
_RESTART_KINDS = ("hang", "crash", "signal", "resumable")


# ---------------------------------------------------------------------------
# Env knobs (strict parse — the MXTPU_ZERO discipline: a typo'd budget
# must not silently become an infinite relaunch loop).

def supervise_max_restarts() -> int:
    try:
        n = int(env.get("MXTPU_SUPERVISE_MAX_RESTARTS"))
    except (TypeError, ValueError):
        raise MXNetError(
            "MXTPU_SUPERVISE_MAX_RESTARTS: expected an integer, got "
            f"{env.raw('MXTPU_SUPERVISE_MAX_RESTARTS')!r}")
    check(n >= 0, f"MXTPU_SUPERVISE_MAX_RESTARTS: must be >= 0, got {n}")
    return n


def supervise_crash_window_s() -> float:
    try:
        s = float(env.get("MXTPU_SUPERVISE_CRASH_WINDOW_S"))
    except (TypeError, ValueError):
        raise MXNetError(
            "MXTPU_SUPERVISE_CRASH_WINDOW_S: expected a number, got "
            f"{env.raw('MXTPU_SUPERVISE_CRASH_WINDOW_S')!r}")
    check(s > 0, f"MXTPU_SUPERVISE_CRASH_WINDOW_S: must be > 0, got {s}")
    return s


def supervise_crash_limit() -> int:
    try:
        n = int(env.get("MXTPU_SUPERVISE_CRASH_LIMIT"))
    except (TypeError, ValueError):
        raise MXNetError(
            "MXTPU_SUPERVISE_CRASH_LIMIT: expected an integer, got "
            f"{env.raw('MXTPU_SUPERVISE_CRASH_LIMIT')!r}")
    check(n >= 1, f"MXTPU_SUPERVISE_CRASH_LIMIT: must be >= 1, got {n}")
    return n


def _resumable_code() -> int:
    from .. import fit
    return fit.resumable_exit_code()


def classify_exit(rc: Optional[int]) -> str:
    """Exit-code taxonomy shared with ``tools/launch.py``: ``"ok"`` (0),
    ``"resumable"`` (the MXTPU_RESUMABLE_EXIT_CODE drain code, default
    75/EX_TEMPFAIL), ``"signal"`` (negative — Popen's killed-by-signal
    convention), ``"fatal"`` (everything else). ``None`` (still
    running) is a caller bug."""
    check(rc is not None, "classify_exit: process has not exited")
    if rc == 0:
        return "ok"
    if rc == _resumable_code():
        return "resumable"
    if rc < 0:
        return "signal"
    return "fatal"


# ---------------------------------------------------------------------------
# The escalation ladder, as a pure function of the event history.

def decide(events: Sequence[Dict[str, Any]], *, world: int,
           floor: int = 1,
           max_restarts: Optional[int] = None,
           crash_window_s: Optional[float] = None,
           crash_limit: Optional[int] = None) -> Dict[str, Any]:
    """What the supervisor does about the LATEST event in ``events``.

    Pure: no clock, no process tree, no env reads when all knobs are
    passed — the whole ladder is table-testable. Each event is a dict
    ``{"kind": one of EVENT_KINDS, "rank": int|None, "time": float,
    "ranks": [int, ...] (optional, defaults to [rank])}`` with ``time``
    on any monotonic clock (only differences are compared).

    Returns one action dict:

    * ``{"op": "retry"}`` — rung 1: the latest event is a transient kv
      flake; the transport's retry/backoff already handled it, nothing
      to relaunch.
    * ``{"op": "fail", "reason": ...}`` — rung 4: the restart budget is
      exhausted (or a shrink/exclude would go below ``floor``); the
      caller must write the forensic bundle and exit nonzero.
    * ``{"op": "exclude", "rank": r, "world": w}`` — rung 3: rank slot
      ``r`` crashed ``crash_limit`` times within ``crash_window_s``;
      continue at ``w = world - 1`` with the slot excluded.
    * ``{"op": "shrink", "world": w, "lost": [...]}`` — rung 2: relaunch
      at the surviving world under elastic resume.
    * ``{"op": "resume", "world": world}`` — every rank drained with the
      resumable code; relaunch at the same world (the caller then honors
      any ``resize_to`` the final checkpoint requested).
    """
    check(len(events) > 0, "decide: empty event history")
    if max_restarts is None:
        max_restarts = supervise_max_restarts()
    if crash_window_s is None:
        crash_window_s = supervise_crash_window_s()
    if crash_limit is None:
        crash_limit = supervise_crash_limit()
    ev = events[-1]
    kind = ev.get("kind")
    check(kind in EVENT_KINDS,
          f"decide: unknown event kind {kind!r} (known: {EVENT_KINDS})")

    # Rung 1: transient flake — already absorbed downstream.
    if kind == "flake":
        return {"op": "retry"}

    # Rung 4 (checked first among the relaunch rungs: a relaunch the
    # budget does not cover must not happen no matter which lower rung
    # would otherwise fire). The latest event IS a restart-requiring
    # incident at this point, so strictly-greater means "this relaunch
    # would be restart number max_restarts + 1".
    incidents = [e for e in events if e.get("kind") in _RESTART_KINDS]
    if len(incidents) > max_restarts:
        return {"op": "fail",
                "reason": f"restart budget exhausted: "
                          f"{len(incidents)} failure-driven relaunches "
                          f"needed, MXTPU_SUPERVISE_MAX_RESTARTS="
                          f"{max_restarts}"}

    # Rung 3: crash loop — the SAME slot keeps dying; relaunching it a
    # fourth time onto the same bad host is not resilience.
    if kind in ("crash", "signal") and ev.get("rank") is not None:
        rank, now = ev["rank"], ev.get("time", 0.0)
        recent = [e for e in events
                  if e.get("kind") in ("crash", "signal")
                  and e.get("rank") == rank
                  and now - e.get("time", 0.0) <= crash_window_s]
        if len(recent) >= crash_limit:
            if world - 1 < floor:
                return {"op": "fail",
                        "reason": f"rank slot {rank} crash-looped "
                                  f"({len(recent)}x within "
                                  f"{crash_window_s:g}s) and excluding "
                                  f"it would drop the fleet below the "
                                  f"floor of {floor}"}
            return {"op": "exclude", "rank": rank, "world": world - 1}

    # Rung 2: one-off death or hang — shrink to the survivors.
    if kind in ("hang", "crash", "signal"):
        lost = sorted(set(ev.get("ranks") or
                          ([ev["rank"]] if ev.get("rank") is not None
                           else [])))
        survivors = world - len(lost)
        if survivors < floor:
            # Whole-group death: nothing survived to shrink to, but the
            # last checkpoint did — relaunch at the floor (the budget
            # rung above bounds how often).
            survivors = floor
        return {"op": "shrink", "world": survivors, "lost": lost}

    # Graceful drain: every rank exited with the resumable code.
    return {"op": "resume", "world": world}


# ---------------------------------------------------------------------------
# Capacity models: how many rank slots COULD run right now.

class CapacityModel:
    """Pluggable answer to "how many slots does the scheduler offer"
    — the supervisor grows back toward the target world only when the
    model says the capacity exists. Subclass for a real scheduler
    (query the TPU pod manager, the k8s node pool, ...)."""

    def note_lost(self, n: int, now: float) -> None:  # pragma: no cover
        """A failure just took ``n`` slots away at monotonic ``now``."""

    def available(self, now: float) -> int:  # pragma: no cover
        raise NotImplementedError


class StaticCapacity(CapacityModel):
    """Capacity never moves: ``target`` slots, always (dedicated pod)."""

    def __init__(self, target: int):
        check(target >= 1, f"StaticCapacity: target must be >= 1, "
                           f"got {target}")
        self._target = target

    def note_lost(self, n: int, now: float) -> None:
        pass

    def available(self, now: float) -> int:
        return self._target


class SpotCapacityModel(CapacityModel):
    """Spot/preemption capacity: a lost slot comes back ``recovery_s``
    seconds later (the scheduler reschedules the preempted VM). This is
    the model the chaos soak exercises: kill a rank, watch the fleet
    shrink, watch it grow back once the modeled recovery elapses."""

    def __init__(self, target: int, recovery_s: float = 30.0):
        check(target >= 1, f"SpotCapacityModel: target must be >= 1, "
                           f"got {target}")
        check(recovery_s >= 0, f"SpotCapacityModel: recovery_s must be "
                               f">= 0, got {recovery_s}")
        self._target = target
        self._recovery_s = recovery_s
        self._lost: List[Tuple[float, int]] = []  # (when, how many)

    def note_lost(self, n: int, now: float) -> None:
        if n > 0:
            self._lost.append((now, n))

    def available(self, now: float) -> int:
        still_out = sum(n for t, n in self._lost
                        if now - t < self._recovery_s)
        return max(0, self._target - still_out)


# ---------------------------------------------------------------------------
# Forensic bundle: what rung 4 leaves behind instead of a relaunch.

def write_forensic_bundle(out_dir: str, *, events: Sequence[Dict],
                          summary: Dict[str, Any],
                          dump_dir: Optional[str] = None,
                          run_report_dir: Optional[str] = None,
                          trace_paths: Sequence[str] = ()) -> str:
    """Assemble the fail-loudly artifact: the full supervisor event
    history, every watchdog flight record, the newest run report, and —
    when per-rank chrome traces exist — the clock-aligned merged fleet
    trace (tools/fleet_trace.py). Everything is COPIED into one
    directory with a SHA-256 manifest so the bundle survives the
    job's scratch space being reaped. Returns the bundle directory."""
    bdir = os.path.join(out_dir, "forensics")
    os.makedirs(bdir, exist_ok=True)
    contents: Dict[str, Any] = {"flight_records": [], "run_report": None,
                                "fleet_trace": None}

    with open(os.path.join(bdir, "events.json"), "w") as f:
        json.dump({"events": list(events), "summary": summary}, f,
                  indent=2, sort_keys=True, default=str)

    if dump_dir and os.path.isdir(dump_dir):
        for name in sorted(os.listdir(dump_dir)):
            if name.startswith("coll_flight_") and name.endswith(".json"):
                try:
                    shutil.copy2(os.path.join(dump_dir, name),
                                 os.path.join(bdir, name))
                    contents["flight_records"].append(name)
                except OSError:
                    pass

    if run_report_dir is None:
        run_report_dir = str(env.get("MXTPU_RUN_REPORT_DIR") or "")
    if run_report_dir and os.path.isdir(run_report_dir):
        reports = sorted(
            (n for n in os.listdir(run_report_dir) if n.endswith(".json")),
            key=lambda n: os.path.getmtime(os.path.join(run_report_dir, n)))
        if reports:
            try:
                shutil.copy2(os.path.join(run_report_dir, reports[-1]),
                             os.path.join(bdir, "last_run_report.json"))
                contents["run_report"] = reports[-1]
            except OSError:
                pass

    existing = [p for p in trace_paths if os.path.exists(p)]
    if existing:
        try:
            from tools import fleet_trace
            merged = fleet_trace.merge(
                [fleet_trace.load_trace(p) for p in existing])
            with open(os.path.join(bdir, "fleet_trace.json"), "w") as f:
                json.dump({"traceEvents": merged}, f)
            contents["fleet_trace"] = "fleet_trace.json"
        except Exception:  # best-effort: a broken trace must not
            pass           # mask the failure being bundled

    with open(os.path.join(bdir, "MANIFEST.txt"), "w") as f:
        json.dump(contents, f, indent=2, sort_keys=True)
    try:
        from ..fault import write_manifest
        write_manifest(bdir)
    except Exception:
        pass
    return bdir


# ---------------------------------------------------------------------------
# The supervisor driver.

def _counter(name: str, doc: str):
    from ..telemetry import default_registry
    return default_registry().counter(name, doc)


class Supervisor:
    """The control loop. ``spawn(world, gen, extra_env)`` (provided by
    tools/launch.py) must start ``world`` worker processes and return
    ``{rank: subprocess.Popen}``; the supervisor owns everything after
    that: watching exits and the watchdog dump dir, terminating
    survivors, deciding via :func:`decide`, and relaunching.

    One fleet GENERATION = one spawn. Generation 0 is the fresh start;
    every later generation runs under ``MXTPU_ELASTIC=on`` +
    ``MXNET_IS_RECOVERY=1`` and resumes from the shared checkpoint
    stream. ``run()`` returns a process exit code: 0 when a generation
    ran to completion, nonzero after rung 4 wrote the forensic bundle.
    """

    def __init__(self, spawn: Callable[[int, int, Dict[str, str]],
                                       Dict[int, subprocess.Popen]],
                 target_world: int, *,
                 ckpt_dir: Optional[str] = None,
                 dump_dir: Optional[str] = None,
                 state_dir: Optional[str] = None,
                 capacity: Optional[CapacityModel] = None,
                 floor: int = 1,
                 term_grace_s: float = 5.0,
                 poll_s: float = 0.05,
                 max_restarts: Optional[int] = None,
                 crash_window_s: Optional[float] = None,
                 crash_limit: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic,
                 log: Callable[[str], None] = None):
        check(target_world >= 1,
              f"Supervisor: target_world must be >= 1, got {target_world}")
        self._spawn = spawn
        self._target = target_world
        self._ckpt_dir = ckpt_dir
        self._dump_dir = dump_dir or str(env.get("MXTPU_MEM_DUMP_DIR")
                                         or "") or None
        self._state_dir = state_dir
        self._capacity = capacity or StaticCapacity(target_world)
        self._floor = floor
        self._grace = term_grace_s
        self._poll = poll_s
        self._max_restarts = (supervise_max_restarts()
                              if max_restarts is None else max_restarts)
        self._crash_window = (supervise_crash_window_s()
                              if crash_window_s is None else crash_window_s)
        self._crash_limit = (supervise_crash_limit()
                             if crash_limit is None else crash_limit)
        self._clock = clock
        self._log = log or (lambda m: print(f"[supervisor] {m}",
                                            file=sys.stderr, flush=True))
        self.events: List[Dict[str, Any]] = []
        self.restarts = 0        # failure-driven relaunches (budgeted)
        self.grows = 0           # capacity-driven relaunches (free)
        self.excluded: List[int] = []   # crash-looped rank slots
        self.generations: List[Dict[str, Any]] = []
        self._seen_flights: set = set()

    # -- group control ----------------------------------------------------

    def _terminate(self, procs: Dict[int, subprocess.Popen]) -> Dict[int, int]:
        """SIGTERM everyone still alive (FitLoop drains to a final
        checkpoint at the next step boundary and exits resumable), wait
        out the grace period, SIGKILL stragglers. Returns {rank: rc}."""
        for p in procs.values():
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = self._clock() + self._grace
        while self._clock() < deadline and \
                any(p.poll() is None for p in procs.values()):
            time.sleep(self._poll)
        for p in procs.values():
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass
        return {r: p.wait() for r, p in procs.items()}

    def _scan_hangs(self) -> List[int]:
        """New watchdog flight records naming an absent rank."""
        if not self._dump_dir:
            return []
        from ..telemetry.collective import scan_flight_records
        recs = scan_flight_records(self._dump_dir, self._seen_flights)
        return sorted({r["absent_rank"] for r in recs
                       if r.get("absent_rank") is not None})

    def _watch(self, procs: Dict[int, subprocess.Popen],
               world: int) -> Dict[str, Any]:
        """Run one generation to its end. Returns
        ``{"kind": "done"}`` | ``{"kind": "grow", "world": w}`` |
        ``{"kind": "incident", "event": {...}}``."""
        alive = dict(procs)
        exits: Dict[int, int] = {}
        while alive:
            for rank, p in list(alive.items()):
                rc = p.poll()
                if rc is not None:
                    exits[rank] = rc
                    del alive[rank]

            # Hard death: kill the rest NOW — they would only wedge in
            # the next collective waiting for the dead peer. (A
            # resumable exit is NOT a death: peers may still be draining
            # their own final checkpoint; let them finish.) Checked
            # BEFORE the hang scan: a registered exit code is more
            # authoritative than a survivor's flight record naming the
            # same absent rank — both fire when a peer dies mid-step,
            # and the incident is a crash, not a hang.
            dead = {r: rc for r, rc in exits.items()
                    if classify_exit(rc) in ("fatal", "signal")}
            if dead:
                # Event time is DETECTION time (pre-drain): it is what
                # the crash-loop window should measure, and what the
                # shrink-latency metric counts from.
                t_dec = self._clock()
                exits.update(self._terminate(alive))
                kinds = {classify_exit(rc) for rc in dead.values()}
                kind = "signal" if kinds == {"signal"} else "crash"
                lost = sorted(dead)
                self._log(f"rank(s) {lost} died "
                          f"({ {r: rc for r, rc in dead.items()} }); "
                          f"draining survivors")
                return {"kind": "incident", "event": {
                    "kind": kind, "rank": lost[0], "ranks": lost,
                    "time": t_dec, "exits": exits}}

            # Hung collective: a flight record names the withholding
            # rank. The wedged survivors are still "alive" — drain them.
            absent = self._scan_hangs()
            if absent:
                t_dec = self._clock()
                self._log(f"hung collective: absent rank(s) {absent}; "
                          f"draining survivors")
                exits.update(self._terminate(alive))
                return {"kind": "incident", "event": {
                    "kind": "hang", "rank": absent[0], "ranks": absent,
                    "time": t_dec, "exits": exits}}

            # Grow: capacity says more slots exist than we are using.
            if alive and world < self._eff_target() and \
                    self._capacity.available(self._clock()) > world:
                t_dec = self._clock()
                self._log(f"capacity returned: growing {world} -> "
                          f"{self._grow_world(world)}; draining fleet")
                exits.update(self._terminate(alive))
                # A negative rc here is OUR signal (the SIGTERM drain,
                # or the SIGKILL after grace on a worker that could not
                # reach a step boundary) — the relaunch resumes from
                # the last durable checkpoint either way, so it is not
                # an incident. Only a worker FAILING on its own during
                # the drain (positive non-resumable exit) is.
                bad = {r: rc for r, rc in exits.items()
                       if rc > 0 and classify_exit(rc) == "fatal"}
                if bad:
                    lost = sorted(bad)
                    return {"kind": "incident", "event": {
                        "kind": "crash", "rank": lost[0], "ranks": lost,
                        "time": self._clock(), "exits": exits}}
                return {"kind": "grow", "world": self._grow_world(world),
                        "time": t_dec}

            time.sleep(self._poll)

        # Everyone exited on their own.
        classes = {classify_exit(rc) for rc in exits.values()}
        if classes == {"ok"}:
            return {"kind": "done", "exits": exits}
        if classes <= {"ok", "resumable"}:
            return {"kind": "incident", "event": {
                "kind": "resumable", "rank": None, "ranks": [],
                "time": self._clock(), "exits": exits}}
        dead = sorted(r for r, rc in exits.items()
                      if classify_exit(rc) in ("fatal", "signal"))
        kinds = {classify_exit(exits[r]) for r in dead}
        return {"kind": "incident", "event": {
            "kind": "signal" if kinds == {"signal"} else "crash",
            "rank": dead[0], "ranks": dead,
            "time": self._clock(), "exits": exits}}

    # -- world arithmetic -------------------------------------------------

    def _eff_target(self) -> int:
        """Target world minus crash-loop-excluded slots."""
        return max(self._floor, self._target - len(self.excluded))

    def _grow_world(self, world: int) -> int:
        return min(self._eff_target(),
                   max(world + 1,
                       min(self._capacity.available(self._clock()),
                           self._eff_target())))

    def _resume_world(self, fallback: int) -> int:
        """World for a resumable-drain relaunch: the ``resize_to`` the
        final checkpoint requested (chaos ``resize@N:M``, or an operator
        writing one) wins; otherwise same world."""
        if self._ckpt_dir:
            from ..fault import latest_checkpoint_meta
            from .elastic import resize_request
            found = latest_checkpoint_meta(self._ckpt_dir)
            rz = resize_request(found[1]) if found else None
            if rz:
                self._log(f"checkpoint requests resize_to={rz}")
                return max(self._floor, min(rz, self._eff_target()))
        return fallback

    # -- main loop --------------------------------------------------------

    def run(self) -> int:
        world = max(self._floor,
                    min(self._target,
                        self._capacity.available(self._clock())))
        gen = 0
        while True:
            extra = {"MXTPU_SUPERVISE_GEN": str(gen)}
            if gen > 0:
                extra["MXTPU_ELASTIC"] = "on"
                extra["MXNET_IS_RECOVERY"] = "1"
            t0 = self._clock()
            self._log(f"generation {gen}: world={world} "
                      f"(target {self._eff_target()}, "
                      f"restarts {self.restarts}/{self._max_restarts})")
            # Absorb flight records written during the previous
            # generation's drain grace window — they describe a fleet
            # that no longer exists and must not indict the new one.
            self._scan_hangs()
            procs = self._spawn(world, gen, extra)
            check(len(procs) == world,
                  f"spawn returned {len(procs)} processes for "
                  f"world={world}")
            outcome = self._watch(procs, world)
            rec = {"gen": gen, "world": world, "t_start": t0,
                   "t_end": self._clock(), "outcome": outcome["kind"],
                   # detection time: when the incident was observed /
                   # the grow was decided, BEFORE the drain — what
                   # relaunch-latency metrics count from
                   "t_decide": outcome.get(
                       "event", {}).get("time", outcome.get("time"))}
            self.generations.append(rec)

            if outcome["kind"] == "done":
                self._summary(world, ok=True)
                return 0

            if outcome["kind"] == "grow":
                self.grows += 1
                _counter("mxtpu_supervisor_grows_total",
                         "Capacity-driven fleet grow relaunches.").inc()
                world = outcome["world"]
                gen += 1
                continue

            event = outcome["event"]
            self.events.append(event)
            if event["kind"] in ("hang", "crash", "signal"):
                self._capacity.note_lost(len(event.get("ranks") or [1]),
                                         event["time"])
            action = decide(self.events, world=world, floor=self._floor,
                            max_restarts=self._max_restarts,
                            crash_window_s=self._crash_window,
                            crash_limit=self._crash_limit)
            self._log(f"event {event['kind']} (ranks "
                      f"{event.get('ranks')}) -> {action}")

            if action["op"] == "fail":
                self._fail(world, action["reason"])
                return 1

            self.restarts += 1
            _counter("mxtpu_supervisor_restarts_total",
                     "Failure-driven fleet relaunches (budgeted by "
                     "MXTPU_SUPERVISE_MAX_RESTARTS).").inc()
            if action["op"] == "exclude":
                self.excluded.append(action["rank"])
                self._log(f"rank slot {action['rank']} excluded "
                          f"(crash loop); continuing at "
                          f"{action['world']}")
                world = max(self._floor, action["world"])
            elif action["op"] == "shrink":
                world = max(self._floor, action["world"])
            else:  # resume
                world = self._resume_world(action["world"])
            gen += 1

    # -- reporting --------------------------------------------------------

    def _summary_payload(self, world: int, ok: bool) -> Dict[str, Any]:
        return {"ok": ok, "final_world": world,
                "target_world": self._target,
                "restarts": self.restarts, "grows": self.grows,
                "excluded": self.excluded,
                "generations": len(self.generations),
                "events": [{k: v for k, v in e.items() if k != "exits"}
                           for e in self.events],
                "gen_log": self.generations}

    def _summary(self, world: int, ok: bool,
                 forensics: Optional[str] = None) -> None:
        payload = self._summary_payload(world, ok)
        if forensics:
            payload["forensics"] = forensics
        print("SUPERVISOR_SUMMARY " + json.dumps(payload, sort_keys=True,
                                                 default=str), flush=True)

    def _fail(self, world: int, reason: str) -> None:
        self._log(f"FAILING LOUDLY: {reason}")
        bundle = None
        if self._state_dir:
            try:
                bundle = write_forensic_bundle(
                    self._state_dir, events=self.events,
                    summary=dict(self._summary_payload(world, ok=False),
                                 reason=reason),
                    dump_dir=self._dump_dir)
                self._log(f"forensic bundle: {bundle}")
            except OSError as e:
                self._log(f"forensic bundle write failed: {e}")
        self._summary(world, ok=False, forensics=bundle)
