"""Network visualization (ref: python/mxnet/visualization.py —
print_summary + plot_network graphviz)."""
from __future__ import annotations

import json
from typing import Dict, Optional

import numpy as _np

from .base import MXNetError, check

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape: Optional[Dict] = None,
                  line_length: int = 120, positions=(.44, .64, .74, 1.)):
    """Tabular per-layer summary (ref: visualization.py print_summary)."""
    out_shapes = {}
    if shape is not None:
        internals = symbol.get_internals()
        _, outs, _ = internals._infer_shape_impl(True, **shape)
        for name, s in zip(internals.list_outputs(), outs):
            out_shapes[name] = s

    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(values, pos):
        line = ""
        for v, p in zip(values, pos):
            line = (line + str(v))[:p - 1].ljust(p)
        print(line)

    print("_" * line_length)
    print_row(fields, positions)
    print("=" * line_length)
    total_params = 0
    for node in symbol._topo():
        if node.is_variable:
            continue
        name = node.name
        op = node.op.name
        key = f"{name}_output"
        oshape = out_shapes.get(key, "")
        n_params = 0
        for inp, _ in node.inputs:
            if inp.is_variable and not inp.extra.get("aux", False) and \
                    "weight" in inp.name or "bias" in inp.name:
                s = out_shapes.get(f"{inp.name}_output")
                if s:
                    n_params += int(_np.prod(s))
        total_params += n_params
        prev = ",".join(i.name for i, _ in node.inputs[:2])
        print_row([f"{name} ({op})", oshape, n_params, prev], positions)
    print("=" * line_length)
    print(f"Total params: {total_params}")
    print("_" * line_length)


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 dtype=None, node_attrs=None, hide_weights=True):
    """Graphviz digraph of the symbol (ref: visualization.py plot_network).
    Returns a graphviz.Digraph; requires the graphviz package at call time."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise MXNetError("plot_network requires the graphviz package")
    dot = Digraph(name=title)
    for node in symbol._topo():
        if node.is_variable:
            if hide_weights and ("weight" in node.name or "bias" in node.name
                                 or node.extra.get("aux", False)):
                continue
            dot.node(node.name, node.name, shape="oval",
                     fillcolor="#8dd3c7", style="filled")
        else:
            dot.node(node.name, f"{node.name}\n{node.op.name}", shape="box",
                     fillcolor="#fb8072", style="filled")
    for node in symbol._topo():
        if node.is_variable:
            continue
        for inp, _ in node.inputs:
            if hide_weights and inp.is_variable and \
                    ("weight" in inp.name or "bias" in inp.name or
                     inp.extra.get("aux", False)):
                continue
            dot.edge(inp.name, node.name)
    return dot
