"""Top-level Executor re-export (ref: mx.executor.Executor)."""
from .symbol.executor import Executor  # noqa: F401
