"""ResNet v1/v2 (ref: python/mxnet/gluon/model_zoo/vision/resnet.py:542).

Fresh TPU-first implementation of the standard architectures (He et al.
1512.03385, 1603.05027). The flagship bench model is resnet50_v1 — the
BASELINE headline metric (BASELINE.md: ResNet-50 training img/s).
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["ResNetV1", "ResNetV2", "BasicBlockV1", "BasicBlockV2",
           "BottleneckV1", "BottleneckV2", "resnet18_v1", "resnet34_v1",
           "resnet50_v1", "resnet101_v1", "resnet152_v1", "resnet18_v2",
           "resnet34_v2", "resnet50_v2", "resnet101_v2", "resnet152_v2",
           "get_resnet"]


def _conv3x3(channels, stride, in_channels, layout="NCHW"):
    return nn.Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                     use_bias=False, in_channels=in_channels, layout=layout)


def _bn_axis(layout):
    return -1 if layout.endswith("C") else 1


def _fuse_epilogue(layout):
    """Channel-last blocks use the fused Pallas BN(+add)+ReLU epilogues
    (ops/pallas_kernels.py): C on the lane-minor dim is what the kernels
    tile. Channel-first keeps the composed lowering."""
    return bool(layout) and layout.endswith("C")


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self._fused = _fuse_epilogue(layout)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(_conv3x3(channels, stride, in_channels, layout))
        if self._fused:
            self.body.add(nn.FusedBatchNormReLU(axis=ax))
            self.body.add(_conv3x3(channels, 1, channels, layout))
            self.body.add(nn.FusedBatchNormAddReLU(axis=ax))
        else:
            self.body.add(nn.BatchNorm(axis=ax))
            self.body.add(nn.Activation("relu"))
            self.body.add(_conv3x3(channels, 1, channels, layout))
            self.body.add(nn.BatchNorm(axis=ax))
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, kernel_size=1,
                                          strides=stride, use_bias=False,
                                          in_channels=in_channels,
                                          layout=layout))
            self.downsample.add(nn.BatchNorm(axis=ax))
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        if self.downsample:
            residual = self.downsample(residual)
        if self._fused:
            kids = list(self.body)
            for child in kids[:-1]:
                x = child(x)
            # tail child is the fused BN+add+ReLU (or, after int8
            # BN-folding, the add+relu epilogue it leaves behind)
            return kids[-1](x, residual)
        x = self.body(x)
        return F.Activation(residual + x, act_type="relu")


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self._fused = _fuse_epilogue(layout)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.Conv2D(channels // 4, kernel_size=1, strides=stride,
                               use_bias=False, layout=layout))
        if self._fused:
            self.body.add(nn.FusedBatchNormReLU(axis=ax))
            self.body.add(_conv3x3(channels // 4, 1, channels // 4, layout))
            self.body.add(nn.FusedBatchNormReLU(axis=ax))
            self.body.add(nn.Conv2D(channels, kernel_size=1, strides=1,
                                   use_bias=False, layout=layout))
            self.body.add(nn.FusedBatchNormAddReLU(axis=ax))
        else:
            self.body.add(nn.BatchNorm(axis=ax))
            self.body.add(nn.Activation("relu"))
            self.body.add(_conv3x3(channels // 4, 1, channels // 4, layout))
            self.body.add(nn.BatchNorm(axis=ax))
            self.body.add(nn.Activation("relu"))
            self.body.add(nn.Conv2D(channels, kernel_size=1, strides=1,
                                   use_bias=False, layout=layout))
            self.body.add(nn.BatchNorm(axis=ax))
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, kernel_size=1,
                                          strides=stride, use_bias=False,
                                          in_channels=in_channels,
                                          layout=layout))
            self.downsample.add(nn.BatchNorm(axis=ax))
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        if self.downsample:
            residual = self.downsample(residual)
        if self._fused:
            kids = list(self.body)
            for child in kids[:-1]:
                x = child(x)
            return kids[-1](x, residual)
        x = self.body(x)
        return F.Activation(x + residual, act_type="relu")


def _bn_relu(ax, fused):
    """Pre-activation BN+ReLU pair: one fused block channel-last, the
    composed pair otherwise (the caller applies the relu itself)."""
    return nn.FusedBatchNormReLU(axis=ax) if fused else nn.BatchNorm(axis=ax)


class BasicBlockV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self._fused = _fuse_epilogue(layout)
        self.bn1 = _bn_relu(ax, self._fused)
        self.conv1 = _conv3x3(channels, stride, in_channels, layout)
        self.bn2 = _bn_relu(ax, self._fused)
        self.conv2 = _conv3x3(channels, 1, channels, layout)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels,
                                        layout=layout)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        if not self._fused:
            x = F.Activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        if not self._fused:
            x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = _bn_axis(layout)
        self._fused = _fuse_epilogue(layout)
        self.bn1 = _bn_relu(ax, self._fused)
        self.conv1 = nn.Conv2D(channels // 4, kernel_size=1, strides=1,
                               use_bias=False, layout=layout)
        self.bn2 = _bn_relu(ax, self._fused)
        self.conv2 = _conv3x3(channels // 4, stride, channels // 4, layout)
        self.bn3 = _bn_relu(ax, self._fused)
        self.conv3 = nn.Conv2D(channels, kernel_size=1, strides=1,
                               use_bias=False, layout=layout)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels,
                                        layout=layout)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        if not self._fused:
            x = F.Activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        if not self._fused:
            x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        x = self.bn3(x)
        if not self._fused:
            x = F.Activation(x, act_type="relu")
        x = self.conv3(x)
        return x + residual


class SpaceToDepthStem(HybridBlock):
    """MXU-efficient replacement for the 7x7/2 stem conv (the MLPerf
    space-to-depth trick): rearrange 2x2 spatial blocks into channels
    (H,W,3 -> H/2,W/2,12) and apply an equivalent 4x4/1 convolution.

    Why: the stem's contraction dim is kh*kw*C = 7*7*3 = 147 padded up to
    the MXU's lane multiple, at terrible utilization; after s2d it is
    4*4*12 = 192 over a quarter the positions — the receptive field
    (8x8 superset of 7x7) and output grid (112x112, stride-2-equivalent)
    are preserved, and the stem trains directly in the rearranged basis.
    """

    # forward ends in self.conv(...): BN folding / quantization may treat
    # this block's output as that conv's output (contrib.quantization)
    _tail_conv = True

    def __init__(self, channels, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        self._layout = layout
        self.conv = nn.Conv2D(channels, 4, 1, 0, use_bias=False,
                              in_channels=12, layout=layout)

    def hybrid_forward(self, F, x):
        if self._layout == "NHWC":
            # (B,H,W,C) -> (B,H/2,2,W/2,2,C) -> (B,H/2,W/2,2,2,C) -> 12ch
            x = F.reshape(x, shape=(0, -4, -1, 2, -4, -1, 2, 0))
            x = F.transpose(x, axes=(0, 1, 3, 2, 4, 5))
            x = F.reshape(x, shape=(0, 0, 0, -3, 0))
            x = F.reshape(x, shape=(0, 0, 0, -3))
            # stride-2 7x7 pad-3 == stride-1 4x4 over s2d with pad (2,1)
            x = F.pad(x, mode="constant",
                      pad_width=(0, 0, 2, 1, 2, 1, 0, 0))
        else:
            x = F.reshape(x, shape=(0, 0, -4, -1, 2, -4, -1, 2))
            x = F.transpose(x, axes=(0, 1, 3, 5, 2, 4))
            x = F.reshape(x, shape=(0, -3, 0, 0, 0))
            x = F.reshape(x, shape=(0, -3, 0, 0))
            x = F.pad(x, mode="constant",
                      pad_width=(0, 0, 0, 0, 2, 1, 2, 1))
        return self.conv(x)


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, layout="NCHW", stem_s2d=False, **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        ax = _bn_axis(layout)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0, layout))
            else:
                if stem_s2d:
                    self.features.add(SpaceToDepthStem(channels[0],
                                                       layout=layout))
                else:
                    self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                                use_bias=False,
                                                layout=layout))
                self.features.add(nn.BatchNorm(axis=ax))
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(3, 2, 1, layout=layout))
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride,
                    in_channels=channels[i], layout=layout))
            self.features.add(nn.GlobalAvgPool2D(layout=layout))
            self.output = nn.Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, in_channels=0,
                    layout="NCHW"):
        layer = nn.HybridSequential(prefix="")
        layer.add(block(channels, stride, channels != in_channels,
                        in_channels=in_channels, layout=layout))
        for _ in range(layers - 1):
            layer.add(block(channels, 1, False, in_channels=channels,
                            layout=layout))
        return layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


class ResNetV2(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        ax = _bn_axis(layout)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.BatchNorm(scale=False, center=False,
                                           axis=ax))
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0, layout))
            else:
                self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                            use_bias=False, layout=layout))
                self.features.add(nn.BatchNorm(axis=ax))
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(3, 2, 1, layout=layout))
            in_channels = channels[0]
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride,
                    in_channels=in_channels, layout=layout))
                in_channels = channels[i + 1]
            self.features.add(nn.BatchNorm(axis=ax))
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.GlobalAvgPool2D(layout=layout))
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes, in_units=in_channels)

    def _make_layer(self, block, layers, channels, stride, in_channels=0,
                    layout="NCHW"):
        layer = nn.HybridSequential(prefix="")
        layer.add(block(channels, stride, channels != in_channels,
                        in_channels=in_channels, layout=layout))
        for _ in range(layers - 1):
            layer.add(block(channels, 1, False, in_channels=channels,
                            layout=layout))
        return layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


_RESNET_SPEC = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}

_BLOCKS_V1 = {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1}
_BLOCKS_V2 = {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2}


def get_resnet(version, num_layers, pretrained=False, ctx=None, root=None,
               **kwargs):
    block_type, layers, channels = _RESNET_SPEC[num_layers]
    if version == 1:
        return ResNetV1(_BLOCKS_V1[block_type], layers, channels, **kwargs)
    return ResNetV2(_BLOCKS_V2[block_type], layers, channels, **kwargs)


def resnet18_v1(**kw): return get_resnet(1, 18, **kw)
def resnet34_v1(**kw): return get_resnet(1, 34, **kw)
def resnet50_v1(**kw): return get_resnet(1, 50, **kw)
def resnet101_v1(**kw): return get_resnet(1, 101, **kw)
def resnet152_v1(**kw): return get_resnet(1, 152, **kw)
def resnet18_v2(**kw): return get_resnet(2, 18, **kw)
def resnet34_v2(**kw): return get_resnet(2, 34, **kw)
def resnet50_v2(**kw): return get_resnet(2, 50, **kw)
def resnet101_v2(**kw): return get_resnet(2, 101, **kw)
def resnet152_v2(**kw): return get_resnet(2, 152, **kw)
