"""Gluon model zoo (ref: python/mxnet/gluon/model_zoo/vision/__init__.py)."""
from .resnet import *  # noqa: F401,F403
from .alexnet import alexnet  # noqa: F401
from .vgg import (vgg11, vgg13, vgg16, vgg19, vgg11_bn, vgg13_bn,  # noqa
                  vgg16_bn, vgg19_bn, VGG)
from .squeezenet import squeezenet1_0, squeezenet1_1, SqueezeNet  # noqa
from .mobilenet import (mobilenet1_0, mobilenet0_75, mobilenet0_5,  # noqa
                        mobilenet0_25, mobilenet_v2_1_0,
                        mobilenet_v2_0_75, mobilenet_v2_0_5,
                        mobilenet_v2_0_25, MobileNet, MobileNetV2)
from .densenet import (densenet121, densenet161, densenet169,  # noqa
                       densenet201, DenseNet)
from .inception import inception_v3, Inception3  # noqa

from ....base import MXNetError

_models = {}


def _register_models():
    import sys
    mod = sys.modules[__name__]
    # zoo names whose registry key differs from the function name
    aliases = {"mobilenetv2_1.0": "mobilenet_v2_1_0",
               "mobilenetv2_0.75": "mobilenet_v2_0_75",
               "mobilenetv2_0.5": "mobilenet_v2_0_5",
               "mobilenetv2_0.25": "mobilenet_v2_0_25",
               "inceptionv3": "inception_v3"}
    for name in ["resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
                 "resnet152_v1", "resnet18_v2", "resnet34_v2", "resnet50_v2",
                 "resnet101_v2", "resnet152_v2", "alexnet", "vgg11", "vgg13",
                 "vgg16", "vgg19", "vgg11_bn", "vgg13_bn", "vgg16_bn",
                 "vgg19_bn", "squeezenet1.0", "squeezenet1.1",
                 "mobilenet1.0", "mobilenet0.75", "mobilenet0.5",
                 "mobilenet0.25", "mobilenetv2_1.0", "mobilenetv2_0.75",
                 "mobilenetv2_0.5", "mobilenetv2_0.25", "densenet121",
                 "densenet161", "densenet169", "densenet201",
                 "inceptionv3"]:
        attr = aliases.get(name, name.replace(".", "_"))
        fn = getattr(mod, attr, None)
        if fn is not None:
            _models[name] = fn


def get_model(name, **kwargs):
    """(ref: model_zoo/__init__.py get_model)"""
    if not _models:
        _register_models()
    name = name.lower()
    if name not in _models:
        raise MXNetError(
            f"model {name!r} not in zoo: {sorted(_models)}")
    return _models[name](**kwargs)
