"""Inception V3 (ref: python/mxnet/gluon/model_zoo/vision/inception.py;
"Rethinking the Inception Architecture for Computer Vision", Szegedy 2015).

The whole architecture is expressed as a declarative table of compact
unit strings (the same data-driven style as ``densenet.py``'s ``_SPEC``)
interpreted by a ~20-line builder, rather than per-stage constructor
functions.  Grammar for one unit:

    "<ch>:<kh>[x<kw>][v|/2]"    conv -> BN(eps 1e-3) -> relu
        kernel (kh, kw) (square if "x<kw>" absent); stride-1 convs are
        SAME-padded (pad k//2 per dim) unless the "v" (valid) suffix is
        present; "/2" means stride 2 and implies valid padding (the two
        suffixes are mutually exclusive — every stride-2 conv in
        Inception-v3 is valid-padded).
    "avgpool"                   3x3 avg pool, stride 1, SAME
    "maxpool"                   3x3 max pool, stride 2, valid
    [branch, branch, ...]       nested concurrent split (used by the E
                                blocks' 1x3/3x1 fan-outs)

Input is 299x299.  Every branch is convolutions + pooling, so the whole
network is one fused XLA program when hybridized; the HybridConcurrent
branch joins become a single concat in HLO.
"""
from ...block import HybridBlock
from ... import nn
from ...contrib.nn import HybridConcurrent

__all__ = ["Inception3", "inception_v3"]

# Stem: 299x299x3 -> 35x35x192.
_STEM = ["32:3/2", "32:3v", "64:3", "maxpool", "80:1", "192:3v", "maxpool"]

# One entry per mixed block, in network order: (tag, [branches]).
# 3xA (35x35), reduction B (17x17), 4xC, reduction D (8x8), 2xE.
_MIXED = (
    [("A%d" % i, [["64:1"],
                  ["48:1", "64:5"],
                  ["64:1", "96:3", "96:3"],
                  ["avgpool", "%d:1" % p]]) for i, p in enumerate((32, 64, 64), 1)]
    + [("B", [["384:3/2"],
              ["64:1", "96:3", "96:3/2"],
              ["maxpool"]])]
    + [("C%d" % i, [["192:1"],
                    ["%d:1" % c, "%d:1x7" % c, "192:7x1"],
                    ["%d:1" % c, "%d:7x1" % c, "%d:1x7" % c,
                     "%d:7x1" % c, "192:1x7"],
                    ["avgpool", "192:1"]]) for i, c in enumerate((128, 160, 160, 192), 1)]
    + [("D", [["192:1", "320:3/2"],
              ["192:1", "192:1x7", "192:7x1", "192:3/2"],
              ["maxpool"]])]
    + [("E%d" % i, [["320:1"],
                    ["384:1", [["384:1x3"], ["384:3x1"]]],
                    ["448:1", "384:3", [["384:1x3"], ["384:3x1"]]],
                    ["avgpool", "192:1"]]) for i in (1, 2)]
)


def _unit(spec):
    """Interpret one unit string of the grammar above into a block."""
    if spec == "avgpool":
        return nn.AvgPool2D(pool_size=3, strides=1, padding=1)
    if spec == "maxpool":
        return nn.MaxPool2D(pool_size=3, strides=2)
    head, _, tail = spec.partition(":")
    channels = int(head)
    strides = 2 if tail.endswith("/2") else 1
    valid = strides == 2 or tail.endswith("v")
    kdims = tail.rstrip("v").split("/")[0].split("x")
    kernel = tuple(int(k) for k in kdims) * (2 // len(kdims))
    conv = nn.HybridSequential(prefix="")
    conv.add(nn.Conv2D(channels, kernel_size=kernel, strides=strides,
                       padding=(0, 0) if valid else tuple(k // 2 for k in kernel),
                       use_bias=False))
    conv.add(nn.BatchNorm(epsilon=0.001))
    conv.add(nn.Activation("relu"))
    return conv


def _chain(units):
    """A branch: sequential units, any of which may itself be a split."""
    out = nn.HybridSequential(prefix="")
    for u in units:
        out.add(_split(u) if isinstance(u, list) else _unit(u))
    return out


def _split(branches, prefix=""):
    """Concurrent branches joined by a channel concat."""
    out = HybridConcurrent(axis=1, prefix=prefix)
    with out.name_scope():
        for units in branches:
            out.add(_chain(units))
    return out


class Inception3(HybridBlock):
    """Inception V3 (ref: model_zoo/vision/inception.py Inception3)."""

    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            for spec in _STEM:
                self.features.add(_unit(spec))
            for tag, branches in _MIXED:
                self.features.add(_split(branches, prefix=tag + "_"))
            self.features.add(nn.AvgPool2D(pool_size=8))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


def inception_v3(pretrained=False, classes=1000, **kwargs):
    """Inception V3 constructor (ref: inception.py inception_v3)."""
    return Inception3(classes=classes, **kwargs)
