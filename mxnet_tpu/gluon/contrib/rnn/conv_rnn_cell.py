"""Convolutional RNN/LSTM/GRU cells
(ref: python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py).

All cells keep spatial dims fixed across steps: the state-to-state (h2h)
convolution uses same-padding (odd kernels required, as in the reference),
and the input-to-state (i2h) convolution's output spatial shape — set by the
user's i2h kernel/pad/dilate — defines the state shape. On TPU both convs
land on the MXU; the gate arithmetic fuses into their epilogues under jit.
"""
from __future__ import annotations

from ....base import MXNetError, check
from ...rnn.rnn_cell import RecurrentCell

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


def _tuplify(x, ndim):
    if isinstance(x, (list, tuple)):
        check(len(x) == ndim, f"expected length-{ndim} tuple, got {x}")
        return tuple(int(v) for v in x)
    return (int(x),) * ndim


class _BaseConvRNNCell(RecurrentCell):
    """(ref: conv_rnn_cell.py:37 _BaseConvRNNCell)"""

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, i2h_dilate, h2h_dilate, num_gates, activation,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 conv_layout="NCHW", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        ndim = len(conv_layout) - 2
        self._input_shape = tuple(input_shape)
        self._hidden_channels = hidden_channels
        self._i2h_kernel = _tuplify(i2h_kernel, ndim)
        self._h2h_kernel = _tuplify(h2h_kernel, ndim)
        for k in self._h2h_kernel:
            check(k % 2 == 1,
                  f"h2h_kernel dims must be odd for same-padding, got "
                  f"{self._h2h_kernel}")
        self._i2h_pad = _tuplify(i2h_pad, ndim)
        self._i2h_dilate = _tuplify(i2h_dilate, ndim)
        self._h2h_dilate = _tuplify(h2h_dilate, ndim)
        self._h2h_pad = tuple(d * (k - 1) // 2 for d, k in
                              zip(self._h2h_dilate, self._h2h_kernel))
        self._num_gates = num_gates
        self._activation = activation
        self._conv_layout = conv_layout
        in_channels = self._input_shape[0]
        spatial = self._input_shape[1:]
        # state spatial dims = i2h conv output dims (stride 1)
        self._state_shape = (hidden_channels,) + tuple(
            (s + 2 * p - d * (k - 1) - 1) + 1
            for s, p, d, k in zip(spatial, self._i2h_pad, self._i2h_dilate,
                                  self._i2h_kernel))
        g = num_gates
        self.i2h_weight = self.params.get(
            "i2h_weight",
            shape=(g * hidden_channels, in_channels) + self._i2h_kernel,
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight",
            shape=(g * hidden_channels, hidden_channels) + self._h2h_kernel,
            init=h2h_weight_initializer)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(g * hidden_channels,),
            init=i2h_bias_initializer)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(g * hidden_channels,),
            init=h2h_bias_initializer)

    def infer_shape_from_inputs(self, inputs, states=None):
        pass  # shapes fully specified by input_shape at construction

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size,) + self._state_shape,
                 "__layout__": self._conv_layout}]

    def _conv_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                      i2h_bias, h2h_bias):
        g = self._num_gates
        c = self._hidden_channels
        i2h = F.Convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, stride=(1,) *
                            len(self._i2h_kernel), pad=self._i2h_pad,
                            dilate=self._i2h_dilate, num_filter=g * c)
        h2h = F.Convolution(states[0], h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, stride=(1,) *
                            len(self._h2h_kernel), pad=self._h2h_pad,
                            dilate=self._h2h_dilate, num_filter=g * c)
        return i2h, h2h

    def _act(self, F, x):
        return F.Activation(x, act_type=self._activation)

    def __repr__(self):
        return (f"{type(self).__name__}(in={self._input_shape}, "
                f"hidden={self._hidden_channels})")


class _ConvRNNCell(_BaseConvRNNCell):
    """(ref: conv_rnn_cell.py:177)"""

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, i2h_dilate, h2h_dilate, activation, conv_layout,
                 **kw):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, i2h_dilate, h2h_dilate,
                         num_gates=1, activation=activation,
                         conv_layout=conv_layout, **kw)

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        out = self._act(F, i2h + h2h)
        return out, [out]


class _ConvLSTMCell(_BaseConvRNNCell):
    """(ref: conv_rnn_cell.py:420; Shi et al. 2015 "Convolutional LSTM")"""

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, i2h_dilate, h2h_dilate, activation, conv_layout,
                 **kw):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, i2h_dilate, h2h_dilate,
                         num_gates=4, activation=activation,
                         conv_layout=conv_layout, **kw)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size,) + self._state_shape,
                 "__layout__": self._conv_layout}] * 2

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        gates = i2h + h2h
        slices = F.op.split(gates, num_outputs=4, axis=1)
        i = F.sigmoid(slices[0])
        f = F.sigmoid(slices[1])
        g = self._act(F, slices[2])
        o = F.sigmoid(slices[3])
        c = f * states[1] + i * g
        out = o * self._act(F, c)
        return out, [out, c]


class _ConvGRUCell(_BaseConvRNNCell):
    """(ref: conv_rnn_cell.py:704)"""

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, i2h_dilate, h2h_dilate, activation, conv_layout,
                 **kw):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, i2h_dilate, h2h_dilate,
                         num_gates=3, activation=activation,
                         conv_layout=conv_layout, **kw)

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev = states[0]
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        i2h_s = F.op.split(i2h, num_outputs=3, axis=1)
        h2h_s = F.op.split(h2h, num_outputs=3, axis=1)
        r = F.sigmoid(i2h_s[0] + h2h_s[0])
        z = F.sigmoid(i2h_s[1] + h2h_s[1])
        n = self._act(F, i2h_s[2] + r * h2h_s[2])
        out = (1 - z) * n + z * prev
        return out, [out]


def _make(base, ndim, name, doc_line):
    layout = {1: "NCW", 2: "NCHW", 3: "NCDHW"}[ndim]

    class Cell(base):
        def __init__(self, input_shape, hidden_channels, i2h_kernel,
                     h2h_kernel, i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                     activation="tanh", conv_layout=layout, **kw):
            super().__init__(input_shape, hidden_channels, i2h_kernel,
                             h2h_kernel, i2h_pad, i2h_dilate, h2h_dilate,
                             activation, conv_layout, **kw)

    Cell.__name__ = Cell.__qualname__ = name
    Cell.__doc__ = doc_line
    return Cell


Conv1DRNNCell = _make(_ConvRNNCell, 1, "Conv1DRNNCell",
                      "1D conv RNN cell (ref: conv_rnn_cell.py:218)")
Conv2DRNNCell = _make(_ConvRNNCell, 2, "Conv2DRNNCell",
                      "2D conv RNN cell (ref: conv_rnn_cell.py:285)")
Conv3DRNNCell = _make(_ConvRNNCell, 3, "Conv3DRNNCell",
                      "3D conv RNN cell (ref: conv_rnn_cell.py:352)")
Conv1DLSTMCell = _make(_ConvLSTMCell, 1, "Conv1DLSTMCell",
                       "1D conv LSTM cell (ref: conv_rnn_cell.py:473)")
Conv2DLSTMCell = _make(_ConvLSTMCell, 2, "Conv2DLSTMCell",
                       "2D conv LSTM cell (ref: conv_rnn_cell.py:550)")
Conv3DLSTMCell = _make(_ConvLSTMCell, 3, "Conv3DLSTMCell",
                       "3D conv LSTM cell (ref: conv_rnn_cell.py:627)")
Conv1DGRUCell = _make(_ConvGRUCell, 1, "Conv1DGRUCell",
                      "1D conv GRU cell (ref: conv_rnn_cell.py:762)")
Conv2DGRUCell = _make(_ConvGRUCell, 2, "Conv2DGRUCell",
                      "2D conv GRU cell (ref: conv_rnn_cell.py:834)")
Conv3DGRUCell = _make(_ConvGRUCell, 3, "Conv3DGRUCell",
                      "3D conv GRU cell (ref: conv_rnn_cell.py:906)")
