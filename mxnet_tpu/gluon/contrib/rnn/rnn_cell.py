"""Contrib RNN cells (ref: python/mxnet/gluon/contrib/rnn/rnn_cell.py)."""
from __future__ import annotations

from ...rnn.rnn_cell import RecurrentCell, _BaseCell

__all__ = ["VariationalDropoutCell", "LSTMPCell"]


class VariationalDropoutCell(RecurrentCell):
    """Variational (locked) dropout: one mask sampled per unroll and reused
    across all time steps for inputs/states/outputs
    (ref: contrib/rnn/rnn_cell.py:27).
    """

    def __init__(self, base_cell, drop_inputs=0., drop_states=0.,
                 drop_outputs=0., **kw):
        super().__init__(**kw)
        self.register_child(base_cell, "base_cell")
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    @property
    def base_cell(self):
        return self._children["base_cell"]

    def reset(self):
        super().reset()
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return self.base_cell.begin_state(batch_size=batch_size, **kwargs)

    def _initialize_mask(self, F, rate, like):
        # Dropout of ones gives the inverted-dropout mask (0 or 1/(1-p)).
        return F.Dropout(F.ones_like(like), p=rate)

    def forward(self, inputs, states):
        from .... import ndarray as F
        from .... import autograd
        if autograd.is_training():
            if self.drop_inputs:
                if self.drop_inputs_mask is None:
                    self.drop_inputs_mask = self._initialize_mask(
                        F, self.drop_inputs, inputs)
                inputs = inputs * self.drop_inputs_mask
            if self.drop_states:
                if self.drop_states_mask is None:
                    self.drop_states_mask = self._initialize_mask(
                        F, self.drop_states, states[0])
                states = [states[0] * self.drop_states_mask] + list(states[1:])
        out, next_states = self.base_cell(inputs, states)
        if autograd.is_training() and self.drop_outputs:
            if self.drop_outputs_mask is None:
                self.drop_outputs_mask = self._initialize_mask(
                    F, self.drop_outputs, out)
            out = out * self.drop_outputs_mask
        return out, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        return super().unroll(length, inputs, begin_state=begin_state,
                              layout=layout, merge_outputs=merge_outputs,
                              valid_length=valid_length)

    def __repr__(self):
        return (f"VariationalDropoutCell(p_in={self.drop_inputs}, "
                f"p_state={self.drop_states}, p_out={self.drop_outputs})")


class LSTMPCell(_BaseCell):
    """LSTM with a projection of the hidden state
    (ref: contrib/rnn/rnn_cell.py:198, arXiv:1402.1128).

    States: [projected hidden (N, projection_size), cell (N, hidden_size)].
    """

    def __init__(self, hidden_size, projection_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", prefix=None, params=None):
        # _BaseCell creates i2h/h2h weights sized on hidden_size; LSTMP's h2h
        # consumes the projected state instead, so build weights manually.
        RecurrentCell.__init__(self, prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, projection_size),
            init=h2h_weight_initializer)
        self.h2r_weight = self.params.get(
            "h2r_weight", shape=(projection_size, hidden_size),
            init=h2r_weight_initializer)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,), init=i2h_bias_initializer)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,), init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       h2r_weight, i2h_bias, h2h_bias):
        h = self._hidden_size
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias, num_hidden=4 * h)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * h)
        gates = i2h + h2h
        slices = F.op.split(gates, num_outputs=4, axis=1)
        i = F.sigmoid(slices[0])
        f = F.sigmoid(slices[1])
        g = F.tanh(slices[2])
        o = F.sigmoid(slices[3])
        c = f * states[1] + i * g
        hidden = o * F.tanh(c)
        proj = F.FullyConnected(hidden, h2r_weight, num_hidden=
                                self._projection_size, no_bias=True)
        return proj, [proj, c]

    def __repr__(self):
        return (f"LSTMPCell({self._hidden_size}, "
                f"proj={self._projection_size})")
