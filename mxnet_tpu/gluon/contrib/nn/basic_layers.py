"""Contrib layers (ref: python/mxnet/gluon/contrib/nn/basic_layers.py).

TPU notes: SyncBatchNorm lowers to the `_contrib_SyncBatchNorm` op whose
batch reductions become XLA psums when the batch axis is sharded over the
mesh — `ndev`/`key` are kept for API parity but the mesh, not a comm key,
decides the reduction group. PixelShuffle is pure reshape/transpose, which
XLA fuses into the surrounding convolution's output layout change.
"""
from __future__ import annotations

from ...block import Block, HybridBlock
from ..nn import __name__ as _  # noqa: F401  (package anchor)
from ....base import check
from ...nn.basic_layers import Sequential, HybridSequential, BatchNorm

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm", "PixelShuffle1D", "PixelShuffle2D",
           "PixelShuffle3D"]


class Concurrent(Sequential):
    """Runs children on the same input, concatenates outputs on `axis`
    (ref: contrib/nn/basic_layers.py:31)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import ndarray as F
        out = [child(x) for child in self._children.values()]
        return F.concatenate(out, axis=self.axis)


class HybridConcurrent(HybridSequential):
    """Hybridizable Concurrent (ref: contrib/nn/basic_layers.py:64)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def _imperative_call(self, x):
        from .... import ndarray as F
        out = [child._imperative_call(x) if isinstance(child, HybridBlock)
               else child(x) for child in self._children.values()]
        return F.concatenate(out, axis=self.axis)

    def hybrid_forward(self, F, x):
        out = [child(x) for child in self._children.values()]
        return F.concatenate(out, axis=self.axis)


class Identity(HybridBlock):
    """Identity mapping (ref: contrib/nn/basic_layers.py:97)."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Block):
    """Embedding with row-sparse gradient (ref: contrib/nn/basic_layers.py:118).

    On TPU the gradient is computed as a dense scatter-add; the row-sparse
    contract (only touched rows updated) is preserved by the optimizer's
    lazy-update path for rows whose gradient is exactly zero.
    """

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self.weight = self.params.get("weight",
                                      shape=(input_dim, output_dim),
                                      init=weight_initializer, dtype=dtype,
                                      stype="row_sparse",
                                      grad_stype="row_sparse")

    def forward(self, x):
        from .... import ndarray as F
        return F.Embedding(x, self.weight.data(), sparse_grad=True,
                           input_dim=self._input_dim,
                           output_dim=self._output_dim)

    def __repr__(self):
        return f"SparseEmbedding({self._input_dim} -> {self._output_dim})"


class SyncBatchNorm(BatchNorm):
    """Cross-device synchronized BatchNorm (ref: contrib/nn/basic_layers.py:165
    -> src/operator/contrib/sync_batch_norm.cc).

    TPU-native: the op's batch-statistics reductions become `psum`s over the
    data-parallel mesh axis under pjit/shard_map, so the `num_devices`/key
    machinery of the reference collapses into the sharding annotation.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", prefix=None,
                 params=None):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=running_variance_initializer,
                         in_channels=in_channels, prefix=prefix,
                         params=params)
        self._num_devices = num_devices if num_devices is not None else 1

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        from .... import autograd
        out, mean, var = F.contrib.SyncBatchNorm(
            x, gamma, beta, running_mean, running_var,
            eps=self._epsilon, momentum=self._momentum,
            fix_gamma=not self._scale,
            use_global_stats=self._use_global_stats,
            ndev=self._num_devices, key=self.name)
        if autograd.is_training() and not self._use_global_stats:
            with autograd.pause():
                m = self._momentum
                running_mean._rebind((running_mean * m + mean * (1 - m))._data)
                running_var._rebind((running_var * m + var * (1 - m))._data)
        return out


class PixelShuffle1D(HybridBlock):
    """(N, f*C, W) -> (N, C, W*f) (ref: contrib/nn/basic_layers.py:244)."""

    def __init__(self, factor, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._factor = int(factor)

    def hybrid_forward(self, F, x):
        f = self._factor
        # (N, f*C, W) -> (N, f, C, W) -> (N, C, W, f) -> (N, C, W*f)
        x = F.reshape(x, shape=(0, -4, f, -1, 0))
        x = F.transpose(x, axes=(0, 2, 3, 1))
        return F.reshape(x, shape=(0, 0, -3))

    def __repr__(self):
        return f"PixelShuffle1D({self._factor})"


class PixelShuffle2D(HybridBlock):
    """(N, f1*f2*C, H, W) -> (N, C, H*f1, W*f2)
    (ref: contrib/nn/basic_layers.py:292)."""

    def __init__(self, factor, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if isinstance(factor, (list, tuple)):
            self._factors = (int(factor[0]), int(factor[1]))
        else:
            self._factors = (int(factor),) * 2

    def hybrid_forward(self, F, x):
        f1, f2 = self._factors
        # (N, f1*f2*C, H, W) -> (N, f1, f2, C, H, W)
        x = F.reshape(x, shape=(0, -4, f1 * f2, -1, 0, 0))
        x = F.reshape(x, shape=(0, -4, f1, f2, 0, 0, 0))
        # -> (N, C, H, f1, W, f2)
        x = F.transpose(x, axes=(0, 3, 4, 1, 5, 2))
        # -> (N, C, H*f1, W*f2)
        x = F.reshape(x, shape=(0, 0, -3, -3))
        return x

    def __repr__(self):
        return f"PixelShuffle2D({self._factors})"


class PixelShuffle3D(HybridBlock):
    """(N, f1*f2*f3*C, D, H, W) -> (N, C, D*f1, H*f2, W*f3)
    (ref: contrib/nn/basic_layers.py:354)."""

    def __init__(self, factor, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if isinstance(factor, (list, tuple)):
            check(len(factor) == 3, "factor must be int or 3-tuple")
            self._factors = tuple(int(f) for f in factor)
        else:
            self._factors = (int(factor),) * 3

    def hybrid_forward(self, F, x):
        f1, f2, f3 = self._factors
        x = F.reshape(x, shape=(0, -4, f1 * f2 * f3, -1, 0, 0, 0))
        x = F.reshape(x, shape=(0, -4, f1, f2 * f3, 0, 0, 0, 0))
        x = F.reshape(x, shape=(0, 0, -4, f2, f3, 0, 0, 0, 0))
        # now (N, f1, f2, f3, C, D, H, W)
        x = F.transpose(x, axes=(0, 4, 5, 1, 6, 2, 7, 3))
        x = F.reshape(x, shape=(0, 0, -3, -3, -3))
        return x

    def __repr__(self):
        return f"PixelShuffle3D({self._factors})"
