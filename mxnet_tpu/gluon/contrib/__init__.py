"""Gluon contrib namespace (ref: python/mxnet/gluon/contrib/__init__.py)."""
from . import nn
from . import rnn
from . import data
