"""Gluon utilities (ref: python/mxnet/gluon/utils.py)."""
from __future__ import annotations

import math
from typing import List, Optional

from ..base import MXNetError, check

__all__ = ["split_data", "split_and_load", "clip_global_norm",
           "check_sha1", "download"]


def split_data(data, num_slice: int, batch_axis: int = 0,
               even_split: bool = True) -> List:
    """Split a batch along ``batch_axis`` (ref: utils.py split_data)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"cannot evenly split batch of {size} into {num_slice}")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(axis=batch_axis, begin=begin, end=end))
    return slices


def split_and_load(data, ctx_list, batch_axis: int = 0,
                   even_split: bool = True) -> List:
    """Split a batch across contexts (ref: utils.py split_and_load).

    On the SPMD path one sharded array replaces this; kept for API parity and
    the per-device Gluon training loop.
    """
    from ..ndarray import ndarray as _nd
    if not isinstance(data, _nd.NDArray):
        data = _nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm: float, check_isfinite: bool = True):
    """Rescale arrays so the joint L2 norm <= max_norm
    (ref: utils.py clip_global_norm)."""
    import numpy as _np
    check(len(arrays) > 0, "need at least one array")
    total = 0.0
    for a in arrays:
        n = a.norm().asscalar()
        total += float(n) ** 2
    total = math.sqrt(total)
    if check_isfinite and not math.isfinite(total):
        import warnings
        warnings.warn("nan or inf in clip_global_norm")
    scale = max_norm / (total + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a._rebind((a * scale)._data)
    return total


def check_sha1(filename: str, sha1_hash: str) -> bool:
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):
    """Zero-egress environment: downloads are unavailable; kept for API
    parity (raises with a clear message)."""
    raise MXNetError("network downloads are disabled in this environment")
