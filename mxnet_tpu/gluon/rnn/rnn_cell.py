"""Gluon RNN cells (ref: python/mxnet/gluon/rnn/rnn_cell.py)."""
from __future__ import annotations

from ...base import MXNetError, check
from ..block import HybridBlock

__all__ = ["RecurrentCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "DropoutCell", "BidirectionalCell",
           "ResidualCell", "ZoneoutCell"]


class RecurrentCell(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as F
        func = func or F.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            shape = info["shape"]
            states.append(func(shape, **kwargs))
        return states

    def __call__(self, inputs, states):
        self._counter += 1
        return self.forward(inputs, states)

    def forward(self, inputs, states):
        return self._imperative_call(inputs, states)

    def _imperative_call(self, inputs, states):
        from ... import ndarray as F
        try:
            params = self._resolved_params()
        except Exception:
            self.infer_shape_from_inputs(inputs, states)
            for _, p in self._params.items():
                if p._deferred_init is not None:
                    p._finish_deferred_init()
            params = self._resolved_params()
        return self.hybrid_forward(F, inputs, states, **params)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """(ref: rnn_cell.py BaseRNNCell.unroll)"""
        from ... import ndarray as F
        self.reset()
        axis = layout.find("T")
        batch_axis = layout.find("N")
        if not isinstance(inputs, (list, tuple)):
            batch = inputs.shape[batch_axis]
            if length > 1:
                seq = list(F.op.split(inputs, num_outputs=length, axis=axis,
                                      squeeze_axis=True))
            else:
                seq = [F.op.squeeze(inputs, axis=axis)]
        else:
            seq = list(inputs)
            batch = seq[0].shape[0]
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch)
        states = begin_state
        outputs = []
        for i in range(length):
            out, states = self(seq[i], states)
            outputs.append(out)
        if merge_outputs:
            outputs = F.stack(*outputs, axis=axis)
        return outputs, states


class _BaseCell(RecurrentCell):
    def __init__(self, hidden_size, num_gates, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        g = num_gates
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(g * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(g * hidden_size, hidden_size),
            init=h2h_weight_initializer)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(g * hidden_size,),
            init=i2h_bias_initializer)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(g * hidden_size,),
            init=h2h_bias_initializer)

    def infer_shape_from_inputs(self, inputs, states=None):
        self.i2h_weight.shape_hint(
            (self.i2h_weight.shape[0], inputs.shape[-1]))


class RNNCell(_BaseCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0, **kw):
        super().__init__(hidden_size, 1, input_size, **kw)
        self._activation = activation

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        out = F.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class LSTMCell(_BaseCell):
    def __init__(self, hidden_size, input_size=0, **kw):
        super().__init__(hidden_size, 4, input_size, **kw)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        h = self._hidden_size
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias, num_hidden=4 * h)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * h)
        gates = i2h + h2h
        slices = F.op.split(gates, num_outputs=4, axis=1)
        i = F.sigmoid(slices[0])
        f = F.sigmoid(slices[1])
        g = F.tanh(slices[2])
        o = F.sigmoid(slices[3])
        c = f * states[1] + i * g
        out = o * F.tanh(c)
        return out, [out, c]


class GRUCell(_BaseCell):
    def __init__(self, hidden_size, input_size=0, **kw):
        super().__init__(hidden_size, 3, input_size, **kw)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        h = self._hidden_size
        prev = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias, num_hidden=3 * h)
        h2h = F.FullyConnected(prev, h2h_weight, h2h_bias, num_hidden=3 * h)
        i2h_s = F.op.split(i2h, num_outputs=3, axis=1)
        h2h_s = F.op.split(h2h, num_outputs=3, axis=1)
        r = F.sigmoid(i2h_s[0] + h2h_s[0])
        z = F.sigmoid(i2h_s[1] + h2h_s[1])
        n = F.tanh(i2h_s[2] + r * h2h_s[2])
        out = (1 - z) * n + z * prev
        return out, [out]


class SequentialRNNCell(RecurrentCell):
    """(ref: rnn_cell.py SequentialRNNCell)"""

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        out = []
        for cell in self._children.values():
            out.extend(cell.state_info(batch_size))
        return out

    def begin_state(self, batch_size=0, **kwargs):
        out = []
        for cell in self._children.values():
            out.extend(cell.begin_state(batch_size=batch_size, **kwargs))
        return out

    def forward(self, inputs, states):
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            inputs, st = cell(inputs, states[p:p + n])
            next_states.extend(st)
            p += n
        return inputs, next_states

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]


class DropoutCell(RecurrentCell):
    def __init__(self, rate, axes=(), **kw):
        super().__init__(**kw)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def forward(self, inputs, states):
        from ... import ndarray as F
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class BidirectionalCell(RecurrentCell):
    def __init__(self, l_cell, r_cell, **kw):
        super().__init__(**kw)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")

    def state_info(self, batch_size=0):
        return (self._children["l_cell"].state_info(batch_size) +
                self._children["r_cell"].state_info(batch_size))

    def begin_state(self, batch_size=0, **kwargs):
        return (self._children["l_cell"].begin_state(batch_size=batch_size,
                                                     **kwargs) +
                self._children["r_cell"].begin_state(batch_size=batch_size,
                                                     **kwargs))

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell supports only unroll()")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F
        l_cell = self._children["l_cell"]
        r_cell = self._children["r_cell"]
        if begin_state is None:
            batch = inputs.shape[layout.find("N")] \
                if not isinstance(inputs, (list, tuple)) else inputs[0].shape[0]
            begin_state = self.begin_state(batch_size=batch)
        nl = len(l_cell.state_info())
        l_out, l_states = l_cell.unroll(length, inputs,
                                        begin_state[:nl], layout, False)
        if isinstance(inputs, (list, tuple)):
            rev = list(reversed(inputs))
        else:
            rev = F.op.SequenceReverse(inputs.swapaxes(0, 1) if layout == "NTC"
                                       else inputs)
            rev = rev.swapaxes(0, 1) if layout == "NTC" else rev
        r_out, r_states = r_cell.unroll(length, rev, begin_state[nl:],
                                        layout, False)
        r_out = list(reversed(r_out))
        outputs = [F.concatenate([l, r], axis=1)
                   for l, r in zip(l_out, r_out)]
        if merge_outputs:
            outputs = F.stack(*outputs, axis=layout.find("T"))
        return outputs, l_states + r_states


class ResidualCell(RecurrentCell):
    def __init__(self, base_cell, **kw):
        super().__init__(**kw)
        self.register_child(base_cell, "base_cell")

    def state_info(self, batch_size=0):
        return self._children["base_cell"].state_info(batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return self._children["base_cell"].begin_state(batch_size=batch_size,
                                                       **kwargs)

    def forward(self, inputs, states):
        out, states = self._children["base_cell"](inputs, states)
        return out + inputs, states


class ZoneoutCell(RecurrentCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0,
                 **kw):
        super().__init__(**kw)
        self.register_child(base_cell, "base_cell")
        self._zo = zoneout_outputs
        self._zs = zoneout_states
        self._prev_output = None

    def state_info(self, batch_size=0):
        return self._children["base_cell"].state_info(batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return self._children["base_cell"].begin_state(batch_size=batch_size,
                                                       **kwargs)

    def forward(self, inputs, states):
        from ... import ndarray as F
        from ... import autograd
        cell = self._children["base_cell"]
        out, next_states = cell(inputs, states)
        if autograd.is_training():
            if self._zo > 0:
                mask = F.Dropout(F.ones_like(out), p=self._zo) > 0
                prev = self._prev_output if self._prev_output is not None \
                    else F.zeros_like(out)
                out = F.op.where(mask, out, prev)
            if self._zs > 0:
                next_states = [
                    F.op.where(F.Dropout(F.ones_like(ns), p=self._zs) > 0,
                               ns, s)
                    for ns, s in zip(next_states, states)]
        self._prev_output = out
        return out, next_states
