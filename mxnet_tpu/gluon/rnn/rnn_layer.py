"""Gluon fused RNN layers (ref: python/mxnet/gluon/rnn/rnn_layer.py —
the layers that dispatch to the fused RNN op instead of unrolled cells)."""
from __future__ import annotations

from ...base import MXNetError, check
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, mode,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        check(layout in ("TNC", "NTC"), f"invalid layout {layout}")
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        g = _GATES[mode]
        ng = g * hidden_size
        for layer in range(num_layers):
            for d in range(self._dir):
                suffix = ["l", "r"][d] + str(layer)
                in_sz = input_size if layer == 0 else hidden_size * self._dir
                setattr(self, f"{suffix}_i2h_weight", self.params.get(
                    f"{suffix}_i2h_weight", shape=(ng, in_sz),
                    init=i2h_weight_initializer, allow_deferred_init=True))
                setattr(self, f"{suffix}_h2h_weight", self.params.get(
                    f"{suffix}_h2h_weight", shape=(ng, hidden_size),
                    init=h2h_weight_initializer))
                setattr(self, f"{suffix}_i2h_bias", self.params.get(
                    f"{suffix}_i2h_bias", shape=(ng,),
                    init=i2h_bias_initializer))
                setattr(self, f"{suffix}_h2h_bias", self.params.get(
                    f"{suffix}_h2h_bias", shape=(ng,),
                    init=h2h_bias_initializer))

    def infer_shape_from_inputs(self, inputs, *rest):
        in_sz = inputs.shape[2] if self._layout == "TNC" else inputs.shape[2]
        for layer in range(self._num_layers):
            for d in range(self._dir):
                suffix = ["l", "r"][d] + str(layer)
                p = self._params[self._prefix + f"{suffix}_i2h_weight"]
                if layer == 0:
                    p.shape_hint((p.shape[0], in_sz))

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        if self._mode == "lstm":
            return [{"shape": shape}, {"shape": shape}]
        return [{"shape": shape}]

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as F
        func = func or F.zeros
        return [func(info["shape"], **kwargs)
                for info in self.state_info(batch_size)]

    def _pack(self, F, params):
        """Flatten per-layer weights into the fused-op layout
        (weights then biases — ops/rnn_op.py packing)."""
        weights = []
        biases = []
        for layer in range(self._num_layers):
            for d in range(self._dir):
                suffix = ["l", "r"][d] + str(layer)
                weights.append(params[f"{suffix}_i2h_weight"].reshape((-1,)))
                weights.append(params[f"{suffix}_h2h_weight"].reshape((-1,)))
        for layer in range(self._num_layers):
            for d in range(self._dir):
                suffix = ["l", "r"][d] + str(layer)
                biases.append(params[f"{suffix}_i2h_bias"])
                biases.append(params[f"{suffix}_h2h_bias"])
        return F.concatenate(weights + biases, axis=0)

    def hybrid_forward(self, F, inputs, *states, **params):
        states = list(states)
        if states and isinstance(states[0], (list, tuple)):
            states = list(states[0])
        x = inputs
        if self._layout == "NTC":
            x = x.swapaxes(0, 1)
        batch = x.shape[1]
        if not states:
            states = self.begin_state(batch, ctx=None)
        packed = self._pack(F, params)
        args = [x, packed, states[0]]
        if self._mode == "lstm":
            args.append(states[1] if len(states) > 1
                        else F.zeros(states[0].shape))
        outs = F.RNN(*args, state_size=self._hidden_size,
                     num_layers=self._num_layers, mode=self._mode,
                     bidirectional=self._dir == 2, p=self._dropout,
                     state_outputs=True)
        if self._mode == "lstm":
            out, h, c = outs
            new_states = [h, c]
        else:
            out, h = outs
            new_states = [h]
        if self._layout == "NTC":
            out = out.swapaxes(0, 1)
        return out, new_states

    def forward(self, inputs, states=None):
        explicit = states is not None
        if self._active:
            if self._cached_op is None:
                from ...cached_op import CachedOp
                try:
                    self._collect_deferred_check()
                except Exception:
                    self._imperative_call(inputs, states)
                self._cached_op = CachedOp(self)
            out, new_states = self._cached_op(inputs, states) if explicit \
                else self._cached_op(inputs)
        else:
            out, new_states = self._imperative_call(inputs, states) \
                if explicit else self._imperative_call(inputs)
        return (out, new_states) if explicit else out

    def _imperative_call(self, inputs, states=None):
        from ... import ndarray as F
        try:
            params = self._resolved_params()
        except Exception:
            self.infer_shape_from_inputs(
                inputs if self._layout == "TNC" else inputs.swapaxes(0, 1))
            for _, p in self._params.items():
                if p._deferred_init is not None:
                    p._finish_deferred_init()
            params = self._resolved_params()
        if states is None:
            return self.hybrid_forward(F, inputs, **params)
        return self.hybrid_forward(F, inputs, states, **params)

    def __repr__(self):
        return (f"{type(self).__name__}({self._hidden_size}, "
                f"layers={self._num_layers}, bidir={self._dir == 2})")


class RNN(_RNNLayer):
    """(ref: gluon.rnn.RNN)"""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False, input_size=0,
                 **kwargs):
        mode = "rnn_relu" if activation == "relu" else "rnn_tanh"
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, mode, **kwargs)


class LSTM(_RNNLayer):
    """(ref: gluon.rnn.LSTM — the word_language_model workhorse,
    BASELINE config #3)"""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "lstm", **kwargs)


class GRU(_RNNLayer):
    """(ref: gluon.rnn.GRU)"""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "gru", **kwargs)
