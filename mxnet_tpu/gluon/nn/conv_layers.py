"""Gluon convolution and pooling layers
(ref: python/mxnet/gluon/nn/conv_layers.py)."""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D",
           "GlobalMaxPool2D", "GlobalMaxPool3D", "GlobalAvgPool1D",
           "GlobalAvgPool2D", "GlobalAvgPool3D", "ReflectionPad2D"]


def _tup(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", op_name="Convolution", adj=None,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._channels = channels
        self._in_channels = in_channels
        nd = len(kernel_size)
        self._op_name = op_name
        self._layout = layout
        self._channel_axis = layout.index("C") if layout else 1
        self._kwargs = {
            "kernel": kernel_size, "stride": _tup(strides, nd),
            "dilate": _tup(dilation, nd), "pad": _tup(padding, nd),
            "num_filter": channels, "num_group": groups,
            "no_bias": not use_bias, "layout": layout}
        if adj is not None:
            self._kwargs["adj"] = _tup(adj, nd)
        channel_last = bool(layout) and layout.endswith("C")
        if op_name == "Convolution":
            if channel_last:
                # MXNet NHWC weight convention: (O, *k, I/groups)
                wshape = (channels,) + tuple(kernel_size) + \
                    (in_channels // groups,)
            else:
                wshape = (channels, in_channels // groups) + \
                    tuple(kernel_size)
        else:  # Deconvolution: weight is (in, out/g, *k)
            wshape = (in_channels, channels // groups) + tuple(kernel_size)
        self.weight = self.params.get("weight", shape=wshape,
                                      init=weight_initializer,
                                      allow_deferred_init=True)
        if use_bias:
            self.bias = self.params.get("bias", shape=(channels,),
                                        init=bias_initializer)
        else:
            self.bias = None
        self._activation = activation

    def infer_shape_from_inputs(self, x):
        c = x.shape[self._channel_axis]
        w = self.weight
        g = self._kwargs["num_group"]
        if self._op_name == "Convolution":
            if self._layout and self._layout.endswith("C"):
                shape = (w.shape[0],) + w.shape[1:-1] + (c // g,)
            else:
                shape = (w.shape[0], c // g) + w.shape[2:]
        else:
            shape = (c,) + w.shape[1:]
        w.shape_hint(shape)

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        if bias is None:
            out = op(x, weight, **self._kwargs)
        else:
            out = op(x, weight, bias, **self._kwargs)
        if self._activation:
            out = F.Activation(out, act_type=self._activation)
        return out

    def __repr__(self):
        return (f"{type(self).__name__}({self._channels}, "
                f"kernel={self._kwargs['kernel']})")


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kw):
        super().__init__(channels, _tup(kernel_size, 1), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer, **kw)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kw):
        super().__init__(channels, _tup(kernel_size, 2), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer, **kw)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kw):
        super().__init__(channels, _tup(kernel_size, 3), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer, **kw)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kw):
        super().__init__(channels, _tup(kernel_size, 1), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         op_name="Deconvolution", adj=output_padding, **kw)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kw):
        super().__init__(channels, _tup(kernel_size, 2), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         op_name="Deconvolution", adj=output_padding, **kw)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kw):
        super().__init__(channels, _tup(kernel_size, 3), strides, padding,
                         dilation, groups, layout, in_channels, activation,
                         use_bias, weight_initializer, bias_initializer,
                         op_name="Deconvolution", adj=output_padding, **kw)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, layout=None, count_include_pad=None,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if strides is None:
            strides = pool_size
        nd = len(pool_size)
        self._kwargs = {
            "kernel": pool_size, "stride": _tup(strides, nd),
            "pad": _tup(padding, nd), "pool_type": pool_type,
            "global_pool": global_pool,
            "pooling_convention": "full" if ceil_mode else "valid"}
        if layout is not None:
            self._kwargs["layout"] = layout
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs['kernel']})"


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kw):
        super().__init__(_tup(pool_size, 1), strides, padding, ceil_mode,
                         False, "max", layout=layout, **kw)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kw):
        super().__init__(_tup(pool_size, 2), strides, padding, ceil_mode,
                         False, "max", layout=layout, **kw)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kw):
        super().__init__(_tup(pool_size, 3), strides, padding, ceil_mode,
                         False, "max", layout=layout, **kw)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kw):
        super().__init__(_tup(pool_size, 1), strides, padding, ceil_mode,
                         False, "avg", layout=layout,
                         count_include_pad=count_include_pad, **kw)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True, **kw):
        super().__init__(_tup(pool_size, 2), strides, padding, ceil_mode,
                         False, "avg", layout=layout,
                         count_include_pad=count_include_pad, **kw)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 **kw):
        super().__init__(_tup(pool_size, 3), strides, padding, ceil_mode,
                         False, "avg", layout=layout,
                         count_include_pad=count_include_pad, **kw)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", **kw):
        super().__init__((1,), None, 0, False, True, "max", layout=layout,
                         **kw)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kw):
        super().__init__((1, 1), None, 0, False, True, "max", layout=layout,
                         **kw)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kw):
        super().__init__((1, 1, 1), None, 0, False, True, "max",
                         layout=layout, **kw)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", **kw):
        super().__init__((1,), None, 0, False, True, "avg", layout=layout,
                         **kw)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kw):
        super().__init__((1, 1), None, 0, False, True, "avg", layout=layout,
                         **kw)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kw):
        super().__init__((1, 1, 1), None, 0, False, True, "avg",
                         layout=layout, **kw)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        self._padding = tuple(padding)

    def hybrid_forward(self, F, x):
        return F.Pad(x, mode="reflect", pad_width=self._padding)
