"""Gluon basic layers (ref: python/mxnet/gluon/nn/basic_layers.py)."""
from __future__ import annotations

from ...base import MXNetError, check
from ..block import Block, HybridBlock
from ..parameter import DeferredInitializationError

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "InstanceNorm", "LayerNorm", "Embedding", "Flatten", "Lambda",
           "HybridLambda"]


class Sequential(Block):
    """Sequentially stacked blocks (ref: nn.Sequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def forward(self, x):
        for child in self._children.values():
            x = child(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, idx):
        return list(self._children.values())[idx]

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(HybridBlock):
    """Sequential that compiles to one XLA program when hybridized."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def _imperative_call(self, x):
        for child in self._children.values():
            x = child._imperative_call(x) if isinstance(child, HybridBlock) \
                else child(x)
        return x

    def hybrid_forward(self, F, x):
        for child in self._children.values():
            x = child(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, idx):
        return list(self._children.values())[idx]

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer (ref: nn.Dense -> FullyConnected op)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._in_units = in_units
        self._flatten = flatten
        self._use_bias = use_bias
        self._activation = activation
        self.weight = self.params.get("weight", shape=(units, in_units),
                                      init=weight_initializer, dtype=dtype,
                                      allow_deferred_init=True)
        if use_bias:
            self.bias = self.params.get("bias", shape=(units,),
                                        init=bias_initializer, dtype=dtype)
        else:
            self.bias = None

    def infer_shape_from_inputs(self, x):
        in_units = 1
        if self._flatten:
            for s in x.shape[1:]:
                in_units *= s
        else:
            in_units = x.shape[-1]
        self.weight.shape_hint((self._units, in_units))

    def hybrid_forward(self, F, x, weight, bias=None):
        if bias is None:
            out = F.FullyConnected(x, weight, num_hidden=self._units,
                                   no_bias=True, flatten=self._flatten)
        else:
            out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                                   no_bias=False, flatten=self._flatten)
        if self._activation is not None:
            out = F.Activation(out, act_type=self._activation)
        return out

    def __repr__(self):
        return f"Dense({self._units}, act={self._activation})"


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = tuple(axes)

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes)

    def __repr__(self):
        return f"Dropout(p={self._rate})"


class BatchNorm(HybridBlock):
    """(ref: nn.BatchNorm; moving stats updated functionally — see
    ops/nn.py BatchNorm docstring)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        self.gamma = self.params.get("gamma",
                                     grad_req="write" if scale else "null",
                                     shape=(in_channels,),
                                     init=gamma_initializer,
                                     allow_deferred_init=True)
        self.beta = self.params.get("beta",
                                    grad_req="write" if center else "null",
                                    shape=(in_channels,),
                                    init=beta_initializer,
                                    allow_deferred_init=True)
        self.running_mean = self.params.get("running_mean", grad_req="null",
                                            shape=(in_channels,),
                                            init=running_mean_initializer,
                                            allow_deferred_init=True,
                                            differentiable=False)
        self.running_var = self.params.get("running_var", grad_req="null",
                                           shape=(in_channels,),
                                           init=running_variance_initializer,
                                           allow_deferred_init=True,
                                           differentiable=False)

    def infer_shape_from_inputs(self, x):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape_hint((c,))

    def _update_running_stats(self, running_mean, running_var, mean, var):
        """Momentum-blend the batch stats into the running buffers
        (training mode only) — the functional replacement for the
        reference's in-op aux-state mutation; shared with the fused
        epilogue subclasses (fused.py)."""
        from ... import autograd
        if autograd.is_training() and not self._use_global_stats:
            with autograd.pause():
                m = self._momentum
                running_mean._rebind((running_mean * m + mean * (1 - m))._data)
                running_var._rebind((running_var * m + var * (1 - m))._data)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        out, mean, var = F.BatchNorm(
            x, gamma, beta, running_mean, running_var,
            eps=self._epsilon, momentum=self._momentum,
            fix_gamma=not self._scale,
            use_global_stats=self._use_global_stats, axis=self._axis)
        self._update_running_stats(running_mean, running_var, mean, var)
        return out

    def __repr__(self):
        return f"BatchNorm(axis={self._axis})"


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._epsilon = epsilon
        self.gamma = self.params.get("gamma",
                                     grad_req="write" if scale else "null",
                                     shape=(in_channels,),
                                     init=gamma_initializer,
                                     allow_deferred_init=True)
        self.beta = self.params.get("beta",
                                    grad_req="write" if center else "null",
                                    shape=(in_channels,),
                                    init=beta_initializer,
                                    allow_deferred_init=True)

    def infer_shape_from_inputs(self, x):
        c = x.shape[1]
        self.gamma.shape_hint((c,))
        self.beta.shape_hint((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._epsilon = epsilon
        self.gamma = self.params.get("gamma",
                                     grad_req="write" if scale else "null",
                                     shape=(in_channels,),
                                     init=gamma_initializer,
                                     allow_deferred_init=True)
        self.beta = self.params.get("beta",
                                    grad_req="write" if center else "null",
                                    shape=(in_channels,),
                                    init=beta_initializer,
                                    allow_deferred_init=True)

    def infer_shape_from_inputs(self, x):
        c = x.shape[self._axis]
        self.gamma.shape_hint((c,))
        self.beta.shape_hint((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        out, _, _ = F.LayerNorm(x, gamma, beta, axis=self._axis,
                                eps=self._epsilon)
        return out


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = sparse_grad
        self.weight = self.params.get(
            "weight", shape=(input_dim, output_dim),
            init=weight_initializer, dtype=dtype,
            grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim,
                           sparse_grad=self._sparse_grad)

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.flatten(x)

    def __repr__(self):
        return "Flatten"


class Lambda(Block):
    """(ref: nn.Lambda)"""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as F
            function = getattr(F, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        self._func_name = function if isinstance(function, str) else None
        self._func = function

    def hybrid_forward(self, F, *args):
        f = getattr(F, self._func_name) if self._func_name else self._func
        if self._func_name is None:
            return f(F, *args)
        return f(*args)
