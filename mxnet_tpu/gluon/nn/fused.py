"""Fused conv-epilogue layers: BatchNorm(+residual add)+ReLU as one block.

The ResNet bottleneck hot path writes the conv output to HBM and then
reads it back for BatchNorm statistics, again for the normalize, and the
normalized copy again for the ReLU/add — the traffic docs/perf.md's
roofline names as the training-step ceiling. These layers route the
whole epilogue through the fused Pallas kernels
(ops/pallas_kernels.py fused_bn_act via the _contrib_fused_bn_relu /
_contrib_fused_bn_add_relu ops), gated at trace time by
MXTPU_FUSED_EPILOGUE.

Both subclass BatchNorm so they hold the standard gamma/beta/running_*
parameters and so graph passes that match
``isinstance(block, BatchNorm)`` — notably the int8 BN-folding pass
(contrib/quantization.py fold_batchnorm) — keep working; the
``_epilogue`` attribute tells such passes which tail (relu / add+relu)
must survive the fold.

Checkpoint note: each fused block's OWN parameter set is exactly a
BatchNorm's, but adopting them in the V1 ResNet Sequential bodies
removes the separate Activation children, so the index-based child
paths of ``save_parameters`` checkpoints shift (e.g. old
``body.3.weight`` -> ``body.2.weight``). Channel-last V1 checkpoints
saved before the adoption need a one-time key remap to load.
"""
from __future__ import annotations

from .basic_layers import BatchNorm

__all__ = ["FusedBatchNormReLU", "FusedBatchNormAddReLU"]


class FusedBatchNormReLU(BatchNorm):
    """``relu(BatchNorm(x))`` in one fused op (conv -> BN -> ReLU
    epilogue). Same parameters/semantics as ``BatchNorm`` + ``Activation
    ('relu')``; channel-last input is required for the Pallas path (the
    op falls back to the composed lowering otherwise)."""

    _epilogue = "relu"

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        out, mean, var = F.contrib.fused_bn_relu(
            x, gamma, beta, running_mean, running_var,
            eps=self._epsilon, momentum=self._momentum,
            fix_gamma=not self._scale,
            use_global_stats=self._use_global_stats, axis=self._axis)
        self._update_running_stats(running_mean, running_var, mean, var)
        return out

    def __repr__(self):
        return f"{type(self).__name__}(axis={self._axis})"


class FusedBatchNormAddReLU(BatchNorm):
    """``relu(BatchNorm(x) + residual)`` in one fused op — the ResNet
    block tail. Called with two inputs: ``block(x, residual)``."""

    _epilogue = "add_relu"

    def infer_shape_from_inputs(self, x, residual=None):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean,
                  self.running_var):
            p.shape_hint((c,))

    def hybrid_forward(self, F, x, residual, gamma, beta, running_mean,
                       running_var):
        out, mean, var = F.contrib.fused_bn_add_relu(
            x, residual, gamma, beta, running_mean, running_var,
            eps=self._epsilon, momentum=self._momentum,
            fix_gamma=not self._scale,
            use_global_stats=self._use_global_stats, axis=self._axis)
        self._update_running_stats(running_mean, running_var, mean, var)
        return out

    def __repr__(self):
        return f"{type(self).__name__}(axis={self._axis})"
