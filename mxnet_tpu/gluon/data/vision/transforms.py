"""Vision transforms (ref: python/mxnet/gluon/data/vision/transforms.py).

Implemented over nd ops (numpy-free where possible) so transforms can also
run inside compiled pipelines.
"""
from __future__ import annotations

import numpy as np

from ....base import MXNetError, check
from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomResizedCrop", "RandomFlipLeftRight",
           "RandomFlipTopBottom", "RandomBrightness", "RandomContrast",
           "RandomSaturation", "RandomLighting", "RandomColorJitter"]


class Compose(Sequential):
    """(ref: transforms.py Compose)"""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.cast(x, dtype=self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (ref: ToTensor)."""

    def hybrid_forward(self, F, x):
        x = F.cast(x, dtype="float32") / 255.0
        if x.ndim == 3:
            return F.transpose(x, axes=(2, 0, 1))
        return F.transpose(x, axes=(0, 3, 1, 2))


class Normalize(HybridBlock):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = mean
        self._std = std

    def hybrid_forward(self, F, x):
        import numpy as _np
        mean = _np.asarray(self._mean, _np.float32).reshape(-1, 1, 1)
        std = _np.asarray(self._std, _np.float32).reshape(-1, 1, 1)
        from ....ndarray import array
        return (x - array(mean)) / array(std)


class Resize(Block):
    """Bilinear resize (ref: Resize; image_io/resize)."""

    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        import jax
        from ....ndarray import from_jax
        data = x._data
        h, w = self._size[1], self._size[0]
        if data.ndim == 3:
            out = jax.image.resize(data.astype("float32"),
                                   (h, w, data.shape[2]), "bilinear")
        else:
            out = jax.image.resize(data.astype("float32"),
                                   (data.shape[0], h, w, data.shape[3]),
                                   "bilinear")
        return from_jax(out.astype(data.dtype))


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        w, h = self._size
        H, W = x.shape[-3], x.shape[-2]
        y0 = max(0, (H - h) // 2)
        x0 = max(0, (W - w) // 2)
        return x[..., y0:y0 + h, x0:x0 + w, :]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio
        self._resize = Resize(self._size)

    def forward(self, x):
        H, W = x.shape[-3], x.shape[-2]
        area = H * W
        for _ in range(10):
            target = np.random.uniform(*self._scale) * area
            ratio = np.random.uniform(*self._ratio)
            w = int(round(np.sqrt(target * ratio)))
            h = int(round(np.sqrt(target / ratio)))
            if w <= W and h <= H:
                x0 = np.random.randint(0, W - w + 1)
                y0 = np.random.randint(0, H - h + 1)
                crop = x[..., y0:y0 + h, x0:x0 + w, :]
                return self._resize(crop)
        return self._resize(x)


class _RandomFlip(Block):
    _axis = -2

    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        if np.random.rand() < self._p:
            return x.flip(axis=x.ndim + self._axis)
        return x


class RandomFlipLeftRight(_RandomFlip):
    _axis = -2


class RandomFlipTopBottom(_RandomFlip):
    _axis = -3


class _ColorJitterBase(Block):
    def __init__(self, magnitude):
        super().__init__()
        self._m = magnitude

    def _alpha(self):
        return 1.0 + np.random.uniform(-self._m, self._m)


class RandomBrightness(_ColorJitterBase):
    def forward(self, x):
        return (x.astype("float32") * self._alpha()).clip(0, 255) \
            .astype(x.dtype)


class RandomContrast(_ColorJitterBase):
    def forward(self, x):
        alpha = self._alpha()
        xf = x.astype("float32")
        gray = xf.mean()
        return (xf * alpha + gray * (1 - alpha)).clip(0, 255).astype(x.dtype)


class RandomSaturation(_ColorJitterBase):
    def forward(self, x):
        alpha = self._alpha()
        xf = x.astype("float32")
        gray = xf.mean(axis=-1, keepdims=True)
        return (xf * alpha + gray * (1 - alpha)).clip(0, 255).astype(x.dtype)


class RandomLighting(_ColorJitterBase):
    """AlexNet-style PCA noise (ref: RandomLighting)."""

    _eigval = np.array([55.46, 4.794, 1.148], np.float32)
    _eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                        [-0.5808, -0.0045, -0.8140],
                        [-0.5836, -0.6948, 0.4203]], np.float32)

    def forward(self, x):
        alpha = np.random.normal(0, self._m, 3).astype(np.float32)
        rgb = (self._eigvec @ (alpha * self._eigval)).astype(np.float32)
        from ....ndarray import array
        return (x.astype("float32") + array(rgb)).clip(0, 255).astype(x.dtype)


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))

    def forward(self, x):
        order = np.random.permutation(len(self._ts))
        for i in order:
            x = self._ts[i](x)
        return x
