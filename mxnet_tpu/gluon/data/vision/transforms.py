"""Vision transforms (ref: python/mxnet/gluon/data/vision/transforms.py).

Like the reference, these Blocks are thin wrappers over the ``mx.nd.image``
operators (src/operator/image/) so the exact same kernels serve both the
transform pipeline and direct op calls; random transforms draw from the
global ``mx.random`` key stream.
"""
from __future__ import annotations

import numpy as np

from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomResizedCrop", "RandomFlipLeftRight",
           "RandomFlipTopBottom", "RandomBrightness", "RandomContrast",
           "RandomSaturation", "RandomHue", "RandomLighting",
           "RandomColorJitter"]


def _image():
    from .... import ndarray as nd
    return nd.image


class Compose(Sequential):
    """(ref: transforms.py Compose)"""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.cast(x, dtype=self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (ref: ToTensor)."""

    def hybrid_forward(self, F, x):
        return F._internal._image_to_tensor(x)


class Normalize(HybridBlock):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = mean if isinstance(mean, (tuple, list)) else (mean,)
        self._std = std if isinstance(std, (tuple, list)) else (std,)

    def hybrid_forward(self, F, x):
        return F._internal._image_normalize(x, mean=tuple(self._mean),
                                            std=tuple(self._std))


class Resize(Block):
    """Resize via the _image_resize op (ref: Resize)."""

    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interp = interpolation

    def forward(self, x):
        return _image().resize(x, size=self._size, keep_ratio=self._keep,
                               interp=self._interp)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        w, h = self._size
        H, W = x.shape[-3], x.shape[-2]
        y0 = max(0, (H - h) // 2)
        x0 = max(0, (W - w) // 2)
        return x[..., y0:y0 + h, x0:x0 + w, :]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio
        self._interp = interpolation

    def forward(self, x):
        H, W = x.shape[-3], x.shape[-2]
        area = H * W
        crop = x
        for _ in range(10):
            target = np.random.uniform(*self._scale) * area
            ratio = np.random.uniform(*self._ratio)
            w = int(round(np.sqrt(target * ratio)))
            h = int(round(np.sqrt(target / ratio)))
            if w <= W and h <= H:
                x0 = np.random.randint(0, W - w + 1)
                y0 = np.random.randint(0, H - h + 1)
                crop = x[..., y0:y0 + h, x0:x0 + w, :]
                break
        return _image().resize(crop, size=(self._size[0], self._size[1]),
                               interp=self._interp)


class RandomFlipLeftRight(Block):
    def forward(self, x):
        return _image().random_flip_left_right(x)


class RandomFlipTopBottom(Block):
    def forward(self, x):
        return _image().random_flip_top_bottom(x)


class _RandomEnhance(Block):
    """factor m -> uniform alpha in [max(0, 1-m), 1+m] like the reference."""

    def __init__(self, magnitude):
        super().__init__()
        self._lo = max(0.0, 1.0 - magnitude)
        self._hi = 1.0 + magnitude


class RandomBrightness(_RandomEnhance):
    def forward(self, x):
        return _image().random_brightness(x, min_factor=self._lo,
                                          max_factor=self._hi)


class RandomContrast(_RandomEnhance):
    def forward(self, x):
        return _image().random_contrast(x, min_factor=self._lo,
                                        max_factor=self._hi)


class RandomSaturation(_RandomEnhance):
    def forward(self, x):
        return _image().random_saturation(x, min_factor=self._lo,
                                          max_factor=self._hi)


class RandomHue(Block):
    def __init__(self, hue):
        super().__init__()
        self._hue = hue

    def forward(self, x):
        return _image().random_hue(x, min_factor=-self._hue,
                                   max_factor=self._hue)


class RandomLighting(Block):
    """AlexNet-style PCA noise (ref: RandomLighting(alpha))."""

    def __init__(self, alpha):
        super().__init__()
        self._std = alpha

    def forward(self, x):
        return _image().random_lighting(x, alpha_std=self._std)


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._args = dict(brightness=brightness, contrast=contrast,
                          saturation=saturation, hue=hue)

    def forward(self, x):
        return _image().random_color_jitter(x, **self._args)
