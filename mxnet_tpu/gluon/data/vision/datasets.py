"""Vision datasets (ref: python/mxnet/gluon/data/vision/datasets.py).

Zero-egress environment: datasets read local files (standard idx/binary
formats); download paths raise with a clear message.
"""
from __future__ import annotations

import gzip
import os
import struct
from typing import Optional

import numpy as np

from ....base import MXNetError, check
from ..dataset import Dataset, ArrayDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "SyntheticImageDataset"]


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        from ....base import data_dir
        marker = os.path.join("~", ".mxnet")
        if root.startswith(marker):
            # default roots re-anchor onto $MXNET_HOME when set
            root = os.path.join(data_dir(), os.path.relpath(root, marker))
        self._root = os.path.expanduser(root)
        self._transform = transform
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        from ....ndarray import array
        x = array(self._data[idx])
        y = self._label[idx]
        if self._transform is not None:
            return self._transform(x, y)
        return x, y

    def __len__(self):
        return len(self._label)


class MNIST(_DownloadedDataset):
    """(ref: datasets.py MNIST — idx file format)"""

    def __init__(self, root="~/.mxnet/datasets/mnist", train=True,
                 transform=None):
        self._train = train
        super().__init__(root, transform)

    def _get_data(self):
        prefix = "train" if self._train else "t10k"
        img_path = os.path.join(self._root, f"{prefix}-images-idx3-ubyte")
        lbl_path = os.path.join(self._root, f"{prefix}-labels-idx1-ubyte")

        def _open(p):
            if os.path.exists(p):
                return open(p, "rb")
            if os.path.exists(p + ".gz"):
                return gzip.open(p + ".gz", "rb")
            raise MXNetError(
                f"MNIST file {p} not found (downloads disabled; place idx "
                "files locally or use SyntheticImageDataset)")

        with _open(lbl_path) as f:
            struct.unpack(">II", f.read(8))
            self._label = np.frombuffer(f.read(), dtype=np.uint8) \
                .astype(np.int32)
        with _open(img_path) as f:
            _, n, rows, cols = struct.unpack(">IIII", f.read(16))
            self._data = np.frombuffer(f.read(), dtype=np.uint8) \
                .reshape(n, rows, cols, 1)


class FashionMNIST(MNIST):
    def __init__(self, root="~/.mxnet/datasets/fashion-mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """(ref: datasets.py CIFAR10 — binary batch format)"""

    def __init__(self, root="~/.mxnet/datasets/cifar10", train=True,
                 transform=None):
        self._train = train
        super().__init__(root, transform)

    def _file_list(self):
        if self._train:
            return [f"data_batch_{i}.bin" for i in range(1, 6)]
        return ["test_batch.bin"]

    def _read_batch(self, filename):
        if not os.path.exists(filename):
            raise MXNetError(f"CIFAR file {filename} not found "
                             "(downloads disabled)")
        with open(filename, "rb") as f:
            raw = np.frombuffer(f.read(), dtype=np.uint8)
        rec = raw.reshape(-1, 3073)
        return rec[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1), \
            rec[:, 0].astype(np.int32)

    def _get_data(self):
        data, label = [], []
        for name in self._file_list():
            d, l = self._read_batch(os.path.join(self._root, name))
            data.append(d)
            label.append(l)
        self._data = np.concatenate(data)
        self._label = np.concatenate(label)


class CIFAR100(CIFAR10):
    def __init__(self, root="~/.mxnet/datasets/cifar100", fine_label=False,
                 train=True, transform=None):
        self._fine = fine_label
        super().__init__(root, train, transform)

    def _file_list(self):
        return ["train.bin"] if self._train else ["test.bin"]

    def _read_batch(self, filename):
        if not os.path.exists(filename):
            raise MXNetError(f"CIFAR file {filename} not found")
        with open(filename, "rb") as f:
            raw = np.frombuffer(f.read(), dtype=np.uint8)
        rec = raw.reshape(-1, 3074)
        lbl_col = 1 if self._fine else 0
        return rec[:, 2:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1), \
            rec[:, lbl_col].astype(np.int32)


class ImageRecordDataset(Dataset):
    """Dataset over a packed image .rec (ref: ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        from ..dataset import RecordFileDataset
        self._record = RecordFileDataset(filename)
        self._transform = transform

    def __len__(self):
        return len(self._record)

    def __getitem__(self, idx):
        from ....recordio import unpack_img
        from ....ndarray import array
        header, img = unpack_img(self._record[idx])
        x = array(img)
        label = header.label if header.flag else float(header.label)
        if self._transform is not None:
            return self._transform(x, label)
        return x, label


class SyntheticImageDataset(Dataset):
    """Deterministic synthetic images — the zero-egress stand-in for
    benchmarks and tests (no reference analog; this environment cannot
    download)."""

    def __init__(self, num_samples=1024, shape=(32, 32, 3), classes=10,
                 seed=0, transform=None):
        rs = np.random.RandomState(seed)
        self._label = rs.randint(0, classes, num_samples).astype(np.int32)
        centers = rs.rand(classes, *shape) * 255
        noise = rs.rand(num_samples, *shape) * 64
        self._data = np.clip(centers[self._label] + noise, 0,
                             255).astype(np.uint8)
        self._transform = transform

    def __len__(self):
        return len(self._label)

    def __getitem__(self, idx):
        from ....ndarray import array
        x = array(self._data[idx])
        if self._transform is not None:
            return self._transform(x, self._label[idx])
        return x, self._label[idx]
