"""Gluon vision data (ref: python/mxnet/gluon/data/vision/__init__.py)."""
from .datasets import (MNIST, FashionMNIST, CIFAR10, CIFAR100,  # noqa
                       ImageRecordDataset, SyntheticImageDataset)
from . import transforms  # noqa: F401
