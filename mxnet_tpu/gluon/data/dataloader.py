"""DataLoader (ref: python/mxnet/gluon/data/dataloader.py:595).

The reference forks worker processes that exchange NDArrays over POSIX
shared memory (ForkingPickler reductions :26-68, backed by
cpu_shared_storage_manager.h). TPU-native: batches are assembled on the host
with a *thread* pool — the heavy lifting (augmentation) is numpy which
releases the GIL, and the device transfer is one ``device_put`` per batch;
multiprocess + shm adds copies without wins here. ``num_workers`` therefore
sizes a thread pool. Batchify semantics match the reference.
"""
from __future__ import annotations

import concurrent.futures
from typing import Any, Callable, List, Optional

import numpy as np

from ...base import MXNetError, check
from ...ndarray import ndarray as _nd
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (ref: dataloader.py default_batchify_fn)."""
    if isinstance(data[0], _nd.NDArray):
        return _nd.stack(*data, axis=0)
    if isinstance(data[0], tuple):
        return tuple(default_batchify_fn(list(x)) for x in zip(*data))
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return _nd.array(arr, dtype=arr.dtype)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=True):
        self._dataset = dataset
        if batch_sampler is None:
            check(batch_size is not None,
                  "batch_size required when batch_sampler is None")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle conflicts with explicit sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                        last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None or
              last_batch is not None):
            raise MXNetError("batch_sampler conflicts with batch_size/"
                             "shuffle/sampler/last_batch")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)

    def __len__(self):
        return len(self._batch_sampler)

    def _load(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._load(indices)
            return
        with concurrent.futures.ThreadPoolExecutor(self._num_workers) as ex:
            pending = []
            it = iter(self._batch_sampler)
            try:
                for _ in range(self._prefetch or self._num_workers):
                    pending.append(ex.submit(self._load, next(it)))
            except StopIteration:
                pass
            while pending:
                fut = pending.pop(0)
                try:
                    pending.append(ex.submit(self._load, next(it)))
                except StopIteration:
                    pass
                yield fut.result()
