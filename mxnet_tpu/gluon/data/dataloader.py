"""DataLoader (ref: python/mxnet/gluon/data/dataloader.py:595).

Worker modes:
- ``num_workers > 0`` (default ``thread_pool=False``): forked worker
  PROCESSES assemble batches and ship them back zero-copy through POSIX
  shared memory (``multiprocessing.shared_memory`` — the
  ForkingPickler/cpu_shared_storage_manager.h analog, dataloader.py:26-68).
  Python-side decode/augment code runs truly in parallel, not under one
  GIL.
- ``thread_pool=True``: the round-2 thread pool (fine when transforms are
  GIL-releasing numpy).
- ``pin_memory=True``: the parent eagerly stages each reassembled batch
  onto the default device (the DeviceStagingIter handoff), overlapping
  H2D with worker compute.

Constraint shared with the reference's process workers: samples crossing
the process boundary must be host data (numpy/python); device arrays
cannot survive a fork (the reference has the same rule for GPU NDArrays).
"""
from __future__ import annotations

import concurrent.futures
import multiprocessing as _mp
import traceback
from typing import Any, Callable, List, Optional

import numpy as np

from ...base import MXNetError, check
from ...ndarray import ndarray as _nd
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (ref: dataloader.py default_batchify_fn)."""
    if isinstance(data[0], _nd.NDArray):
        return _nd.stack(*data, axis=0)
    if isinstance(data[0], tuple):
        return tuple(default_batchify_fn(list(x)) for x in zip(*data))
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return _nd.array(arr, dtype=arr.dtype)


def _np_batchify(data):
    """Worker-side batchify: pure numpy (no device arrays in children)."""
    if isinstance(data[0], tuple):
        return tuple(_np_batchify(list(x)) for x in zip(*data))
    arr = np.stack([np.asarray(d) for d in data]) if \
        getattr(data[0], "ndim", 0) else np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return arr


def _tree_to_shm(tree):
    """np-array tree -> shm segment descriptors (one segment per array)."""
    from multiprocessing import shared_memory
    if isinstance(tree, tuple):
        return tuple(_tree_to_shm(t) for t in tree)
    if isinstance(tree, _nd.NDArray):  # custom batchify returning NDArray
        tree = tree.asnumpy()
    arr = np.ascontiguousarray(tree)
    shm = shared_memory.SharedMemory(create=True, size=max(arr.nbytes, 1))
    if arr.nbytes:
        # write straight into the mapped segment (no tobytes() staging)
        np.ndarray(arr.shape, arr.dtype, buffer=shm.buf)[...] = arr
    desc = ("__shm__", shm.name, arr.shape, arr.dtype.str)
    # ownership transfers to the parent (it unlinks after reading):
    # unregister from this process's resource tracker so worker exit
    # doesn't double-unlink (cpython's shared_memory fork-ownership wart)
    try:
        from multiprocessing import resource_tracker
        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass
    shm.close()
    return desc


def _unlink_tree(desc):
    """Free the segments of an unconsumed payload (early iterator exit)."""
    from multiprocessing import shared_memory
    if isinstance(desc, tuple) and (not desc or desc[0] != "__shm__"):
        for d in desc:
            _unlink_tree(d)
        return
    try:
        shm = shared_memory.SharedMemory(name=desc[1])
        shm.close()
        shm.unlink()
    except Exception:
        pass


def _tree_from_shm(desc, pin_memory):
    from multiprocessing import shared_memory
    if isinstance(desc, tuple) and (not desc or desc[0] != "__shm__"):
        return tuple(_tree_from_shm(d, pin_memory) for d in desc)
    _, name, shape, dtype = desc
    shm = shared_memory.SharedMemory(name=name)
    try:
        n = int(np.prod(shape)) if shape else 1
        view = np.frombuffer(shm.buf, dtype=np.dtype(dtype),
                             count=n).reshape(shape)
        host = view.copy()  # one host copy: CPU backends may otherwise
        del view            # alias the shm buffer past its lifetime
        if pin_memory:
            # eager device staging (DeviceStagingIter handoff): the H2D
            # transfer overlaps with the workers producing the next batch
            import jax
            out = _nd.from_jax(jax.device_put(host))
        else:
            out = _nd.array(host)
    finally:
        shm.close()
        shm.unlink()
    return out


def _worker_loop(dataset, batchify_payload, task_q, result_q):
    # a custom batchify crosses the process boundary as a pickle (the
    # ForkingPickler analog, ref dataloader.py:26-68): loading it HERE
    # builds fresh objects in the child instead of aliasing whatever the
    # parent's closure captured
    if isinstance(batchify_payload, bytes):
        import pickle
        batchify_fn = pickle.loads(batchify_payload)
    else:
        batchify_fn = batchify_payload
    while True:
        job = task_q.get()
        if job is None:
            return
        bidx, indices = job
        try:
            batch = batchify_fn([dataset[i] for i in indices])
            result_q.put((bidx, _tree_to_shm(batch), None))
        except Exception:
            result_q.put((bidx, None, traceback.format_exc()))


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=None):
        self._dataset = dataset
        if batch_sampler is None:
            check(batch_size is not None,
                  "batch_size required when batch_sampler is None")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle conflicts with explicit sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                        last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None or
              last_batch is not None):
            raise MXNetError("batch_sampler conflicts with batch_size/"
                             "shuffle/sampler/last_batch")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._pin_memory = pin_memory
        # thread_pool=None (default): process workers whenever the
        # batchify_fn can cross the fork as a pickle (the reference ships
        # ANY batchify through ForkingPickler, dataloader.py:26-68);
        # non-picklable callables (lambdas, closures over live state)
        # fall back to thread workers WITH a warning — silent GIL
        # serialization of detection/padding batchifies was round-3's
        # weak finding #6
        self._thread_pool = thread_pool
        self._mode = None
        self._batchify_pickle = None
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)

    def __len__(self):
        return len(self._batch_sampler)

    def _load(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def _worker_mode(self) -> str:
        """'serial' | 'thread' | 'process' (decided once, cached).

        Custom batchifies returning NDArrays are reduced to numpy INSIDE
        the child (_tree_to_shm) — like the reference, the contract is
        that user batchify code produces host data; device-array work
        belongs after the loader (the module docstring's fork rule)."""
        if self._mode is not None:
            return self._mode
        if self._num_workers == 0:
            self._mode = "serial"
        elif self._thread_pool is not None:
            self._mode = "thread" if self._thread_pool else "process"
        elif self._batchify_fn is not default_batchify_fn:
            self._mode = "process"
            import pickle
            try:
                self._batchify_pickle = pickle.dumps(self._batchify_fn)
            except Exception:
                import warnings
                warnings.warn(
                    "DataLoader: custom batchify_fn is not picklable; "
                    "falling back to GIL-bound thread workers. Define the "
                    "callable at module top level (not a lambda/closure) "
                    "to enable process workers.", stacklevel=2)
                self._mode = "thread"
        else:
            self._mode = "process"
        return self._mode

    def __iter__(self):
        mode = self._worker_mode()
        if mode == "serial":
            for indices in self._batch_sampler:
                yield self._load(indices)
            return
        if mode == "thread":
            yield from self._iter_threads()
        else:
            yield from self._iter_processes()

    def _iter_threads(self):
        with concurrent.futures.ThreadPoolExecutor(self._num_workers) as ex:
            pending = []
            it = iter(self._batch_sampler)
            try:
                for _ in range(self._prefetch or self._num_workers):
                    pending.append(ex.submit(self._load, next(it)))
            except StopIteration:
                pass
            while pending:
                fut = pending.pop(0)
                try:
                    pending.append(ex.submit(self._load, next(it)))
                except StopIteration:
                    pass
                yield fut.result()

    def _iter_processes(self):
        """Forked workers + shared-memory transport (ref:
        dataloader.py:595 _MultiWorkerIter)."""
        ctx = _mp.get_context("fork")
        task_q = ctx.SimpleQueue()
        result_q = ctx.Queue()
        if self._batchify_fn is default_batchify_fn:
            batchify = _np_batchify
        else:
            if self._batchify_pickle is None:
                # explicit thread_pool=False skipped the auto-mode pickle
                # attempt: still prefer shipping a pickle (fresh objects
                # in the child, no parent-closure aliasing); only an
                # unpicklable callable rides fork inheritance
                import pickle
                try:
                    self._batchify_pickle = pickle.dumps(self._batchify_fn)
                except Exception:
                    pass
            batchify = self._batchify_pickle or self._batchify_fn
        workers = [ctx.Process(target=_worker_loop,
                               args=(self._dataset, batchify, task_q,
                                     result_q), daemon=True)
                   for _ in range(self._num_workers)]
        for w in workers:
            w.start()
        try:
            it = iter(self._batch_sampler)
            sent = 0
            received = 0
            buffered = {}
            depth = self._prefetch or self._num_workers

            def send_next():
                nonlocal sent
                try:
                    task_q.put((sent, next(it)))
                    sent += 1
                    return True
                except StopIteration:
                    return False

            for _ in range(depth):
                if not send_next():
                    break
            import queue as _queue
            while received < sent:
                while received not in buffered:
                    try:
                        bidx, payload, err = result_q.get(timeout=5.0)
                    except _queue.Empty:
                        dead = [w for w in workers if not w.is_alive()]
                        if dead:
                            raise MXNetError(
                                f"DataLoader worker pid(s) "
                                f"{[w.pid for w in dead]} died "
                                f"(exitcode {[w.exitcode for w in dead]}) "
                                "without producing a batch — likely "
                                "OOM-killed or crashed in native code")
                        continue
                    if err is not None:
                        raise MXNetError(f"DataLoader worker failed:\n{err}")
                    buffered[bidx] = payload
                payload = buffered.pop(received)
                received += 1
                send_next()
                yield _tree_from_shm(payload, self._pin_memory)
        finally:
            # free any in-flight payloads the consumer never took (early
            # break / error): workers unregistered the segments, so they
            # would otherwise outlive the process
            for payload in buffered.values():
                _unlink_tree(payload)
            for _ in workers:
                task_q.put(None)
            for w in workers:
                w.join(timeout=5)
                if w.is_alive():
                    w.terminate()
            try:
                while True:
                    _bidx, payload, err = result_q.get_nowait()
                    if payload is not None:
                        _unlink_tree(payload)
            except Exception:
                pass
