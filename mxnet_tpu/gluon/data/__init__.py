"""Gluon data API (ref: python/mxnet/gluon/data/__init__.py)."""
from .dataset import (Dataset, SimpleDataset, ArrayDataset,  # noqa
                      RecordFileDataset)
from .sampler import (Sampler, SequentialSampler, RandomSampler,  # noqa
                      BatchSampler)
from .dataloader import DataLoader  # noqa: F401
from . import vision  # noqa: F401
