"""Gluon Block / HybridBlock / SymbolBlock.

Reference: python/mxnet/gluon/block.py (Block.__call__:535,
HybridBlock.hybridize:504, _build_cache:748 -> CachedOp:785, export:868,
SymbolBlock:1082).

TPU-native: hybridize() swaps the imperative per-op path for a CachedOp that
jit-compiles the whole forward into one XLA module (cached_op.py). The
`F`-namespace convention of ``hybrid_forward(F, x, ...)`` is preserved —
``F`` is always the nd namespace here because tracing happens at the jax
level, not via symbol proxies.
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict
from typing import Optional

from ..base import MXNetError, check
from ..context import current_context
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock", "nn_block_scope"]


class _NameManager(threading.local):
    def __init__(self):
        self.counters = {}
        self.prefix_stack = [""]

    def next_prefix(self, hint: str) -> str:
        scope = self.prefix_stack[-1]
        key = (scope, hint)
        n = self.counters.get(key, 0)
        self.counters[key] = n + 1
        return f"{scope}{hint}{n}_"


_names = _NameManager()


class nn_block_scope:
    """Prefix scope for child block naming (ref: _BlockScope)."""

    def __init__(self, prefix: str):
        self.prefix = prefix

    def __enter__(self):
        _names.prefix_stack.append(self.prefix)
        return self

    def __exit__(self, *a):
        _names.prefix_stack.pop()


class Block:
    """Base imperative building block (ref: gluon/block.py Block)."""

    def __init__(self, prefix: Optional[str] = None, params=None):
        hint = re.sub("(.)([A-Z][a-z]+)", r"\1_\2", type(self).__name__)
        hint = re.sub("([a-z0-9])([A-Z])", r"\1\2", hint).lower()
        self._prefix = prefix if prefix is not None else _names.next_prefix(hint)
        self._params = ParameterDict(self._prefix, shared=params)
        self._children: "OrderedDict[str, Block]" = OrderedDict()
        self._scope = nn_block_scope(self._prefix)

    # -- naming ---------------------------------------------------------
    @property
    def prefix(self) -> str:
        return self._prefix

    @property
    def name(self) -> str:
        return self._prefix[:-1] if self._prefix.endswith("_") else self._prefix

    def name_scope(self):
        return self._scope

    @property
    def params(self) -> ParameterDict:
        return self._params

    # -- child registration --------------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            pd = self.__dict__.get("_params")
            if pd is not None and value.name not in pd:
                pd._params[value.name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None) -> None:
        self._children[name or str(len(self._children))] = block

    def collect_params(self, select: Optional[str] = None) -> ParameterDict:
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self._params)
        else:
            pattern = re.compile(select)
            ret._params.update({k: v for k, v in self._params.items()
                                if pattern.match(k)})
        for child in self._children.values():
            ret.update(child.collect_params(select))
        return ret

    def _collect_params_with_prefix(self, prefix: str = "") -> dict:
        if prefix:
            prefix += "."
        ret = {prefix + k[len(self._prefix):]: v
               for k, v in self._params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    # -- lifecycle ------------------------------------------------------
    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False) -> None:
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def hybridize(self, active: bool = True, **kwargs) -> None:
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def cast(self, dtype) -> None:
        for child in self._children.values():
            child.cast(dtype)
        for _, p in self._params.items():
            p.cast(dtype)

    def apply(self, fn) -> "Block":
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    # -- persistence (ref: save_parameters/load_parameters) -------------
    def save_parameters(self, filename: str) -> None:
        from ..ndarray import utils as nd_utils
        params = self._collect_params_with_prefix()
        nd_utils.save(filename, {k: v.data() for k, v in params.items()})

    def load_parameters(self, filename: str, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False) -> None:
        from ..ndarray import utils as nd_utils
        loaded = nd_utils.load(filename)
        params = self._collect_params_with_prefix()
        if not allow_missing:
            for name in params:
                check(name in loaded, f"parameter {name} missing in file")
        for name, data in loaded.items():
            if name not in params:
                if ignore_extra:
                    continue
                raise MXNetError(f"parameter {name} not present in Block")
            params[name].set_data(data if ctx is None
                                  else data.as_in_context(ctx))

    # compat aliases (ref: deprecated save_params/load_params)
    save_params = save_parameters

    def load_params(self, filename, ctx=None, **kw):
        self.load_parameters(filename, ctx=ctx, **kw)

    # -- execution ------------------------------------------------------
    def __call__(self, *args):
        return self.forward(*args)

    def forward(self, *args):
        raise NotImplementedError

    def summary(self, *inputs) -> None:
        outputs = self(*inputs)
        n_params = sum(int(p.data().size) for p in
                       self.collect_params().values() if p._data is not None)
        print(f"{type(self).__name__}: {n_params} parameters")

    def __repr__(self):
        lines = [f"{type(self).__name__}("]
        for name, child in self._children.items():
            child_repr = repr(child).replace("\n", "\n  ")
            lines.append(f"  ({name}): {child_repr}")
        lines.append(")")
        return "\n".join(lines)


class HybridBlock(Block):
    """Block that can be compiled to a single XLA program
    (ref: gluon/block.py HybridBlock)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_op = None
        self._cached_op_kwargs = {}

    def hybridize(self, active: bool = True, static_alloc: bool = False,
                  static_shape: bool = False, mirror=None, **kwargs) -> None:
        self._active = active
        self._cached_op = None
        # mirror: rematerialize activations in backward (None = follow the
        # MXNET_BACKWARD_DO_MIRROR env flag)
        self._cached_op_kwargs = {"mirror": mirror}
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape, **kwargs)

    def infer_shape(self, *args) -> None:
        """Resolve deferred parameter shapes from input shapes.

        Layers override _infer_shape_impl; containers recurse by running the
        forward in shape-inference mode (cheap: runs eagerly once).
        """
        self._deferred_infer(*args)

    def _deferred_infer(self, *args) -> None:
        # run the imperative forward; layers hitting deferred params will
        # resolve them from the concrete inputs they see.
        self._imperative_call(*args)

    def _resolved_params(self) -> dict:
        out = {}
        for k, p in self._params.items():
            short = k[len(self._prefix):]
            out[short] = p.data()
        return out

    def _imperative_call(self, *args):
        """Un-jitted forward: hybrid_forward(F=nd, ...) with own params."""
        from .. import ndarray as F
        try:
            params = self._resolved_params()
        except DeferredInitializationError:
            self._shape_hint_from(*args)
            params = self._resolved_params()
        return self.hybrid_forward(F, *args, **params)

    def _shape_hint_from(self, *args) -> None:
        """Give each deferred param a shape using layer-specific logic."""
        self.infer_shape_from_inputs(*args)
        for _, p in self._params.items():
            if p._deferred_init is not None:
                p._finish_deferred_init()

    def infer_shape_from_inputs(self, *args) -> None:
        raise DeferredInitializationError(
            f"{type(self).__name__} has uninitialized-shape parameters and "
            "no shape inference rule; initialize with explicit shapes")

    def _symbolic_call(self, *args):
        """Trace hybrid_forward with Symbol proxies: params become named
        vars, the return is a Symbol graph (ref: block.py:748 _build_cache
        tracing with symbol inputs)."""
        from .. import symbol as F
        params = {}
        for k, p in self._params.items():
            short = k[len(self._prefix):]
            v = F.var(p.name)
            if not getattr(p, "_differentiable", True):
                v._outputs[0][0].extra["aux"] = True
            params[short] = v
        return self.hybrid_forward(F, *args, **params)

    def forward(self, *args):
        from ..symbol.symbol import Symbol
        if any(isinstance(a, Symbol) for a in args):
            return self._symbolic_call(*args)
        if self._active:
            if self._cached_op is None:
                from ..cached_op import CachedOp
                # make sure deferred params are resolved before tracing
                try:
                    self._collect_deferred_check()
                except DeferredInitializationError:
                    self._imperative_call(*args)
                self._cached_op = CachedOp(
                    self, **getattr(self, "_cached_op_kwargs", {}))
            return self._cached_op(*args)
        return self._imperative_call(*args)

    def _collect_deferred_check(self) -> None:
        for _, p in self.collect_params().items():
            if p._data is None:
                raise DeferredInitializationError(p.name)

    def hybrid_forward(self, F, *args, **kwargs):
        raise NotImplementedError

    def export(self, path: str, epoch: int = 0, input_names=("data",)):
        """Serialize for deployment (ref: block.py:868 export): traces the
        block symbolically and writes ``path-symbol.json`` +
        ``path-{epoch:04d}.params`` with ``arg:``/``aux:`` keyed entries —
        the reference checkpoint layout, reloadable by SymbolBlock.imports,
        Module, the C predict API, and contrib.onnx.export_model."""
        from .. import symbol as F
        from ..symbol import symbol as sym_mod
        from ..ndarray import utils as nd_utils
        self._collect_deferred_check()
        sym = self._symbolic_call(*[F.var(n) for n in input_names])
        if isinstance(sym, (list, tuple)):
            sym = sym_mod.Group(list(sym))
        sym.save(f"{path}-symbol.json")
        aux_names = set(sym.list_auxiliary_states())
        payload = {}
        for _, p in sorted(self.collect_params().items()):
            kind = "aux" if p.name in aux_names else "arg"
            payload[f"{kind}:{p.name}"] = p.data()
        nd_utils.save(f"{path}-{epoch:04d}.params", payload)
        return sym


class SymbolBlock(HybridBlock):
    """Run a loaded symbolic graph as a block (ref: block.py:1082)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        from ..symbol.symbol import Symbol
        if isinstance(inputs, Symbol):
            inputs = [inputs]
        self._sym_outputs = outputs
        self._sym_inputs = [i.name if isinstance(i, Symbol) else i
                            for i in inputs]
        # every non-input variable becomes a Parameter of this block
        aux = set(outputs.list_auxiliary_states())
        for name in outputs.list_inputs():
            if name in self._sym_inputs:
                continue
            self.params.get(name, grad_req="null" if name in aux else "write",
                            allow_deferred_init=True,
                            differentiable=name not in aux)

    def _symbolic_call(self, *args):
        # splice the stored graph into the outer symbolic trace by
        # composing input vars with the caller's symbols (params stay as
        # named vars, so a parent block's export sees them)
        subs = {name: a for name, a in zip(self._sym_inputs, args)}
        return self._sym_outputs(**subs)

    @classmethod
    def imports(cls, symbol_file: str, input_names, param_file=None,
                ctx=None):
        """Load an exported model (ref: block.py SymbolBlock.imports)."""
        from ..symbol import symbol as sym_mod
        from ..ndarray import utils as nd_utils
        if isinstance(input_names, str):
            input_names = [input_names]
        sym = sym_mod.load(symbol_file)
        net = cls(sym, list(input_names))
        if param_file is not None:
            loaded = nd_utils.load(param_file)
            for k, v in loaded.items():
                name = k.split(":", 1)[1] if ":" in k else k
                if name in net._params:
                    net._params.get(name).set_data(
                        v if ctx is None else v.as_in_context(ctx))
        return net

    def hybrid_forward(self, F, *args, **params):
        from ..symbol.executor import eval_symbol
        return eval_symbol(self._sym_outputs, self._sym_inputs, args, params)
