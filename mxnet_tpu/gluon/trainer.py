"""Gluon Trainer (ref: python/mxnet/gluon/trainer.py:27).

Applies an Optimizer to a set of Parameters after backward. With a kvstore,
gradients ride the communication layer (XLA collectives over the mesh — see
kvstore.py) exactly like the reference's push/pull flow (trainer.py:327
allreduce_grads); without one, updates are local fused ops.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..base import MXNetError, check
from .. import optimizer as opt_mod
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = [params[k] for k in sorted(params.keys())]
        if not isinstance(params, (list, tuple)):
            raise MXNetError("params must be a ParameterDict/list of Parameter")
        self._params: List[Parameter] = []
        self._param2idx: Dict[str, int] = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise MXNetError(f"invalid parameter {p!r}")
            self._param2idx[p.name] = i
            self._params.append(p)
        self._compression_params = compression_params
        self._contains_sparse = any(p.stype != "default" for p in self._params)
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_arg = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._params_synced = False
        self._chaos_step = 0  # step clock for env-driven chaos plans

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt_mod.Optimizer):
            check(not optimizer_params,
                  "optimizer_params must be empty when an Optimizer instance "
                  "is passed")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt_mod.create(optimizer,
                                             param_dict=param_dict,
                                             **optimizer_params)
        self._updaters = [opt_mod.get_updater(self._optimizer)]

    def _init_kvstore(self):
        """Lazy kvstore creation (ref: trainer.py:169)."""
        if self._kvstore_arg and not isinstance(self._kvstore_arg, str):
            self._kvstore = self._kvstore_arg
        elif self._kvstore_arg:
            from .. import kvstore as kv_mod
            arg = str(self._kvstore_arg).lower()
            try:
                kv = kv_mod.create(self._kvstore_arg)
            except Exception as e:
                # Only the benign default local/device store may degrade to
                # direct updates; a dist or explicitly-requested exotic
                # store failing to come up must NOT silently turn a
                # multi-worker run into single-device training.
                if arg not in ("local", "device"):
                    raise MXNetError(
                        f"failed to create kvstore {self._kvstore_arg!r} "
                        "(refusing to fall back to local updates — a "
                        "misconfigured dist run would silently train "
                        f"single-device): {e}") from e
                self._kvstore = None
            else:
                # a 1-device single-worker store adds nothing over direct
                # update
                self._kvstore = kv if (kv.num_devices > 1 or
                                       kv.num_workers > 1) else None
        self._kv_initialized = True
        if self._kvstore is not None:
            for i, p in enumerate(self._params):
                self._kvstore.init(i, p.data())

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    @property
    def optimizer(self):
        return self._optimizer

    def allreduce_grads(self):
        """Sum gradients across devices (ref: trainer.py:327). With the SPMD
        mesh backend this is an XLA psum ridden through the kvstore."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._kvstore is None:
            return
        from ..ndarray import sparse as _sp
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            g = p.grad()
            if isinstance(g, _sp.RowSparseNDArray):
                # single-process grads are already complete (the tape saw
                # every device's batch); a cross-worker reduce would need
                # the dist store's sparse wire path — densify for it
                # (ref: trainer.py requires update_on_kvstore for
                # row_sparse params for the same reason)
                if self._kvstore.num_workers > 1:
                    # dense [grad | row-mask] reduce: the mask column makes
                    # the rebuilt row set the union across workers, even
                    # for rows whose reduced gradient is exactly zero
                    packed = _sp.mask_pack(g)
                    self._kvstore.push(i, packed)
                    self._kvstore.pull(i, packed)
                    reduced = _sp.mask_unpack(packed, g.shape)
                    g._update(reduced._data, reduced._indices)
                continue
            self._kvstore.push(i, g)
            self._kvstore.pull(i, g)

    def step(self, batch_size, ignore_stale_grad=False):
        """One optimization step: rescale by 1/batch_size, allreduce, update
        (ref: trainer.py:298)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        from ..contrib import chaos
        plan = chaos.active()
        if plan is not None:
            # drive the plan's step clock for classic backward+step loops
            # (FitLoop drives it itself and never calls step())
            plan.begin_step(self._chaos_step)
            self._chaos_step += 1
            plan.poison_grads(self._params)
        self.allreduce_grads()
        self._update(ignore_stale_grad)

    def update(self, batch_size, ignore_stale_grad=False):
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        updater = self._updaters[0]
        live = [(i, p) for i, p in enumerate(self._params)
                if p.grad_req != "null"]
        if not ignore_stale_grad:
            # pre-scan BEFORE applying any update: raising mid-loop would
            # leave a half-stepped model behind a supposedly recoverable
            # error (ref: trainer.py _fresh_grad check)
            stale = [p.name for _, p in live if not p._fresh_grad]
            if stale:
                raise MXNetError(
                    f"gradient of parameter(s) {stale[:4]} is stale (not "
                    "updated by backward since the last step). This "
                    "usually means the parameter was unused in the loss, "
                    "or step() ran twice per backward. Call backward "
                    "first, or pass ignore_stale_grad=True to skip stale "
                    "parameters. No update was applied.")
        for i, p in live:
            if p._fresh_grad:
                updater(i, p.grad(), p.data())
                p._fresh_grad = False

    def save_states(self, fname):
        with open(fname, "wb") as f:
            f.write(self._updaters[0].get_states(dump_optimizer=False))

    def load_states(self, fname):
        with open(fname, "rb") as f:
            self._updaters[0].set_states(f.read())
