"""Gluon Trainer (ref: python/mxnet/gluon/trainer.py:27).

Applies an Optimizer to a set of Parameters after backward. With a kvstore,
gradients ride the communication layer (XLA collectives over the mesh — see
kvstore.py) exactly like the reference's push/pull flow (trainer.py:327
allreduce_grads); without one, updates are local fused ops.

Aggregated hot path (ref: optimizer_op.cc:654 multi_sgd_update +
MXNET_OPTIMIZER_AGGREGATION_SIZE): dense parameters are grouped into
dtype/device buckets of up to ``MXTPU_OPTIMIZER_AGGREGATION`` params and
each bucket is stepped by ONE jitted program with donated weight/state
buffers (optimizer/grouped.py), so a step costs O(buckets) compiled-call
launches instead of O(params). ``allreduce_grads`` likewise concatenates
same-dtype gradients into flat buckets (``MXTPU_GRAD_BUCKET_MB``) and
issues one kvstore push/pull — one collective — per bucket instead of one
per key; the kvstore's retry/chaos hooks wrap each bucketed call, so fault
semantics are preserved per bucket. Sparse (row_sparse) parameters and
gradients always take the original per-key/per-param paths.

ZeRO-1 sharded optimizer state (``MXTPU_ZERO=1``, parallel/zero.py): the
same ``_gbkt`` flat buckets are **reduce-scattered** instead of
allreduced, the grouped donated-buffer update steps only this rank's
parameter shard (optimizer state + f32 masters materialize 1/N per
rank), and the updated weights ride a per-bucket **allgather** back.
The fused finiteness sentinel is AND-reduced across ranks before any
shard applies, so a NaN anywhere skips the step everywhere and
``rollback_step`` stays shard-local. See the plane's module docstring
for partition/portability invariants.

Comm/backward overlap (ref: the dependency engine scheduling each key's
push as soon as its write dependency resolves — PAPER.md §engine,
§KVStore): with ``MXTPU_COMM_OVERLAP=on`` the loop owner brackets
``backward()`` in :meth:`Trainer.overlap_scope`, which installs the
autograd grad-ready hook and launches each bucket's collective the moment
its constituent gradients receive their final contribution DURING the
reverse pass. Buckets use the SAME forward-order layout (and so the same
``_gbkt`` keys) as the barrier path, but *launch* in finalization order —
backward finalizes later layers' grads first, so the last buckets are in
flight while backward is still producing the early layers' gradients;
``allreduce_grads`` then only flushes stragglers and splits the flat wire
buffers back. Numerically identical to the barrier path — the same
buckets, the same sums, launched earlier. Overlapped communication is charged to
the step-breakdown segment ``comm_overlapped`` (exclusive time, nested
inside ``compute``).

The overlap composes with ZeRO-1: under ``MXTPU_ZERO=1`` the same
grad-ready hook launches each bucket's **reduce-scatter** at grad
finality (rebinds deferred to finalization — autograd may still read
the live buffers), and the update path launches each bucket's weight
**allgather** as soon as that bucket's shard updates land, while the
tail buckets are still updating. Same buckets, same sums, same
collective count as the barrier plane; only the launch points move,
into ``comm_overlapped``. See parallel/zero.py for the prefetch
completion contract on distributed groups.
"""
from __future__ import annotations

import functools
import re
from typing import Dict, List, Optional

from ..base import MXNetError, check, env
from .. import optimizer as opt_mod
from ..optimizer import grouped as _grouped
from ..telemetry import memory as _memory
from ..telemetry import numerics as _numerics
from ..telemetry.step_breakdown import segment as _bd_segment
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


def _overlap_requested() -> bool:
    """Strict MXTPU_COMM_OVERLAP parse — a typo'd request to overlap must
    not silently train with the barrier path."""
    raw = str(env.get("MXTPU_COMM_OVERLAP") or "").strip().lower()
    if raw in ("", "0", "off", "false"):
        return False
    if raw in ("1", "on", "true"):
        return True
    raise MXNetError(
        f"MXTPU_COMM_OVERLAP: unknown value {raw!r} (known: on, off)")


@functools.lru_cache(maxsize=1)
def _flatten_fn():
    """One jitted concat of a gradient bucket into a flat wire buffer
    (jit's own trace cache specializes per input shapes/dtypes, so a
    single wrapper serves every bucket signature)."""
    import jax
    import jax.numpy as jnp

    def fn(*gs):
        return jnp.concatenate([g.ravel() for g in gs])
    return jax.jit(fn)


@functools.lru_cache(maxsize=256)
def _split_fn(sig):
    """Inverse of :func:`_flatten_fn`. The split outputs are rebound over
    the old per-param grad buffers (which then free), so steady-state
    grad memory stays one copy; XLA cannot alias one flat buffer into
    many differently-shaped outputs, so ``donate_argnums`` would only
    warn, not help."""
    import jax

    def fn(flat):
        out, off = [], 0
        for shape, _ in sig:
            n = 1
            for s in shape:
                n *= s
            out.append(flat[off:off + n].reshape(shape))
            off += n
        return tuple(out)
    return jax.jit(fn)


@functools.lru_cache(maxsize=1)
def _update_dispatch_counter():
    from ..telemetry import default_registry
    return default_registry().counter(
        "mxtpu_update_dispatches_total",
        "Compiled-program launches per optimizer update "
        "(aggregated: one per dtype/device bucket).")


@functools.lru_cache(maxsize=1)
def _allreduce_counter():
    from ..telemetry import default_registry
    return default_registry().counter(
        "mxtpu_allreduce_collectives_total",
        "kvstore collectives issued by Trainer.allreduce_grads "
        "(bucketed: one per gradient bucket).")


def _natural_key(name: str):
    """Numeric-aware sort key: ``dense9_weight`` < ``dense10_weight``.

    Positional parameter indices (kvstore keys, checkpointed optimizer
    state slots) derive from this order, and gluon block names embed a
    process-global counter — a plain lexicographic sort flips the order
    of structurally identical nets created at different counter values
    (``dense10_*`` < ``dense8_*``), so a resumed run would bind restored
    optimizer state to the wrong parameters."""
    return [(1, int(t)) if t.isdigit() else (0, t)
            for t in re.split(r"(\d+)", name)]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = [params[k] for k in sorted(params.keys(),
                                                key=_natural_key)]
        if not isinstance(params, (list, tuple)):
            raise MXNetError("params must be a ParameterDict/list of Parameter")
        self._params: List[Parameter] = []
        self._param2idx: Dict[str, int] = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise MXNetError(f"invalid parameter {p!r}")
            self._param2idx[p.name] = i
            self._params.append(p)
        self._compression_params = compression_params
        self._contains_sparse = any(p.stype != "default" for p in self._params)
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_arg = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._params_synced = False
        self._chaos_step = 0  # step clock for env-driven chaos plans
        # per-call observability for the aggregated paths (bench + the
        # dispatch-count regression test read these)
        self.last_update_dispatches = 0
        self.last_allreduce_collectives = 0
        self.last_reduce_scatter_collectives = 0
        self.last_allgather_collectives = 0
        # numerics plane (MXTPU_NUMERICS): device stat arrays of the last
        # sampled update — [(param_names, (n,6) matrix)] per bucket, left
        # UN-fetched so FitLoop rides them on its flag+loss transfer
        self.last_numerics_stats = None
        # ZeRO-1 plane: None = not yet resolved, False = off, else the
        # live parallel.zero.ZeroPlane; _zero_step carries the plane from
        # allreduce_grads (reduce-scatter ran) to the following _update;
        # _zero_declined marks a sentinel decline whose classic fallback
        # update() is the ONE sanctioned unsharded update under ZeRO
        self._zero = None
        self._zero_step = None
        self._zero_declined = False
        self._last_fused_indices: List[int] = []
        self._last_fused_created: List[int] = []
        # bucket keys already init'ed on the kvstore (keyed by the full
        # shape-signature string, so a layout change mints a fresh key)
        self._bucket_keys: Dict[str, bool] = {}
        # live comm/backward overlap scope (set on scope entry, consumed
        # by the next allreduce_grads)
        self._overlap_state: Optional["_OverlapScope"] = None

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt_mod.Optimizer):
            check(not optimizer_params,
                  "optimizer_params must be empty when an Optimizer instance "
                  "is passed")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt_mod.create(optimizer,
                                             param_dict=param_dict,
                                             **optimizer_params)
        self._updaters = [opt_mod.get_updater(self._optimizer)]

    def _init_kvstore(self):
        """Lazy kvstore creation (ref: trainer.py:169)."""
        if self._kvstore_arg and not isinstance(self._kvstore_arg, str):
            self._kvstore = self._kvstore_arg
        elif self._kvstore_arg:
            from .. import kvstore as kv_mod
            arg = str(self._kvstore_arg).lower()
            try:
                kv = kv_mod.create(self._kvstore_arg)
            except Exception as e:
                # Only the benign default local/device store may degrade to
                # direct updates; a dist or explicitly-requested exotic
                # store failing to come up must NOT silently turn a
                # multi-worker run into single-device training.
                if arg not in ("local", "device"):
                    raise MXNetError(
                        f"failed to create kvstore {self._kvstore_arg!r} "
                        "(refusing to fall back to local updates — a "
                        "misconfigured dist run would silently train "
                        f"single-device): {e}") from e
                self._kvstore = None
            else:
                # a 1-device single-worker store adds nothing over direct
                # update
                self._kvstore = kv if (kv.num_devices > 1 or
                                       kv.num_workers > 1) else None
        self._kv_initialized = True
        if self._kvstore is not None:
            for i, p in enumerate(self._params):
                self._kvstore.init(i, p.data())

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    @property
    def optimizer(self):
        return self._optimizer

    @staticmethod
    def _bucket_mb() -> float:
        try:
            return float(env.get("MXTPU_GRAD_BUCKET_MB"))
        except (TypeError, ValueError):
            return 0.0

    def overlap_scope(self, chaos_step: Optional[int] = None):
        """Context manager for one backward pass that overlaps gradient
        communication with the reverse pass (``MXTPU_COMM_OVERLAP=on``):
        the autograd grad-ready hook launches each dense bucket's kvstore
        push/pull — or, under ``MXTPU_ZERO=1``, its reduce-scatter — as
        soon as its constituent grads are final, and the following
        :meth:`allreduce_grads` call only flushes stragglers + completes
        the deferred rebinds. Returns an inactive no-op scope when
        overlap is off or there is no kvstore argument — the caller can
        always write ``with trainer.overlap_scope(): loss.backward()``.

        ``chaos_step``: the chaos clock index the upcoming step will run
        under (defaults to this trainer's own ``step()`` clock; FitLoop
        passes its step counter). A step whose grads the chaos plan will
        poison AFTER backward gets an inactive scope: overlapped
        collectives would ship the clean grads during backward — and,
        through a compressing store, advance per-key error-feedback
        residuals a second push on the same keys would then corrupt."""
        # parse FIRST: a typo'd MXTPU_COMM_OVERLAP must raise even when
        # there is no store (short-circuiting the parse away would let
        # the typo silently train with the barrier path)
        active = _overlap_requested() and bool(self._kvstore_arg)
        if active:
            from ..contrib import chaos
            plan = chaos.active()
            if plan is not None and plan.poisons_step(
                    self._chaos_step if chaos_step is None else chaos_step):
                active = False
        return _OverlapScope(self, active)

    def allreduce_grads(self):
        """Sum gradients across devices (ref: trainer.py:327). With the SPMD
        mesh backend this is an XLA psum ridden through the kvstore.

        Dense gradients are bucketed: same-dtype grads are concatenated
        into flat buffers capped at ``MXTPU_GRAD_BUCKET_MB`` and reduced
        with ONE push/pull (one collective) per bucket (ref: kvstore key
        flattening / DDP gradient bucketing), then split back over the old
        per-param grad buffers (which then free) — the flat wire buffer is
        transient, see :func:`_split_fn`. Row-sparse grads keep the
        per-key mask-pack path. Under an active :meth:`overlap_scope` the
        collectives were already launched during backward; this call
        flushes the remainder and completes the splits."""
        st = self._overlap_state
        if st is not None:
            self._overlap_state = None
            st.finalize()
            return
        if not self._kv_initialized:
            self._init_kvstore()
        self.last_allreduce_collectives = 0
        self.last_reduce_scatter_collectives = 0
        self._zero_step = None
        # a fresh comm round supersedes a stale un-consumed decline (the
        # caller skipped that step's update): without this, the stale
        # flag would sanction one later bare unsharded update()
        self._zero_declined = False
        plane = self._zero_plane()
        if plane is not None:
            # ZeRO-1: reduce-scatter the same buckets instead of
            # allreduce; the following _update consumes the plane (shard
            # update + weight allgather)
            plane.reduce_scatter_grads(self)
            self._zero_step = plane
            return
        if self._kvstore is None:
            return
        from ..ndarray import sparse as _sp
        bucket_mb = self._bucket_mb()
        flat_items = []
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            g = p.grad()
            if isinstance(g, _sp.RowSparseNDArray):
                self._allreduce_rowsparse(i, g)
                continue
            if bucket_mb > 0:
                flat_items.append((i, g))
            else:
                self._kvstore.push(i, g)
                self._kvstore.pull(i, g)
                self.last_allreduce_collectives += 1
        if flat_items:
            self._allreduce_bucketed(flat_items, bucket_mb)
        if self.last_allreduce_collectives:
            _allreduce_counter().inc(self.last_allreduce_collectives)

    def _zero_plane(self):
        """The live ZeRO-1 plane, or None. Resolved once: ``MXTPU_ZERO``
        is parsed strictly (typos raise), and a non-composable
        configuration (no store, compression, ungrouped optimizer,
        sparse params, aggregation off) raises at first use instead of
        silently training unsharded."""
        if self._zero is None:
            from ..parallel import zero as _zero
            if not _zero.zero_requested():
                self._zero = False
            else:
                if not self._kv_initialized:
                    self._init_kvstore()
                self._zero = _zero.ZeroPlane(self)
        return self._zero or None

    def _allreduce_rowsparse(self, i, g):
        """Cross-worker reduce of one row_sparse gradient. Single-process
        grads are already complete (the tape saw every device's batch); a
        cross-worker reduce would need the dist store's sparse wire path —
        densify for it (ref: trainer.py requires update_on_kvstore for
        row_sparse params for the same reason)."""
        from ..ndarray import sparse as _sp
        if self._kvstore.num_workers > 1:
            # dense [grad | row-mask] reduce: the mask column makes
            # the rebuilt row set the union across workers, even
            # for rows whose reduced gradient is exactly zero
            packed = _sp.mask_pack(g)
            self._kvstore.push(i, packed)
            self._kvstore.pull(i, packed)
            reduced = _sp.mask_unpack(packed, g.shape)
            g._update(reduced._data, reduced._indices)
            self.last_allreduce_collectives += 1

    def _grad_buckets(self, items, bucket_mb):
        """Deterministic same-dtype runs capped at ``bucket_mb`` MB — the
        layout is a pure function of (param order, dtypes, cap), so the
        kvstore keys stay stable across steps."""
        cap = max(1, int(bucket_mb * (1 << 20)))
        buckets, cur, cur_bytes, cur_dtype = [], [], 0, None
        for i, g in items:
            nbytes = g.size * g._data.dtype.itemsize
            dt = str(g._data.dtype)
            if cur and (dt != cur_dtype or cur_bytes + nbytes > cap):
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append((i, g))
            cur_bytes += nbytes
            cur_dtype = dt
        if cur:
            buckets.append(cur)
        return buckets

    def _allreduce_bucketed(self, items, bucket_mb):
        for bid, bucket in enumerate(self._grad_buckets(items, bucket_mb)):
            flat = self._launch_bucket(bid, bucket)
            if flat is not None:
                self._split_bucket(bucket, *flat)

    def _launch_bucket(self, bid, bucket):
        """Push+pull one dense bucket. A flattened multi-grad bucket
        returns ``(sig, flat_nd)`` with the split DEFERRED to the caller
        (overlap launches split after backward finishes); a singleton
        rides its per-param key, pulled in place, and returns None."""
        if len(bucket) == 1:
            # a lone grad (or one larger than the cap) rides its own
            # already-initialized per-param key — no copy overhead
            i, g = bucket[0]
            self._kvstore.push(i, g)
            self._kvstore.pull(i, g)
            self.last_allreduce_collectives += 1
            return None
        sig, key = self._bucket_sig_key(bid, bucket)
        flat_nd = self._bucket_wire(key, bucket)
        if key not in self._bucket_keys:
            try:
                # the flat wire buffer must NOT be row-sharded by the
                # big-array bound — it is split back immediately
                self._kvstore.init(key, flat_nd, shard=False)
            except TypeError:  # user-supplied store without shard=
                self._kvstore.init(key, flat_nd)
            self._bucket_keys[key] = True
        # retry/chaos hooks (TransientKVError backoff, kv_flake) wrap
        # these calls per BUCKET key inside the kvstore, preserving
        # the fault semantics of the per-key path
        self._kvstore.push(key, flat_nd)
        self._kvstore.pull(key, out=flat_nd)
        self.last_allreduce_collectives += 1
        return sig, flat_nd

    @staticmethod
    def _bucket_sig_key(bid, bucket):
        """(signature, stable store key) of one dense gradient bucket.
        The key encodes the bucket's FULL shape signature (digest): if
        the layout changes mid-run (a param frozen, the MB cap changed) a
        fresh key gets a fresh store buffer and a fresh compressor
        error-feedback residual — a stale key would push a
        differently-laid-out flat into old state. Shared by the allreduce
        path and the ZeRO-1 reduce-scatter/allgather plane, so BOTH comm
        modes see one ``_gbkt*`` layout per step."""
        import hashlib
        sig = tuple((g.shape, str(g._data.dtype)) for _, g in bucket)
        total = sum(int(g.size) for _, g in bucket)
        digest = hashlib.md5(repr(sig).encode()).hexdigest()[:10]
        return sig, (f"_gbkt{bid}:{sig[0][1]}:{total}"
                     f":n{len(bucket)}:{digest}")

    @staticmethod
    def _bucket_wire(key, bucket):
        """Flatten one dense bucket into its transient flat wire buffer.
        The NDArray is ledgered under ``grad_buckets`` and lives until
        the split (or reduce-scatter slicing) rebinds the per-param grads
        and it dies — freed by the NDArray's death, so donation/free
        accounting is automatic. Shared by the allreduce push path and
        the ZeRO-1 reduce-scatter, so both comm modes' memory attribution
        stays identical."""
        from ..ndarray import ndarray as _nd
        flat = _flatten_fn()(*[g._data for _, g in bucket])
        flat_nd = _nd.NDArray(flat, ctx=bucket[0][1]._ctx)
        _memory.track_ndarray("grad_buckets", flat_nd,
                              owner=f"{key.split(':')[0]}:wire")
        return flat_nd

    @staticmethod
    def _split_bucket(bucket, sig, flat_nd):
        parts = _split_fn(sig)(flat_nd._data)
        for (_, g), arr in zip(bucket, parts):
            g._rebind(arr)

    def step(self, batch_size, ignore_stale_grad=False):
        """One optimization step: rescale by 1/batch_size, allreduce, update
        (ref: trainer.py:298)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        from ..contrib import chaos
        plan = chaos.active()
        if plan is not None:
            # drive the plan's step clock for classic backward+step loops
            # (FitLoop drives it itself and never calls step())
            plan.begin_step(self._chaos_step)
            self._chaos_step += 1
            if self._overlap_state is not None and \
                    plan.poisons_step(self._chaos_step - 1):
                # late defense for a plan installed AFTER the scope was
                # entered (overlap_scope() returns an inactive scope for
                # steps it KNOWS will be poisoned): collectives already
                # shipped the CLEAN grads during backward; consuming the
                # state would let the deferred splits overwrite the
                # poison injected below. Abandon it — allreduce re-runs
                # on the poisoned buffers and the fault bites
                self._overlap_state = None
            plan.poison_grads(self._params)
        self.allreduce_grads()
        self._update(ignore_stale_grad)

    def update(self, batch_size, ignore_stale_grad=False):
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def update_with_sentinel(self, batch_size, ignore_stale_grad=False):
        """Aggregated update with the global-finiteness sentinel folded
        into the compiled bucket programs: every update is guarded by one
        fused all-grads-finite reduction, applied as ``where(ok, new,
        old)`` on device. Returns the device-resident flag (fetch it with
        the loss in one transfer; on False call :meth:`rollback_step`), or
        None when the fused path cannot cover the whole parameter set —
        the caller must then use the classic check-then-update flow."""
        self._optimizer.rescale_grad = self._scale / batch_size
        return self._update(ignore_stale_grad, sentinel=True)

    def rollback_step(self):
        """Undo the host-side effects of the last fused sentinel step (the
        device state was already left untouched by the ``where`` guard):
        update counters, and optimizer-state objects first materialized by
        that step — so a skipped step is indistinguishable from the
        per-param path's never-applied update."""
        _grouped.rollback_counts(self._optimizer, self._last_fused_indices)
        for i in self._last_fused_created:
            self._updaters[0].states.pop(i, None)
            self._updaters[0].states_synced.pop(i, None)
            # the state objects die with the pop: release their ledger
            # bytes too, or a skipped first step would leak phantom
            # optimizer/masters accounting forever
            _memory.drop_optimizer_state(self._updaters[0], i)
        self._last_fused_indices = []
        self._last_fused_created = []

    def megastep_plan(self, batch_size):
        """HOST half of one fused megastep (``MXTPU_MEGASTEP=on``): the
        bookkeeping ``update_with_sentinel`` performs between dispatches
        — rescale resolution, per-rank :func:`grouped.prepare_update`
        (update-count bumps, state creation, lr/wd resolution) and
        chunking — extracted so the megastep driver can run it OUTSIDE
        the trace every step while the ONE traced program replays the
        device half. Covers every live parameter (megastep's trace-time
        freshness check replaces the composed path's post-backward
        ``todo`` filter), per rank of the ZeRO plane when active. Arms
        ``_last_fused_indices``/``_last_fused_created`` so the existing
        :meth:`rollback_step` undoes a sentinel-skipped (or
        failed-to-trace) step exactly like the composed fused path.

        Returns ``(live, rank_chunks, lr_list, wd_list)`` where
        ``rank_chunks`` is one chunk list per non-empty rank and
        ``lr_list``/``wd_list`` flatten the per-item scalars in chunk
        order (the megastep program takes them as ONE dynamic f32 vector
        — Adam's bias-corrected lr changes every step and must not
        retrace)."""
        self._optimizer.rescale_grad = self._scale / batch_size
        self.last_numerics_stats = None
        updater = self._updaters[0]
        live = [(i, p) for i, p in enumerate(self._params)
                if p.grad_req != "null"]
        plane = self._zero_plane()
        agg = _grouped.aggregation_size()
        if plane is not None:
            agg = max(1, agg)
            rank_sets = [[(i, p) for i, p in live if plane.owner(i) == r]
                         for r in plane.my_ranks]
        else:
            rank_sets = [live]
        rank_chunks, created, handled = [], [], []
        lr_list, wd_list = [], []
        for items in rank_sets:
            if not items:
                continue
            prepared, cr = _grouped.prepare_update(updater, items)
            chunks = _grouped.chunk_prepared(prepared, agg)
            rank_chunks.append(chunks)
            created += cr
            for chunk in chunks:
                for e in chunk:
                    handled.append(e[0])
                    lr_list.append(e[4])
                    wd_list.append(e[5])
        self._last_fused_indices = handled
        self._last_fused_created = created
        return live, rank_chunks, lr_list, wd_list

    def _update(self, ignore_stale_grad=False, sentinel=False):
        # stale sampled stats must not outlive their step: FitLoop reads
        # this attribute right after the update call
        self.last_numerics_stats = None
        plane = self._zero_step
        self._zero_step = None
        if plane is not None:
            return self._update_zero(plane, ignore_stale_grad, sentinel)
        declined = self._zero_declined
        self._zero_declined = False
        if not declined and self._zero_plane() is not None:
            # MXTPU_ZERO=1 but no reduce-scatter preceded this update:
            # stepping every parameter here would silently materialize
            # FULL optimizer state (and, in a worker group, consume
            # unreduced local gradients) — the exact degradation the
            # plane's strictness contract forbids. The one sanctioned
            # classic fallback is the sentinel's simulated-world decline,
            # flagged above.
            raise MXNetError(
                "MXTPU_ZERO=1: update() without a preceding "
                "allreduce_grads() reduce-scatter would apply an "
                "unsharded update. Call step(), or allreduce_grads() "
                "before update(), or unset MXTPU_ZERO.")
        updater = self._updaters[0]
        live = [(i, p) for i, p in enumerate(self._params)
                if p.grad_req != "null"]
        if not ignore_stale_grad:
            # pre-scan BEFORE applying any update: raising mid-loop would
            # leave a half-stepped model behind a supposedly recoverable
            # error (ref: trainer.py _fresh_grad check)
            stale = [p.name for _, p in live if not p._fresh_grad]
            if stale and sentinel:
                # decline instead of raising: the classic flow checks
                # finiteness FIRST and skips a non-finite step without
                # ever reaching this pre-scan — the fused path must not
                # turn that survivable skip into a crash. The fallback
                # reproduces the old ordering exactly (skip silently on
                # non-finite, raise on the next finite step).
                return None
            if stale:
                raise MXNetError(
                    f"gradient of parameter(s) {stale[:4]} is stale (not "
                    "updated by backward since the last step). This "
                    "usually means the parameter was unused in the loss, "
                    "or step() ran twice per backward. Call backward "
                    "first, or pass ignore_stale_grad=True to skip stale "
                    "parameters. No update was applied.")
        todo = [(i, p) for i, p in live if p._fresh_grad]
        self.last_update_dispatches = 0
        agg = _grouped.aggregation_size()
        if sentinel and (agg <= 0 or not todo or
                         not _grouped.eligible(updater, todo)):
            # all-or-nothing: the sentinel's single skip decision must
            # cover the complete parameter set, so aggregation off or any
            # ineligible param (sparse, un-grouped optimizer) declines
            # the fused path WITHOUT touching a single parameter — the
            # caller falls back to check-then-update
            return None
        handled, flag = set(), None
        stats_out = None
        if agg > 0 and todo:
            if sentinel:
                # numerics plane: one consume-once sampling decision per
                # step; when sampled the bucket programs emit the extra
                # stats output (same dispatch count — cost is outputs,
                # not launches). One cached flag check when off. Consumed
                # only when a grouped call actually runs — a per-param
                # step leaves the sample for the caller's fallback.
                nspec = _numerics.collect_spec()
                stats_out = [] if nspec is not None else None
                # the flag must cover EVERY live grad — including stale
                # ones skipped under ignore_stale_grad — exactly like the
                # classic host check (FitLoop._grads_finite_flag), or the
                # two paths would diverge on whether a step is skipped
                sentinel_grads = tuple(p._grad._data for _, p in live
                                       if p._grad is not None)
                idxs, n, flag, created = _grouped.grouped_update(
                    updater, todo, agg, sentinel=True,
                    sentinel_grads=sentinel_grads, stats_out=stats_out)
                handled = set(idxs)
                self._last_fused_indices = idxs
                self._last_fused_created = created
                self.last_update_dispatches += n + 1  # + finite reduction
            else:
                dense = [(i, p) for i, p in todo
                         if _grouped.eligible(updater, [(i, p)])]
                if dense:
                    # collect only when the grouped call covers EVERY
                    # live param — a mixed dense/ineligible set would
                    # publish a silently under-counted "global" grad
                    # norm; leaving the sample unconsumed lets the
                    # caller's fallback cover the full set instead
                    if len(dense) == len(todo):
                        nspec = _numerics.collect_spec()
                        stats_out = [] if nspec is not None else None
                    idxs, n, _, _ = _grouped.grouped_update(
                        updater, dense, agg, stats_out=stats_out)
                    handled = set(idxs)
                    self.last_update_dispatches += n
        if stats_out:
            self.last_numerics_stats = stats_out
        for i, p in todo:
            if i in handled:
                p._fresh_grad = False
                continue
            updater(i, p.grad(), p.data())
            p._fresh_grad = False
            self.last_update_dispatches += 1
        if self.last_update_dispatches:
            _update_dispatch_counter().inc(self.last_update_dispatches)
        return flag

    def _update_zero(self, plane, ignore_stale_grad, sentinel):
        """ZeRO-1 back half (the reduce-scatter already ran in
        allreduce_grads): shard-local grouped update guarded by the
        GLOBAL finiteness verdict, then the per-bucket weight allgather
        — as one barrier after all updates, or, with
        ``MXTPU_COMM_OVERLAP=on``, launched per bucket the moment that
        bucket's shard updates land (charged to ``comm_overlapped``).
        Only this rank's parameters touch optimizer state; everyone
        else's updated weights arrive through the allgather."""
        import jax
        updater = self._updaters[0]
        self.last_update_dispatches = 0
        self.last_allgather_collectives = 0
        self._last_fused_indices = []
        self._last_fused_created = []
        live = [(i, p) for i, p in enumerate(self._params)
                if p.grad_req != "null"]
        stale = [] if ignore_stale_grad else \
            [p.name for _, p in live if not p._fresh_grad]
        if stale and sentinel and not plane.distributed:
            # decline exactly like the unsharded fused path
            # (Trainer._update's stale pre-scan): the caller's classic
            # fallback host-checks the locally-complete reduced grads
            # and reproduces the old skip-before-stale-raise ordering
            self._zero_declined = True
            return None
        flag = plane.global_finite_flag(live) if sentinel else None
        if stale:
            if sentinel:
                # distributed: the flag is already global — reproduce the
                # classic ordering (a non-finite step skips silently, a
                # finite one surfaces the stale error on every rank)
                if not bool(jax.device_get(flag)):
                    return flag
            raise MXNetError(
                f"gradient of parameter(s) {stale[:4]} is stale (not "
                "updated by backward since the last step). This "
                "usually means the parameter was unused in the loss, "
                "or step() ran twice per backward. Call backward "
                "first, or pass ignore_stale_grad=True to skip stale "
                "parameters. No update was applied.")
        todo = [(i, p) for i, p in live if p._fresh_grad]
        if not todo:
            if sentinel and not plane.distributed:
                # decline: the caller's classic fallback is sanctioned —
                # ONLY here; arming the flag on a non-sentinel call would
                # hand a later buggy bare update() an unsharded bypass
                self._zero_declined = True
                return None
            return flag
        agg = max(1, _grouped.aggregation_size())
        # numerics plane: one sampling decision covers every shard's
        # grouped call this step (simulated worlds step all ranks here,
        # so the stats matrix spans the full parameter set; a real group
        # merges shard-local stats over the byte channel at record time)
        nspec = _numerics.collect_spec()
        stats_out = [] if nspec is not None else None
        handled, created, n_disp = [], [], 0
        overlap = plane.overlap_active(self)
        if overlap:
            # overlapped allgather: walk the comm round's buckets in
            # layout order, update each bucket's shards, and launch that
            # bucket's weight allgather IMMEDIATELY — in flight while the
            # tail buckets still update (the DeviceStagingIter staging
            # idiom applied to weights). Per-param update math is
            # grouping-independent (grouped.py advances per-index
            # counters), so splitting the per-rank grouped calls per
            # bucket is bitwise-neutral vs the barrier plane.
            layout = plane.take_step_layout(self)
            todo_idx = dict(todo)
            seen = set()
            for key, bucket in layout:
                bitems = [(i, todo_idx[i]) for i, _g in bucket
                          if i in todo_idx]
                seen.update(i for i, _p in bitems)
                for r in plane.my_ranks:
                    items = [(i, p) for i, p in bitems
                             if plane.owner(i) == r]
                    if not items:
                        continue
                    idxs, n, _f, cr = _grouped.grouped_update(
                        updater, items, agg, sentinel=sentinel,
                        sentinel_flag=flag, stats_out=stats_out)
                    handled += idxs
                    created += cr
                    n_disp += n
                with _bd_segment("comm_overlapped"):
                    plane.launch_allgather_bucket(self, key, bucket)
            plane.seal_allgather(self)
            # safety net: a fresh grad outside the round's layout cannot
            # exist (the layout covers every grad), but if one ever did
            # its update must not be dropped — it just misses the wire,
            # exactly like a stale-declined param
            leftovers = [(i, p) for i, p in todo if i not in seen]
        else:
            leftovers = todo
        for r in plane.my_ranks:
            items = [(i, p) for i, p in leftovers if plane.owner(i) == r]
            if not items:
                continue
            idxs, n, _f, cr = _grouped.grouped_update(
                updater, items, agg, sentinel=sentinel,
                sentinel_flag=flag, stats_out=stats_out)
            handled += idxs
            created += cr
            n_disp += n
        if stats_out is not None:
            # park even an EMPTY list (a distributed rank owning zero
            # params this step): record_step's cross-rank stats merge is
            # a collective, and a rank that silently skipped it would
            # deadlock every peer on the first sampled step
            self.last_numerics_stats = stats_out
        if sentinel:
            n_disp += 1  # the fused finite reduction
            self._last_fused_indices = handled
            self._last_fused_created = created
        if not overlap:
            # barrier allgather of the (where-guarded) updated weights:
            # wire time is charged to 'comm' so StepBreakdown/
            # trace_report attribute it, even though the call runs
            # inside the optimizer phase
            with _bd_segment("comm"):
                plane.allgather_weights(self)
        for _i, p in todo:
            p._fresh_grad = False
        self.last_update_dispatches = n_disp
        if n_disp:
            _update_dispatch_counter().inc(n_disp)
        return flag

    def get_states_bytes(self) -> bytes:
        """Serialized optimizer state in the TOPOLOGY-PORTABLE unsharded
        format: under ZeRO-1 the shards are gathered back into one full
        state dict (gather-on-save), so the bytes restore into any world
        size — including an unsharded run. CheckpointManager routes
        through here."""
        plane = self._zero_plane()
        if plane is not None:
            return plane.gather_states_bytes(self._updaters[0])
        return self._updaters[0].get_states(dump_optimizer=False)

    def set_states_bytes(self, data: bytes) -> None:
        """Restore from the unsharded format; under distributed ZeRO-1
        the local shard view is re-derived (non-local slots pruned before
        they ever touch device memory or the ledger)."""
        plane = self._zero_plane()
        keep = None
        if plane is not None and plane.distributed:
            keep = plane.local_indices()
        self._updaters[0].set_states(data, keep=keep)

    def save_states(self, fname):
        with open(fname, "wb") as f:
            f.write(self.get_states_bytes())

    def load_states(self, fname):
        with open(fname, "rb") as f:
            self.set_states_bytes(f.read())


class _OverlapScope:
    """One backward pass's comm/backward overlap state.

    Entering installs the autograd grad-ready hook; while backward runs,
    each dense bucket whose constituent grads have ALL received their
    final contribution is pushed/pulled immediately (the barrier path's
    forward-order layout, launched in finalization order: later layers'
    buckets finalize first and go out while backward still computes the
    early layers). The flat-buffer splits are deferred to
    :meth:`finalize` (called by the trainer's next ``allreduce_grads``),
    so the collectives stay in flight behind the remaining backward
    compute.

    The bucket layout is built lazily at the first hook firing: deferred-
    init parameters only materialize shapes during the first forward, and
    the kvstore itself initializes lazily. A backward that announces no
    grads (whole-graph CachedOp bypasses the tape) degrades gracefully:
    finalize launches every bucket, which is exactly the barrier path.

    Under ``MXTPU_ZERO=1`` the scope drives the plane's reduce-scatter
    instead of push/pull: the same buckets launch at grad finality
    through ``ZeroPlane.launch_bucket_rs`` (the collective is pure; only
    the launch moves), and the grad-onto-reduced-slice rebinds are
    deferred to :meth:`finalize` exactly like the dense splits — autograd
    may still read the live grad buffers mid-backward. finalize then
    hands the round's layout to the plane and arms ``_zero_step``, so
    the following update consumes the plane as if the barrier
    ``reduce_scatter_grads`` had run.

    Contract: each entered scope is paired with the following
    ``allreduce_grads``/``step`` call, which consumes it. A scope whose
    backward raised is abandoned on exit (its launched buckets hold a
    partial step's grads); a scope abandoned any other way (the caller
    skipped the update entirely) is superseded wholesale by the next
    scope's entry — interleaving an un-consumed scope with a scopeless
    ``allreduce_grads`` is caller error.
    """

    def __init__(self, trainer: Trainer, active: bool):
        self._trainer = trainer
        self.active = active
        self._cm = None
        self._buckets = None        # list of [(param_idx, grad_nd), ...]
        self._sparse = None         # [(param_idx, grad_nd), ...]
        self._owner: Dict[int, int] = {}   # id(grad) -> bucket index
        self._pending: List[int] = []
        self._launched: List = []   # per bucket: None | True | (sig, flat)
        self._nostore = False
        self._zplane = None         # ZeroPlane when MXTPU_ZERO=1

    # -- context management ---------------------------------------------
    def __enter__(self):
        # any stale state from an aborted step is superseded wholesale —
        # by INACTIVE entries too: a caller that skipped an update and
        # then entered a poisoned-step/off scope must not leave the old
        # scope's launched buckets for the next allreduce_grads to split
        # over fresh gradients
        self._trainer._overlap_state = None
        if not self.active:
            return self
        from .. import autograd
        self._cm = autograd.grad_ready_scope(self._on_ready)
        self._cm.__enter__()
        self._trainer._overlap_state = self
        self._trainer.last_allreduce_collectives = 0
        self._trainer.last_reduce_scatter_collectives = 0
        return self

    def __exit__(self, *exc):
        if self._cm is not None:
            self._cm.__exit__(*exc)
            self._cm = None
        if exc and exc[0] is not None and \
                self._trainer._overlap_state is self:
            # backward died mid-pass: buckets already launched hold a
            # partial step's grads. A later allreduce_grads (next step,
            # or a caller that catches and continues) must NOT consume
            # them — the deferred splits would overwrite fresh gradients
            # with this aborted step's values. Abandon wholesale.
            self._trainer._overlap_state = None
        return False

    # -- layout ---------------------------------------------------------
    def _ensure_ready(self) -> bool:
        """Lazy kvstore + bucket layout; returns False when there is no
        store to communicate through (overlap degrades to a no-op and
        allreduce_grads' normal no-store semantics)."""
        if self._nostore:
            return False
        if self._buckets is not None:
            return True
        t = self._trainer
        if not t._kv_initialized:
            t._init_kvstore()
        if t._kvstore is None:
            self._nostore = True
            return False
        plane = t._zero_plane()
        if plane is not None:
            # ZeRO mode: drive the plane's reduce-scatter from the hook.
            # Same per-round checks and pending-allgather drain the
            # barrier reduce_scatter_grads runs, then the SAME bucket
            # layout below (the plane guarantees dense params only)
            plane.check_comm_round()
            plane.flush_pending()
            self._zplane = plane
        from ..ndarray import sparse as _sp
        items, sparse = [], []
        # the SAME forward-order layout as the barrier path: identical
        # bucket contents and _gbkt keys whichever path runs (a store
        # compressor's per-key error-feedback residual sees one layout,
        # and toggling overlap mid-run — the tuner probes it — can't mint
        # a parallel key set). Launch order still follows FINALIZATION
        # order naturally: backward finalizes later layers' grads first,
        # so the later buckets complete — and ship — while backward is
        # still computing the early layers.
        for i, p in enumerate(t._params):
            if p.grad_req == "null" or p._grad is None:
                continue
            g = p.grad()
            if isinstance(g, _sp.RowSparseNDArray):
                sparse.append((i, g))
                continue
            items.append((i, g))
        bucket_mb = t._bucket_mb()
        if bucket_mb > 0:
            self._buckets = t._grad_buckets(items, bucket_mb)
        else:
            # per-key scheduling: every grad launches the moment it is
            # final — the reference engine's exact behavior
            self._buckets = [[it] for it in items]
        self._sparse = sparse
        self._pending = [len(b) for b in self._buckets]
        self._launched = [None] * len(self._buckets)
        for b, bucket in enumerate(self._buckets):
            for _, g in bucket:
                self._owner[id(g)] = b
        return True

    # -- the grad-ready hook (runs on the backward thread) --------------
    def _on_ready(self, gbuf) -> None:
        if not self._ensure_ready():
            return
        b = self._owner.get(id(gbuf))
        if b is None or self._launched[b] is not None:
            return
        self._pending[b] -= 1
        if self._pending[b] > 0:
            return
        # the whole bucket is final: launch its collective NOW, while
        # backward still runs. Exclusive time lands in 'comm_overlapped'
        # (nested inside the loop owner's 'compute' segment).
        with _bd_segment("comm_overlapped"):
            if self._zplane is not None:
                self._launched[b] = self._launch_zero_bucket(b)
            else:
                self._launched[b] = \
                    self._trainer._launch_bucket(b, self._buckets[b]) or True

    def _launch_zero_bucket(self, b):
        """Reduce-scatter one finalized bucket from the backward thread:
        the same ``_gbkt`` key and wire layout as the barrier plane,
        launched at grad finality. Grad rebinds wait for finalize()."""
        t = self._trainer
        bucket = self._buckets[b]
        key = t._bucket_sig_key(b, bucket)[1]
        parts, slices = self._zplane.launch_bucket_rs(t, key, bucket)
        t.last_reduce_scatter_collectives += 1
        return parts, slices

    # -- completion (from Trainer.allreduce_grads) ----------------------
    def finalize(self) -> None:
        if not self._ensure_ready():
            from ..parallel import zero as _zero
            if not self._nostore or not _zero.zero_requested():
                return
            # no-store semantics diverge under ZeRO: the barrier path
            # raises the plane's no-kvstore error rather than silently
            # training unsharded — reproduce it, don't swallow it
            self._trainer._zero_plane()
            return
        t = self._trainer
        if self._zplane is not None:
            self._finalize_zero()
            return
        # stragglers: grads that never announced (tape bypassed, stale
        # grads under ignore_stale_grad) ride the barrier path now
        for b, bucket in enumerate(self._buckets):
            if self._launched[b] is None:
                self._launched[b] = t._launch_bucket(b, bucket) or True
        for b, bucket in enumerate(self._buckets):
            r = self._launched[b]
            if r is not True:
                t._split_bucket(bucket, *r)
        for i, g in self._sparse:
            t._allreduce_rowsparse(i, g)
        if t.last_allreduce_collectives:
            _allreduce_counter().inc(t.last_allreduce_collectives)

    def _finalize_zero(self) -> None:
        """Complete the overlapped ZeRO comm round: reduce-scatter the
        stragglers (grads that never announced ride the barrier path —
        inside the caller's exposed 'comm' segment, truthfully), rebind
        this rank's grads onto the reduced slices, and hand the round's
        (key, bucket) layout to the plane so the allgather half sees the
        identical layout. Arms ``_zero_step`` like the barrier
        ``allreduce_grads`` branch does."""
        t = self._trainer
        plane = self._zplane
        # a fresh comm round supersedes a stale un-consumed decline (the
        # same contract as the barrier allreduce_grads entry)
        t._zero_declined = False
        for b in range(len(self._buckets)):
            if self._launched[b] is None:
                self._launched[b] = self._launch_zero_bucket(b)
        for parts, slices in self._launched:
            plane.finish_bucket_rs(parts, slices)
        plane._step_layout = [
            (t._bucket_sig_key(b, bucket)[1], bucket)
            for b, bucket in enumerate(self._buckets)]
        t._zero_step = plane
        if t.last_reduce_scatter_collectives:
            from ..parallel.zero import _rs_counter
            _rs_counter().inc(t.last_reduce_scatter_collectives)
