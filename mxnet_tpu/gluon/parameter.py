"""Gluon Parameter / ParameterDict.

Reference: python/mxnet/gluon/parameter.py (Parameter with deferred init,
per-device replicas, grad_req; ParameterDict with prefix scoping and
save/load).

TPU-native notes: a Parameter holds ONE logical NDArray. Multi-device data
parallelism does not replicate parameters at the frontend the way the
reference's list_data() does — SPMD sharding over the mesh handles placement
(parallel/ package), so list_data() returns a single-element list on purpose.
"""
from __future__ import annotations

import re
from collections import OrderedDict
from typing import Optional

import numpy as _np

from ..base import MXNetError, check
from ..context import Context, current_context, cpu
from .. import initializer as init_mod
from ..ndarray import ndarray as _nd
from ..telemetry import memory as _memory

__all__ = ["Parameter", "Constant", "ParameterDict", "DeferredInitializationError"]


class DeferredInitializationError(MXNetError):
    """Parameter accessed before shape known (ref: parameter.py)."""


class Parameter:
    #: set by the ZeRO-1 overlapped weight allgather (parallel/zero.py)
    #: on non-local params whose updated value is still in flight: a
    #: zero-arg closure that completes the whole bucket's rebinds, then
    #: clears itself. Class-level default keeps the hot data() path to
    #: one attribute test for every parameter that never prefetches.
    _pending_fetch = None

    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self.name = name
        # true aux states (BatchNorm moving stats) are differentiable=False;
        # user-frozen weights (grad_req='null') stay differentiable and must
        # still export as args, not aux
        self._differentiable = differentiable
        self._grad_req = grad_req if differentiable else "null"
        if isinstance(shape, int):
            shape = (shape,)
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self.stype = stype
        self.grad_stype = grad_stype
        self._data: Optional[_nd.NDArray] = None
        self._grad: Optional[_nd.NDArray] = None
        self._deferred_init = None  # (init, ctx)

    # -- state ----------------------------------------------------------
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        self._grad_req = req
        if self._data is not None:
            if req == "null":
                self._grad = None
                self._data._tape_entry = None
                _memory.drop_param_grad(self)
            else:
                self._attach()

    @property
    def _fresh_grad(self) -> bool:
        """True while the grad buffer holds a gradient written by backward
        that no optimizer step has consumed yet (ref: parameter.py
        _fresh_grad via NDArray fresh_out_grad)."""
        if self._grad is None:
            return False
        return bool(getattr(self._grad, "_fresh_grad", False))

    @_fresh_grad.setter
    def _fresh_grad(self, fresh: bool) -> None:
        if self._grad is not None:
            self._grad._fresh_grad = bool(fresh)

    def _shape_known(self) -> bool:
        return self.shape is not None and all(s > 0 for s in self.shape)

    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False) -> None:
        """(ref: parameter.py Parameter.initialize)"""
        if self._data is not None and not force_reinit:
            return
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0] if ctx else None
        if default_init is None:
            default_init = init_mod.Uniform(0.07)
        if not self._shape_known():
            if not self.allow_deferred_init:
                raise MXNetError(
                    f"cannot initialize parameter {self.name!r}: shape "
                    f"{self.shape} unknown; set allow_deferred_init=True or "
                    "give a full shape")
            self._deferred_init = (init or self.init or default_init, ctx)
            return
        self._finish_init(init or self.init or default_init, ctx)

    def _finish_init(self, initializer, ctx) -> None:
        ctx = ctx if ctx is not None else current_context()
        initializer = init_mod.create(initializer) \
            if not callable(initializer) else initializer
        data = _nd.zeros(self.shape, ctx=ctx, dtype=self.dtype)
        initializer(init_mod.InitDesc(self.name), data)
        self._data = data
        self._deferred_init = None
        _memory.track_param_data(self)
        if self._grad_req != "null":
            self._attach()

    def _attach(self) -> None:
        from .. import autograd
        if getattr(self, "grad_stype", "default") == "row_sparse":
            from ..ndarray import sparse as _sp
            grad = _sp.zeros("row_sparse", self.shape,
                             ctx=self._data.context,
                             dtype=self._data._data.dtype)
        else:
            grad = _nd.zeros(self.shape, ctx=self._data.context,
                             dtype=self._data._data.dtype)
        self._grad = grad
        _memory.track_param_grad(self)
        autograd.mark_variables([self._data], [grad], self._grad_req)

    def _finish_deferred_init(self, in_shape_hint=None) -> None:
        if self._deferred_init is None:
            raise DeferredInitializationError(
                f"parameter {self.name!r} was not initialized")
        initializer, ctx = self._deferred_init
        check(self._shape_known(),
              f"deferred init of {self.name!r}: shape still unknown")
        self._finish_init(initializer, ctx)

    def shape_hint(self, shape) -> None:
        """Complete unknown (0) dims from an observed input shape."""
        if self.shape is None:
            self.shape = tuple(shape)
        else:
            self.shape = tuple(s if s > 0 else h
                               for s, h in zip(self.shape, shape))

    # -- access ---------------------------------------------------------
    def data(self, ctx=None) -> _nd.NDArray:
        if self._pending_fetch is not None:
            # overlapped ZeRO allgather: this weight's updated value is
            # still in flight from its owner rank — complete the bucket
            # on first read (the closure clears every hook it covers)
            self._pending_fetch()
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    f"parameter {self.name!r} deferred-initialized; run a "
                    "forward pass or give explicit shapes first")
            raise MXNetError(f"parameter {self.name!r} is not initialized; "
                             "call initialize()")
        return self._data

    def list_data(self):
        return [self.data()]

    def grad(self, ctx=None) -> _nd.NDArray:
        if self._grad is None:
            raise MXNetError(f"parameter {self.name!r} has no gradient "
                             f"(grad_req={self._grad_req!r})")
        return self._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        return [self._data.context] if self._data is not None else []

    def set_data(self, data) -> None:
        if not isinstance(data, _nd.NDArray):
            data = _nd.array(data)
        if self._data is None:
            self.shape = data.shape
            self._data = data
            _memory.track_param_data(self)
            if self._grad_req != "null":
                self._attach()
        else:
            self._data._rebind(data.astype(self._data._data.dtype)._data
                               if data._data.dtype != self._data._data.dtype
                               else data._data)
            _memory.track_param_data(self)

    def zero_grad(self) -> None:
        self._fresh_grad = False
        if self._grad is None:
            return
        from ..ndarray import sparse as _sp
        if isinstance(self._grad, _sp.RowSparseNDArray):
            empty = _sp.zeros("row_sparse", self._grad.shape,
                              dtype=self._grad._data.dtype)
            self._grad._update(empty._data, empty._indices)
            _memory.track_param_grad(self)  # sparse buffers shrank
            return
        self._grad._rebind(_nd.zeros(self._grad.shape,
                                     ctx=self._grad.context,
                                     dtype=self._grad._data.dtype)._data)

    def reset_ctx(self, ctx) -> None:
        if self._data is not None:
            self._data._rebind(self._data.as_in_context(ctx)._data)

    def cast(self, dtype) -> None:
        self.dtype = dtype
        if self._data is not None:
            self._data._rebind(self._data.astype(dtype)._data)
            _memory.track_param_data(self)
            if self._grad is not None:
                self._grad._rebind(self._grad.astype(dtype)._data)
                _memory.track_param_grad(self)
                from .. import autograd
                autograd.mark_variables([self._data], [self._grad],
                                        self._grad_req)

    def var(self):
        from ..symbol import symbol as _sym
        return _sym.var(self.name, shape=self.shape, dtype=self.dtype)

    def __repr__(self):
        return f"Parameter {self.name} (shape={self.shape}, dtype={self.dtype})"


class Constant(Parameter):
    """Non-learnable parameter (ref: gluon/parameter.py Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, _np.ndarray):
            value = _np.asarray(value, dtype=_np.float32)
        self.value = value
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype,
                         init=init_mod.Constant(0.0))

    def _finish_init(self, initializer, ctx) -> None:
        ctx = ctx if ctx is not None else current_context()
        self._data = _nd.array(self.value, ctx=ctx)
        self._deferred_init = None
        _memory.track_param_data(self)


class ParameterDict:
    """Prefix-scoped dict of parameters (ref: gluon/parameter.py:854-879)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params: "OrderedDict[str, Parameter]" = OrderedDict()
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __getitem__(self, key) -> Parameter:
        return self._params[key]

    def __contains__(self, key):
        return key in self._params

    def __repr__(self):
        body = "\n".join(f"  {v}" for v in self._params.values())
        return f"ParameterDict '{self._prefix}' (\n{body}\n)"

    def get(self, name, **kwargs) -> Parameter:
        """Create-or-retrieve with prefix (ref behavior)."""
        full = self._prefix + name
        if full in self._params:
            param = self._params[full]
            for k, v in kwargs.items():
                if v is not None and k == "shape":
                    if param.shape is None:
                        param.shape = tuple(v) if not isinstance(v, int) else (v,)
            return param
        if self._shared is not None and full in self._shared:
            param = self._shared[full]
            self._params[full] = param
            return param
        param = Parameter(full, **kwargs)
        self._params[full] = param
        return param

    def get_constant(self, name, value=None) -> Constant:
        full = self._prefix + name
        if full in self._params:
            return self._params[full]
        c = Constant(full, value)
        self._params[full] = c
        return c

    def update(self, other) -> None:
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError(f"duplicate parameter name {k}")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False) -> None:
        for p in self._params.values():
            p.initialize(init=None, ctx=ctx,
                         default_init=init or init_mod.Uniform(0.07),
                         force_reinit=force_reinit)

    def zero_grad(self) -> None:
        for p in self._params.values():
            p.zero_grad()

    def reset_ctx(self, ctx) -> None:
        for p in self._params.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value) -> None:
        for p in self._params.values():
            setattr(p, name, value)

    def save(self, filename, strip_prefix="") -> None:
        from ..ndarray import utils as nd_utils
        payload = {}
        for name, p in self._params.items():
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            payload[name] = p.data()
        nd_utils.save(filename, payload)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix="") -> None:
        from ..ndarray import utils as nd_utils
        loaded = nd_utils.load(filename)
        loaded = {restore_prefix + k: v for k, v in loaded.items()}
        if not allow_missing:
            for name in self._params:
                check(name in loaded,
                      f"parameter {name} missing from file {filename}")
        for name, data in loaded.items():
            if name not in self._params:
                if ignore_extra:
                    continue
                raise MXNetError(f"parameter {name} in file is not in this "
                                 "ParameterDict (pass ignore_extra=True)")
            self._params[name].set_data(data if ctx is None
                                        else data.as_in_context(ctx))
