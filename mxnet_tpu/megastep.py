"""One jitted, donated-buffer program per training step (``MXTPU_MEGASTEP``).

The reference framework's GraphExecutor runs the entire symbolic training
step as ONE graph (PAPER.md §6b) — that is why its symbolic path beats
imperative dispatch. This module is the reproduction's equivalent for the
imperative FitLoop: forward + backward + the finiteness sentinel + the
grouped optimizer update (and, under a simulated ZeRO group, the in-graph
loopback collectives) trace into a SINGLE jitted program per
(signature, world), with the weight/grad/optimizer-state buffers donated
into it. A warm step is exactly one dispatched executable: O(1) launches,
XLA schedules the comm/compute overlap PR 16 hand-coded, and
``unattributed_dispatches == 0`` holds by construction — the one
program's cost resolves exactly, so MFU stops being a lower bound.

How the capture works — the CachedOp discipline, widened to a whole step:

- Every Parameter's weight storage, every gradient storage, and every
  optimizer-state handle is **storage-swapped** to an input tracer
  (restored in ``finally``), then the LITERAL composed code path runs
  under the trace: ``net(x)`` (a hybridized block's CachedOp early-
  returns its imperative call under a tracer, inlining cleanly), the
  loss, ``scaled.backward()`` — the SAME tape machinery delivers grads
  into the swapped buffers — the chaos poison site, the fused
  ``_finite_fn`` reduction, and :func:`grouped.apply_chunk` per bucket
  (the SAME cached bucket programs the composed path dispatches, inlined
  by the outer trace). Bitwise parity with the composed path is the
  acceptance contract, including the where-guarded non-finite skip and
  loss-scale backoff.
- Everything that changes per step WITHOUT changing the graph rides as
  dynamic inputs: lr/wd vectors (Adam's bias-corrected lr changes every
  step), rescale, the loss scale (×1.0 is IEEE-exact, so the
  always-present multiply matches the composed skip-at-1.0 branch
  bitwise), and the chaos poison (an always-present ``where(poison,
  full(fill), g)`` on the first trainable grad — identity when off).
- HOST bookkeeping the composed path performs between dispatches —
  chaos event consumption, update-count bumps, state creation, lr
  resolution (:meth:`Trainer.megastep_plan`), rollback arming, fresh-grad
  flags — replays OUTSIDE the program every step, cold and warm alike,
  so ``FitLoop``'s skip/rollback/backoff paths work unchanged.
- The cold path lowers+compiles ONCE (AOT) under the block's shared
  trace lock (:func:`cached_op.trace_rw_for` — the trace mutates shared
  Parameter storage); warm steps call the compiled executable directly,
  so the python body never re-runs.

Strictness contract (the ZeRO plane's): every non-composable
configuration — gradient compression, sparse params, a non-grouped
optimizer, aggregation off, a real multi-worker group, stale-grad
tolerance, ``skip_nonfinite=False`` — raises loudly instead of silently
falling back to the composed path. ``MXTPU_COMM_OVERLAP`` is the one
exception: megastep *supersedes* it (logged once), because the overlap
it hand-codes is exactly what XLA now schedules inside the program.

Known divergence (documented, not silent): in-trace random ops (dropout)
draw from the program's trace key, not the eager stream, so nets with
training-mode randomness match the composed path statistically, not
bitwise — the same caveat ``CachedOp`` carries. Deterministic nets (the
parity suite) are bitwise.
"""
from __future__ import annotations

import functools
import hashlib
import warnings
from typing import Any, List, Optional, Tuple

from .base import MXNetError, check, env
from .log import get_logger
from .optimizer import grouped as _grouped
from .telemetry import efficiency as _efficiency
from .telemetry import memory as _memory
from .telemetry import numerics as _numerics

__all__ = ["megastep_requested", "Megastep", "cache_info",
           "donation_supported"]

_LOG = get_logger("mxnet_tpu.megastep")


def megastep_requested() -> bool:
    """Strict ``MXTPU_MEGASTEP`` parse: on/1/true | off/0/false/unset;
    anything else raises (a typo'd knob must not silently train on the
    composed path)."""
    raw = str(env.get("MXTPU_MEGASTEP") or "").strip().lower()
    if raw in ("", "0", "off", "false"):
        return False
    if raw in ("1", "on", "true"):
        return True
    raise MXNetError(
        f"MXTPU_MEGASTEP: unknown value {raw!r} (known: on, off)")


def cache_info(net):
    """Megastep signature-cache counters for ``net``
    (:class:`cached_op.CacheInfo`), or None when no megastep ever traced
    it. The warm-step contract tests pin ``misses`` here: steps after the
    first must be pure hits."""
    cache = getattr(net, "_mxtpu_megastep_cache", None)
    return cache.cache_info() if cache is not None else None


@functools.lru_cache(maxsize=1)
def donation_supported() -> bool:
    """Whether this backend actually reuses donated input buffers (probed
    once with a trivial jitted donated program). CPU jaxlib builds vary;
    the donation tests assert buffer death only when this is True — the
    memory-ledger parity assertion holds either way."""
    import jax
    import jax.numpy as jnp
    f = jax.jit(lambda a: a + 1.0, donate_argnums=(0,))
    x = jnp.ones((8,), jnp.float32)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        f(x)
    try:
        return bool(x.is_deleted())
    except Exception:
        return False


class _MegaEntry:
    """One compiled megastep per signature."""
    __slots__ = ("compiled", "label", "cost_stats")

    def __init__(self):
        self.compiled = None
        self.label = None
        # efficiency-plane resolution, cached INCLUDING failures — a
        # backend without cost analysis costs one attempt per program,
        # never one per step (the _analyze_sig discipline)
        self.cost_stats = None


class Megastep:
    """The one-program step driver ``FitLoop`` delegates to under
    ``MXTPU_MEGASTEP=on``. Construct once per fit (every statically
    checkable incompatibility raises here, before any step runs); call
    :meth:`run` once per step."""

    def __init__(self, net, trainer, loss_fn, skip_nonfinite: bool = True,
                 ignore_stale_grad: bool = False):
        from .gluon.trainer import _overlap_requested
        check(skip_nonfinite,
              "MXTPU_MEGASTEP=on requires skip_nonfinite=True: the traced "
              "program guards every update behind the in-graph finiteness "
              "sentinel (where(ok, new, old)); a host check-then-raise "
              "flow cannot live inside one program")
        check(not ignore_stale_grad,
              "MXTPU_MEGASTEP=on does not compose with ignore_stale_grad: "
              "the fused program updates a FIXED parameter set per "
              "signature, it cannot drop stale members per step. Fix the "
              "unused parameter (set grad_req='null') or unset "
              "MXTPU_MEGASTEP")
        check(trainer._compression_params is None,
              "MXTPU_MEGASTEP=on does not compose with gradient "
              "compression: per-key error-feedback residuals are "
              "host-side kvstore state that cannot be traced into the "
              "program")
        check(not trainer._contains_sparse,
              "MXTPU_MEGASTEP=on requires dense parameters/gradients "
              "(row_sparse updates take the per-parameter path)")
        rule = _grouped._rule_for(trainer._optimizer)
        check(rule is not None,
              f"MXTPU_MEGASTEP=on: optimizer "
              f"{type(trainer._optimizer).__name__} has no grouped-update "
              "rule (the fused step IS the grouped donated-buffer path)")
        check(_grouped.aggregation_size() > 0,
              "MXTPU_MEGASTEP=on requires MXTPU_OPTIMIZER_AGGREGATION > 0: "
              "the in-graph update is the grouped bucket program")
        if _overlap_requested():
            # superseded, not incompatible: the hand-coded overlap's whole
            # job (launch comm while compute runs) is what XLA's scheduler
            # does inside the one program
            _LOG.info(
                "MXTPU_COMM_OVERLAP superseded by MXTPU_MEGASTEP: XLA "
                "schedules the comm/compute overlap inside the one-program "
                "step")
        self._net = net
        self._trainer = trainer
        self._loss_fn = loss_fn
        self._rule = rule
        from .cached_op import trace_rw_for, SignatureLRU
        # the SAME lock CachedOp guards its storage-swapping traces with:
        # a megastep trace swaps every Parameter/grad/state storage, so it
        # needs write-side exclusivity against any concurrent forward
        # trace over the same block
        self._rw = trace_rw_for(net)
        cache = getattr(net, "_mxtpu_megastep_cache", None)
        if cache is None:
            cache = SignatureLRU()
            try:
                net._mxtpu_megastep_cache = cache
            except AttributeError:
                pass  # slotted/exotic block: per-instance cache
        self._cache = cache
        # kvstore/plane checks need materialized params — resolved at
        # first run (right after the deferred-init priming forward)
        self._plane = None
        self._world = 1
        self._resolved = False

    # -- first-run resolution -------------------------------------------
    def _resolve_runtime(self) -> None:
        if self._resolved:
            return
        tr = self._trainer
        if not tr._kv_initialized:
            tr._init_kvstore()
        kv = tr._kvstore
        if kv is not None and int(getattr(kv, "num_workers", 1) or 1) > 1:
            raise MXNetError(
                "MXTPU_MEGASTEP=on does not compose with a real "
                "multi-worker group: the kvstore transport is a host-side "
                "byte channel that cannot be traced into the program. Use "
                "a simulated group (MXTPU_ZERO_WORLD) or unset "
                "MXTPU_MEGASTEP")
        self._plane = tr._zero_plane()
        if self._plane is not None and self._plane.distributed:
            raise MXNetError(
                "MXTPU_MEGASTEP=on composes with simulated ZeRO worlds "
                "only: a distributed plane's reduce-scatter rides the "
                "host kvstore transport, which cannot be traced into the "
                "program")
        self._world = self._plane.world if self._plane is not None else 1
        self._resolved = True

    # -- signature -------------------------------------------------------
    def _signature(self, gparams, rank_chunks, x_nd, y_nd,
                   collect: bool) -> Tuple:
        from .ops.registry import _trace_time_flags
        tr = self._trainer
        psig = tuple((tuple(p._data._data.shape), str(p._data._data.dtype),
                      p.grad_req) for p in tr._params)
        gsig = tuple((i, tuple(p._grad._data.shape),
                      str(p._grad._data.dtype)) for i, p in gparams)
        plan_sig = tuple(
            tuple(tuple((e[0], e[3],
                         tuple((tuple(h._data.shape), str(h._data.dtype))
                               for h in e[2])) for e in chunk)
                  for chunk in chunks)
            for chunks in rank_chunks)
        xsig = (tuple(x_nd._data.shape), str(x_nd._data.dtype))
        ysig = (tuple(y_nd._data.shape), str(y_nd._data.dtype)) \
            if y_nd is not None else None
        bucket_mb = tr._bucket_mb() if self._plane is not None else None
        return ("megastep", self._rule.name,
                self._rule.statics(tr._optimizer), self._world,
                _grouped.aggregation_size(), bucket_mb, bool(collect),
                psig, gsig, plan_sig, xsig, ysig, _trace_time_flags())

    # -- the traced body -------------------------------------------------
    def _make_pure_fn(self, live, gparams, handles, rank_chunks,
                      comm_layout, collect: bool, has_y: bool):
        import jax.numpy as jnp
        from . import autograd, random as _random
        from .ndarray.ndarray import from_jax
        from .gluon.trainer import _flatten_fn
        from .parallel import collectives as _coll

        net, tr = self._net, self._trainer
        loss_fn = self._loss_fn
        all_params = list(tr._params)
        updater = tr._updaters[0]
        rule = self._rule
        # chaos poison site: the FIRST trainable param with a grad buffer
        # — the same pick chaos.Plan.poison_grads makes
        ptarget = next((p for p in all_params
                        if getattr(p, "grad_req", "null") != "null"
                        and p._grad is not None), None)

        def body(params, grads, states, lrs, wds, rescale, lscale,
                 poison, fill, key, x, y):
            from .ops import registry as _reg
            orig_p = [p._data._data for p in all_params]
            orig_g = [p._grad._data for _i, p in gparams]
            orig_s = [h._data for h in handles]
            _random.push_trace_key(key)
            # island mode: every op traced below compiles as the same
            # isolated fusion region it is eagerly (optimization_barrier
            # at each op boundary), so no cross-op FMA contraction can
            # flip last bits vs the composed trajectory — the program is
            # the composed step's kernels minus the dispatches, and
            # bitwise parity holds by construction
            _reg.push_op_islands()
            try:
                for p, a in zip(all_params, params):
                    p._data._data = a
                for (_i, p), a in zip(gparams, grads):
                    p._grad._data = a
                for h, a in zip(handles, states):
                    h._data = a
                x_nd = from_jax(x)
                y_nd = from_jax(y) if has_y else None
                # the LITERAL FitLoop step body, recorded under the trace:
                with autograd.record():
                    out = net(x_nd)
                    loss = loss_fn(out, y_nd) if has_y else loss_fn(out)
                    # ×1.0 is IEEE-exact (and preserves NaN payloads), so
                    # the always-present multiply matches the composed
                    # path's skip-the-multiply-at-1.0 branch bitwise —
                    # and a loss-scale backoff changes an input, not the
                    # program
                    scaled = loss * from_jax(
                        lscale.astype(loss._data.dtype))
                scaled.backward()
                # chaos poison, in-graph: where-guarded so the program is
                # one and the same whether this step injects or not
                if ptarget is not None:
                    gbuf = ptarget._grad
                    pg = gbuf._data
                    poisoned = jnp.full(pg.shape, fill.astype(pg.dtype),
                                        pg.dtype)
                    gbuf._data = jnp.where(poison, poisoned, pg)
                # the simulated group's reduce-scatter, THROUGH the
                # collective site (loopback_psum), so the comm lives
                # structurally inside the program — same flatten/slice/
                # reshape walk as ZeroPlane.reduce_scatter_grads
                for _key2, bucket, parts in comm_layout:
                    flat = _flatten_fn()(*[g._data for _i, g in bucket])
                    flat = _coll.loopback_psum(flat)
                    for (_i, g, lo, hi) in parts:
                        g._data = flat[lo:hi].reshape(g.shape)
                # grad seam: composed materializes the grads (program
                # outputs of backward) before the sentinel/bucket
                # programs consume them — the barrier reproduces that
                # boundary for the inlined kernels
                for _i, p in live:
                    if p._grad is not None:
                        p._grad._data = _reg._island(p._grad._data)
                # the fused finiteness sentinel, over the SAME grads in
                # the SAME live order as the composed paths (unsharded
                # sentinel_grads / the sim plane's full my_set shard)
                sgrads = tuple(p._grad._data for _i, p in live
                               if p._grad is not None)
                flag = _grouped._finite_fn(len(sgrads))(*sgrads)
                # the loss leaves the program as the PER-SAMPLE vector,
                # not the scalar: the scalar mean is host reporting, and
                # the composed path computes it with the EAGER mean op —
                # an in-graph reduce over the same values can pick a
                # different summation order (XLA codegen is module-
                # context dependent even across optimization_barrier)
                # and flip the reported loss's last bit. run() feeds
                # this vector through the identical eager op instead:
                # bitwise by construction, O(batch) work
                loss_vec = loss._data
                # the grouped update: the SAME cached bucket programs the
                # composed path dispatches, inlined by this trace; lr/wd
                # arrive as slices of the dynamic per-step vectors
                stats_sink: Optional[List] = [] if collect else None
                off = 0
                for chunks in rank_chunks:
                    for chunk in chunks:
                        n = len(chunk)
                        _grouped.apply_chunk(
                            updater, rule, chunk, lrs[off:off + n],
                            wds[off:off + n], rescale, sentinel=True,
                            flag=flag, stats_out=stats_sink,
                            note_dispatches=False)
                        off += n
                new_p = tuple(p._data._data for p in all_params)
                new_g = tuple(p._grad._data for _i, p in gparams)
                new_s = tuple(h._data for h in handles)
                smats = tuple(m for _n, m in stats_sink) if collect \
                    else ()
                return loss_vec, flag, new_p, new_g, new_s, smats
            finally:
                _reg.pop_op_islands()
                _random.pop_trace_key()
                for p, a in zip(all_params, orig_p):
                    p._data._data = a
                for (_i, p), a in zip(gparams, orig_g):
                    p._grad._data = a
                for h, a in zip(handles, orig_s):
                    h._data = a

        if has_y:
            def fn(params, grads, states, lrs, wds, rescale, lscale,
                   poison, fill, key, x, y):
                return body(params, grads, states, lrs, wds, rescale,
                            lscale, poison, fill, key, x, y)
        else:
            def fn(params, grads, states, lrs, wds, rescale, lscale,
                   poison, fill, key, x):
                return body(params, grads, states, lrs, wds, rescale,
                            lscale, poison, fill, key, x, None)
        return fn

    def _trace(self, entry, sig, live, gparams, handles, rank_chunks,
               collect: bool, has_y: bool, args) -> None:
        import jax
        tr = self._trainer
        comm_layout = []
        if self._plane is not None:
            # layout resolved HOST-side, once per trace (graftcheck's
            # no-env-reads-at-trace-time discipline: _bucket_layout reads
            # MXTPU_GRAD_BUCKET_MB); the bucket entries hold the live
            # grad NDArrays, whose storages the trace swaps
            for key2, bucket in self._plane._bucket_layout(tr):
                parts, _all = self._plane._bucket_parts(bucket)
                comm_layout.append((key2, bucket, parts))
        fn = self._make_pure_fn(live, gparams, handles, rank_chunks,
                                comm_layout, collect, has_y)
        jitted = jax.jit(fn, donate_argnums=(0, 1, 2))
        with warnings.catch_warnings():
            # expected, once per signature: 'write'-mode grad inputs are
            # read by nothing in the graph (backward REPLACES them; they
            # ride as inputs so the buffers die inside the program and
            # the 'add'-mode accumulation reads them), so XLA reports
            # them as unusable donations
            warnings.filterwarnings("ignore", message=".*onat.*")
            lowered = jitted.lower(*args)
            # trace-time staleness check: the tape just ran under the
            # trace, so any live param without a delivered grad is
            # structurally unreachable from the loss — the composed
            # path's stale decline becomes a raise-early here
            stale = [p.name for _i, p in live if not p._fresh_grad]
            if stale:
                tr.rollback_step()  # undo megastep_plan's host half
                raise MXNetError(
                    f"MXTPU_MEGASTEP=on: parameter(s) {stale[:4]} receive "
                    "no gradient from the loss (unused in the traced "
                    "step). The fused program updates every live "
                    "parameter; set grad_req='null' on unused parameters "
                    "or unset MXTPU_MEGASTEP")
            entry.compiled = lowered.compile()
        digest = hashlib.md5(repr(sig).encode()).hexdigest()[:12]
        entry.label = (f"megastep:{self._rule.name}:w{self._world}"
                       f":{digest}")

    # -- efficiency-plane resolver --------------------------------------
    def _cost(self, entry) -> Optional[dict]:
        stats = entry.cost_stats
        if stats is None:
            try:
                stats = _efficiency.compiled_program_stats(entry.compiled)
            except Exception:
                stats = None
            if stats is None:
                stats = {"unavailable": True}
            if "flops" not in stats:
                stats = dict(stats, cost_unavailable=True)
            _memory.record_program("megastep", entry.label, dict(stats))
            entry.cost_stats = stats
        return stats

    # -- one step --------------------------------------------------------
    def run(self, x_nd, y_nd, bs, loss_scale: float, plan, step: int):
        """One fused training step. Returns ``(flag, loss_dev)`` — the
        device-resident finiteness verdict and mean loss, fetched by the
        caller in its single step transfer. All host bookkeeping the
        composed path performs between dispatches replays here, so
        FitLoop's skip / rollback / backoff paths work unchanged."""
        import jax.numpy as jnp
        from . import autograd, random as _random
        from .gluon import trainer as _tr_mod

        tr = self._trainer
        net = self._net
        # deferred-init priming OUTSIDE the trace: a traced deferred init
        # would bake the (random) init values into the program as
        # constants. Same init draws, same order, as the composed path's
        # first recorded forward.
        if any(p._data is None for p in tr._params):
            with autograd.pause():
                net(x_nd)
        self._resolve_runtime()
        plane = self._plane
        if plane is not None:
            plane.check_comm_round()

        # chaos: consume the poison event HOST-side (same injected
        # counters as Plan.poison_grads); the fill itself is applied
        # in-graph through the always-present where-guarded inputs
        poison, fill = False, 0.0
        if plan is not None:
            if plan.should("nan_grad"):
                poison, fill = True, float("nan")
            elif plan.should("inf_grad"):
                poison, fill = True, float("inf")

        # numerics plane: one consume-once sampling decision per step; a
        # sampled step runs the stats VARIANT of the program (extra
        # outputs, not extra dispatches)
        collect = _numerics.collect_spec() is not None

        # host half: counts, state creation, lr/wd resolution, rollback
        # arming (Trainer.megastep_plan == the composed path's
        # between-dispatch bookkeeping)
        live, rank_chunks, lr_list, wd_list = tr.megastep_plan(
            bs * loss_scale)
        gparams = [(i, p) for i, p in live if p._grad is not None]
        handles = [h for chunks in rank_chunks for chunk in chunks
                   for e in chunk for h in e[2]]

        params_in = tuple(p._data._data for p in tr._params)
        grads_in = tuple(p._grad._data for _i, p in gparams)
        states_in = tuple(h._data for h in handles)
        args = (params_in, grads_in, states_in,
                jnp.asarray(lr_list, dtype=jnp.float32),
                jnp.asarray(wd_list, dtype=jnp.float32),
                jnp.asarray(float(tr._optimizer.rescale_grad),
                            dtype=jnp.float32),
                jnp.asarray(float(loss_scale), dtype=jnp.float32),
                jnp.asarray(bool(poison), dtype=bool),
                jnp.asarray(float(fill), dtype=jnp.float32),
                _random.next_key(), x_nd._data)
        if y_nd is not None:
            args = args + (y_nd._data,)

        sig = self._signature(gparams, rank_chunks, x_nd, y_nd, collect)
        entry = self._cache.get_or_insert(sig, _MegaEntry)
        if entry.compiled is None:
            # cold: trace + AOT-compile under the block's write lock (the
            # trace swaps shared Parameter storage)
            self._rw.acquire_write()
            try:
                if entry.compiled is None:
                    self._trace(entry, sig, live, gparams, handles,
                                rank_chunks, collect, y_nd is not None,
                                args)
            finally:
                self._rw.release_write()
        outs = entry.compiled(*args)
        loss_vec, flag, new_p, new_g, new_s, smats = outs

        # host completion: every donated buffer's successor rebinds into
        # the live NDArrays (the old buffers died inside the program)
        for p, a in zip(tr._params, new_p):
            p._data._rebind(a)
        for (_i, p), a in zip(gparams, new_g):
            p._grad._rebind(a)
        for h, a in zip(handles, new_s):
            h._rebind(a)
        for _i, p in live:
            p._fresh_grad = False
        if collect:
            names = [tuple(e[1].name for e in chunk)
                     for chunks in rank_chunks for chunk in chunks]
            tr.last_numerics_stats = list(zip(names, smats))
        # observability: ONE dispatched program; the in-graph collectives
        # are not host collectives, so the host counters read 0 (the
        # program's cost — incl. comm — resolves through the megastep
        # record)
        tr.last_update_dispatches = 1
        tr.last_allreduce_collectives = 0
        tr.last_reduce_scatter_collectives = 0
        tr.last_allgather_collectives = 0
        _tr_mod._update_dispatch_counter().inc(1)
        if _efficiency.enabled():
            _efficiency.note_dispatch(
                ("megastep", id(entry)), "megastep", entry.label,
                functools.partial(self._cost, entry))
        # the reported-loss scalarization: the IDENTICAL eager mean op
        # the composed path dispatches, over the program's per-sample
        # loss output — bitwise by construction (see the body comment);
        # O(batch) elements, device-resident, fetched by FitLoop in its
        # one step transfer
        from .ndarray.ndarray import from_jax
        loss_dev = from_jax(loss_vec).mean()._data
        return flag, loss_dev
