"""AttrScope: scoped symbol attributes (ref: python/mxnet/attribute.py).

The reference uses this for ``ctx_group`` model-parallel placement
(example/model-parallel; AttrScope(ctx_group='dev1')). Here ctx_group attrs
map to sharding groups consumed by the parallel layer (see
parallel/sharding.ShardingPlan) instead of device ids.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = ["AttrScope", "current", "attr_scope"]


class _TLS(threading.local):
    def __init__(self):
        self.stack = []


_tls = _TLS()


class AttrScope:
    def __init__(self, **kwargs):
        self._attr = {k: str(v) for k, v in kwargs.items()}

    def get(self, attr: Optional[Dict] = None) -> Dict:
        merged = {}
        for scope in _tls.stack:
            merged.update(scope._attr)
        if attr:
            merged.update(attr)
        return merged

    def __enter__(self):
        _tls.stack.append(self)
        return self

    def __exit__(self, *a):
        _tls.stack.pop()


def current() -> AttrScope:
    return _tls.stack[-1] if _tls.stack else AttrScope()


attr_scope = AttrScope
