"""Global functional PRNG state.

Reference: python/mxnet/random.py (mx.random.seed) over per-device PRNG
resources (include/mxnet/resource.h kRandom). TPU-native: one root
``jax.random`` key per process; every random op consumes a fresh split.
``seed(n)`` makes the whole program reproducible (the reference needed
per-device seeding; XLA's threefry is deterministic per key regardless of
partitioning).
"""
from __future__ import annotations

import threading
from typing import Optional

__all__ = ["seed", "next_key", "uniform", "normal", "randint", "gamma",
           "exponential", "poisson", "negative_binomial",
           "generalized_negative_binomial", "multinomial", "shuffle"]

_lock = threading.Lock()
_key = None
_counter = 0
# None until the user calls seed() explicitly (MXNET_ENFORCE_DETERMINISM
# uses this to detect unseeded host-side sampling)
_seed_value = None

# While tracing a CachedOp/jitted graph, random ops must derive their keys
# from a *traced* key input (otherwise the trace would bake one fixed mask
# into the compiled program). push_trace_key installs that traced key; each
# next_key() call folds in a counter so every op in the graph gets a distinct,
# per-invocation-fresh stream.
_trace_stack = threading.local()


def _jr():
    import jax.random as jr
    return jr


def seed(seed_state: int, ctx=None) -> None:
    """Reset the root key (ref: python/mxnet/random.py seed)."""
    global _key, _seed_value, _counter
    with _lock:
        _seed_value = int(seed_state)
        _key = _jr().PRNGKey(_seed_value)
        _counter = 0


def push_trace_key(key) -> None:
    if not hasattr(_trace_stack, "stack"):
        _trace_stack.stack = []
    _trace_stack.stack.append([key, 0])


def pop_trace_key() -> None:
    _trace_stack.stack.pop()


def next_key():
    """Derive a fresh subkey for one sampling op.

    The root key is NEVER mutated with the result of a jax op: splitting
    under an active jit trace would store a tracer into module state
    (UnexpectedTracerError on the next eager call). Instead subkeys are
    fold_in(root, counter) — the counter is plain python state, safe to
    advance during tracing.
    """
    stack = getattr(_trace_stack, "stack", None)
    if stack:
        entry = stack[-1]
        entry[1] += 1
        return _jr().fold_in(entry[0], entry[1])
    global _key, _counter
    with _lock:
        if _key is None:
            import jax
            # force eager creation even if the first next_key() happens
            # inside a jit trace — a staged PRNGKey would be a tracer
            with jax.ensure_compile_time_eval():
                _key = _jr().PRNGKey(0)
        _counter += 1
        # distinguished fold so the eager stream cannot collide with a
        # trace-key stream even when a caller pushes the root key itself
        return _jr().fold_in(_jr().fold_in(_key, 0xEA6E4), _counter)


def np_rng() -> "_numpy.random.Generator":
    """Numpy Generator seeded from the mx.random key stream.

    Host-side samplers (e.g. the DGL neighbor samplers, which are numpy
    graph algorithms) draw from this instead of the global numpy RNG so
    that `mx.random.seed()` makes them reproducible like every
    device-side random op.

    Under MXNET_ENFORCE_DETERMINISM, using a host-side sampler without an
    explicit mx.random.seed() is an error (the run would not be
    reproducible across restarts)."""
    import numpy as _numpy
    from .base import MXNetError, env
    if env.get("MXNET_ENFORCE_DETERMINISM") and _seed_value is None:
        raise MXNetError(
            "MXNET_ENFORCE_DETERMINISM is set but mx.random.seed() was "
            "never called — host-side sampling would be irreproducible")
    k = next_key()
    try:
        raw = _jr().key_data(k)  # typed keys (jax >= 0.4.16)
    except Exception:
        raw = k  # raw uint32 key arrays
    seed_words = _numpy.asarray(raw).astype(_numpy.uint32).reshape(-1)
    return _numpy.random.default_rng(_numpy.random.SeedSequence(seed_words))


def _nd():
    from .ndarray import register as ndreg
    return ndreg.registry_namespace()


def uniform(low=0, high=1, shape=(1,), dtype=None, ctx=None, out=None):
    from .ndarray import op as _op
    return _op._random_uniform(low=low, high=high, shape=shape, dtype=dtype,
                               ctx=ctx, out=out)


def normal(loc=0, scale=1, shape=(1,), dtype=None, ctx=None, out=None):
    from .ndarray import op as _op
    return _op._random_normal(loc=loc, scale=scale, shape=shape, dtype=dtype,
                              ctx=ctx, out=out)


def randint(low, high, shape=(1,), dtype="int32", ctx=None, out=None):
    from .ndarray import op as _op
    return _op._random_randint(low=low, high=high, shape=shape, dtype=dtype,
                               ctx=ctx, out=out)


def gamma(alpha=1, beta=1, shape=(1,), dtype=None, ctx=None, out=None):
    from .ndarray import op as _op
    return _op._random_gamma(alpha=alpha, beta=beta, shape=shape, dtype=dtype,
                             ctx=ctx, out=out)


def exponential(scale=1, shape=(1,), dtype=None, ctx=None, out=None):
    from .ndarray import op as _op
    return _op._random_exponential(lam=1.0 / scale, shape=shape, dtype=dtype,
                                   ctx=ctx, out=out)


def poisson(lam=1, shape=(1,), dtype=None, ctx=None, out=None):
    from .ndarray import op as _op
    return _op._random_poisson(lam=lam, shape=shape, dtype=dtype, ctx=ctx,
                               out=out)


def negative_binomial(k=1, p=1, shape=(1,), dtype=None, ctx=None, out=None):
    from .ndarray import op as _op
    return _op._random_negative_binomial(k=k, p=p, shape=shape, dtype=dtype,
                                         ctx=ctx, out=out)


def generalized_negative_binomial(mu=1, alpha=1, shape=(1,), dtype=None,
                                  ctx=None, out=None):
    from .ndarray import op as _op
    return _op._random_generalized_negative_binomial(
        mu=mu, alpha=alpha, shape=shape, dtype=dtype, ctx=ctx, out=out)


def multinomial(data, shape=(), get_prob=False, out=None, dtype="int32"):
    from .ndarray import op as _op
    return _op._sample_multinomial(data, shape=shape, get_prob=get_prob,
                                   dtype=dtype, out=out)


def shuffle(data, out=None):
    from .ndarray import op as _op
    return _op._shuffle(data, out=out)
