"""Transformer LM: the long-context / distributed flagship.

The reference's sequence-model story is the fused cuDNN RNN + bucketing
(src/operator/rnn.cc, example/rnn/word_lm); the TPU-native framework adds a
transformer family designed for the mesh from day one:

- weights carry Megatron-style tp shardings (column/row parallel),
- activations are sharded (dp, sp, -) with explicit constraints,
- attention runs as ring attention over the 'sp' axis for long context
  (parallel/ring_attention.py) or plain attention when sp=1,
- the train step is ONE pjit'd program: loss, psum'd grads (inserted by
  GSPMD), and optimizer update fused.

Pure-jax parameter pytree (not Gluon Blocks) so every tensor can carry a
PartitionSpec; the Gluon layer zoo covers the eager/imperative use case.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

from ..base import check

__all__ = ["TransformerConfig", "init_params", "forward", "loss_fn",
           "make_train_step", "param_specs", "make_pipeline_train_step"]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 2048
    max_seq_len: int = 2048
    dtype: Any = None  # e.g. jnp.bfloat16 for MXU-friendly compute
    causal: bool = True
    remat: bool = False  # jax.checkpoint each layer (HBM <-> FLOPs trade)
    # Mixture-of-Experts (expert parallelism over the 'ep' mesh axis;
    # parallel/moe.py). n_experts=0 -> dense FFN everywhere.
    n_experts: int = 0
    moe_every: int = 1   # layer i uses MoE when (i+1) % moe_every == 0
    capacity_factor: float = 1.25
    router_k: int = 1    # top-k routing (1=Switch, 2=GShard)
    aux_loss_coef: float = 0.01

    def is_moe_layer(self, i: int) -> bool:
        return self.n_experts > 0 and (i + 1) % self.moe_every == 0


def _dt(config):
    import jax.numpy as jnp
    return config.dtype or jnp.float32


def _on_tpu() -> bool:
    import jax
    return jax.devices()[0].platform == "tpu"


def init_params(key, config: TransformerConfig) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp
    k = jax.random.split(key, 2 + config.n_layers)
    d, h, f = config.d_model, config.n_heads, config.d_ff
    dt = _dt(config)
    params: Dict[str, Any] = {
        "embed": (jax.random.normal(k[0], (config.vocab_size, d)) * 0.02
                  ).astype(dt),
        "ln_f_scale": jnp.ones((d,), dt),
        "ln_f_bias": jnp.zeros((d,), dt),
    }
    for i in range(config.n_layers):
        kk = jax.random.split(k[2 + i], 6)
        s = 0.02
        lp = {
            "ln1_scale": jnp.ones((d,), dt),
            "ln1_bias": jnp.zeros((d,), dt),
            "w_qkv": (jax.random.normal(kk[0], (d, 3 * d)) * s).astype(dt),
            "wo": (jax.random.normal(kk[1], (d, d)) * s /
                   math.sqrt(2 * config.n_layers)).astype(dt),
            "ln2_scale": jnp.ones((d,), dt),
            "ln2_bias": jnp.zeros((d,), dt),
        }
        if config.is_moe_layer(i):
            from ..parallel.moe import init_moe_params
            lp["moe"] = init_moe_params(kk[4], d, f, config.n_experts,
                                        dtype=dt)
        else:
            lp.update({
                "ffn_in": (jax.random.normal(kk[2], (d, f)) * s).astype(dt),
                "ffn_in_b": jnp.zeros((f,), dt),
                "ffn_out": (jax.random.normal(kk[3], (f, d)) * s /
                            math.sqrt(2 * config.n_layers)).astype(dt),
                "ffn_out_b": jnp.zeros((d,), dt),
            })
        params[f"layer{i}"] = lp
    return params


def _single_layer_specs(config: TransformerConfig, mesh, i: int):
    """Megatron-style tp shardings for one layer: qkv/ffn_in
    column-parallel, wo/ffn_out row-parallel; MoE layers delegate to
    moe_param_specs (ep x tp)."""
    from jax.sharding import PartitionSpec as P
    names = mesh.axis_names if mesh is not None else ()
    tp = "tp" if "tp" in names else None
    vec = P()
    lsp = {
        "ln1_scale": vec, "ln1_bias": vec,
        "w_qkv": P(None, tp),
        "wo": P(tp, None),
        "ln2_scale": vec, "ln2_bias": vec,
    }
    if config.is_moe_layer(i):
        from ..parallel.moe import moe_param_specs
        lsp["moe"] = moe_param_specs(mesh)
    else:
        lsp.update({"ffn_in": P(None, tp), "ffn_in_b": P(tp),
                    "ffn_out": P(tp, None), "ffn_out_b": vec})
    return lsp


def param_specs(config: TransformerConfig, mesh) -> Dict[str, Any]:
    """Full-model shardings: embedding sharded over vocab on tp, layers
    per _single_layer_specs."""
    from jax.sharding import PartitionSpec as P
    tp = "tp" if "tp" in mesh.axis_names else None
    specs: Dict[str, Any] = {
        "embed": P(tp, None),
        "ln_f_scale": P(), "ln_f_bias": P(),
    }
    for i in range(config.n_layers):
        specs[f"layer{i}"] = _single_layer_specs(config, mesh, i)
    return specs


def _pos_encode(tokens, d: int, dtype):
    """Stateless sinusoidal positional encoding, (1, T, d)."""
    import jax.numpy as jnp
    pos = jnp.arange(tokens.shape[1])[:, None]
    dim = jnp.arange(d // 2)[None, :]
    angle = pos / jnp.power(10000.0, 2 * dim / d)
    pe = jnp.concatenate([jnp.sin(angle), jnp.cos(angle)], axis=-1)
    return pe[None].astype(dtype)


def _layernorm(x, scale, bias, eps=1e-5):
    import jax.numpy as jnp
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mean) / jnp.sqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def _block(x, lp, config: TransformerConfig, mesh, act_spec):
    import jax
    import jax.numpy as jnp
    b, t, d = x.shape
    h = config.n_heads
    hd = d // h

    y = _layernorm(x, lp["ln1_scale"], lp["ln1_bias"])
    qkv = jnp.einsum("btd,de->bte", y, lp["w_qkv"])
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, t, h, hd)
    k = k.reshape(b, t, h, hd)
    v = v.reshape(b, t, h, hd)
    from ..parallel.ring_attention import attention, ring_attention
    if mesh is not None and "sp" in mesh.axis_names and \
            dict(zip(mesh.axis_names, mesh.devices.shape))["sp"] > 1:
        attn = ring_attention(q, k, v, mesh, axis="sp", causal=config.causal)
    elif _on_tpu() and t % 128 == 0 and hd >= 64:
        # single-chip hot path: fused Pallas attention (no (T,T) in HBM)
        from ..ops.pallas_kernels import flash_attention
        attn = flash_attention(q, k, v, causal=config.causal)
    else:
        attn = attention(q, k, v, causal=config.causal)
    attn = attn.reshape(b, t, d)
    x = x + jnp.einsum("btd,de->bte", attn, lp["wo"])
    if act_spec is not None:
        x = jax.lax.with_sharding_constraint(x, act_spec)

    y = _layernorm(x, lp["ln2_scale"], lp["ln2_bias"])
    aux = jnp.zeros((), jnp.float32)
    if "moe" in lp:
        from ..parallel.moe import moe_ffn
        ff, aux = moe_ffn(y, lp["moe"], config.n_experts,
                          capacity_factor=config.capacity_factor,
                          k=config.router_k)
        x = x + ff
    else:
        hdn = jnp.einsum("btd,df->btf", y, lp["ffn_in"]) + lp["ffn_in_b"]
        hdn = jax.nn.gelu(hdn)
        x = x + jnp.einsum("btf,fd->btd", hdn, lp["ffn_out"]) \
            + lp["ffn_out_b"]
    if act_spec is not None:
        x = jax.lax.with_sharding_constraint(x, act_spec)
    return x, aux


def forward(params, tokens, config: TransformerConfig, mesh=None,
            return_aux: bool = False):
    """tokens (B, T) int32 -> logits (B, T, vocab).
    With return_aux=True also returns the summed MoE load-balance loss."""
    import jax
    import jax.numpy as jnp
    act_spec = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        act_spec = NamedSharding(
            mesh, P("dp" if "dp" in sizes else None,
                    "sp" if "sp" in sizes else None, None))
    x = params["embed"][tokens]  # (B, T, D)
    x = x + _pos_encode(tokens, config.d_model, x.dtype)
    if act_spec is not None:
        x = jax.lax.with_sharding_constraint(x, act_spec)

    blk = _block
    if config.remat:
        # config, Mesh and NamedSharding are all hashable non-array args
        blk = jax.checkpoint(_block, static_argnums=(2, 3, 4))

    aux = jnp.zeros((), jnp.float32)
    for i in range(config.n_layers):
        x, a = blk(x, params[f"layer{i}"], config, mesh, act_spec)
        aux = aux + a
    x = _layernorm(x, params["ln_f_scale"], params["ln_f_bias"])
    logits = jnp.einsum("btd,vd->btv", x, params["embed"])
    return (logits, aux) if return_aux else logits


def loss_fn(params, tokens, targets, config: TransformerConfig, mesh=None):
    import jax
    import jax.numpy as jnp
    logits, aux = forward(params, tokens, config, mesh, return_aux=True)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll) + config.aux_loss_coef * aux


def make_train_step(config: TransformerConfig, mesh=None, lr: float = 1e-3):
    """Returns (jitted_step, shard_params_fn). step(params, tokens, targets)
    -> (loss, new_params). One XLA program: fwd+bwd+sgd, GSPMD collectives."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def step(params, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets,
                                                  config, mesh)
        new_params = jax.tree_util.tree_map(
            lambda w, g: w - lr * g.astype(w.dtype), params, grads)
        return loss, new_params

    if mesh is None:
        return jax.jit(step, donate_argnums=(0,)), lambda p: p

    specs = param_specs(config, mesh)
    param_shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda s: isinstance(s, P))
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    tok_sharding = NamedSharding(
        mesh, P("dp" if "dp" in sizes else None,
                "sp" if "sp" in sizes else None))

    def shard_params(params):
        return jax.tree_util.tree_map(jax.device_put, params,
                                      param_shardings)

    jitted = jax.jit(step,
                     in_shardings=(param_shardings, tok_sharding,
                                   tok_sharding),
                     out_shardings=(NamedSharding(mesh, P()),
                                    param_shardings),
                     donate_argnums=(0,))
    return jitted, shard_params


# ----------------------------------------------------------------------
# Pipeline parallelism: layers stage-stacked over the 'pp' mesh axis.
# ----------------------------------------------------------------------

def make_pipeline_train_step(config: TransformerConfig, mesh,
                             lr: float = 1e-3,
                             n_microbatches: Optional[int] = None):
    """Pipelined train step over a mesh with a 'pp' axis.

    Layers are grouped into S = |pp| stages (config.n_layers % S == 0; all
    layers must share one structure, i.e. uniformly dense or uniformly
    MoE, so the stage stack is a single pytree). Returns
    (jitted_step, prepare): ``prepare(init_params(...))`` stacks per-layer
    params into {'embed', 'ln_f_*', 'stages'} with leaves (S, L/S, ...)
    sharded P('pp', ...), and ``step(pparams, tokens, targets)`` runs
    fwd (GPipe microbatch schedule, parallel/pipeline.py) + bwd + SGD as
    one XLA program. MoE aux loss is not threaded through the pipeline
    scan (load-balance term is omitted on this path).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..parallel.pipeline import pipeline_apply
    from ..parallel.mesh import axis_size

    S = axis_size(mesh, "pp")
    L = config.n_layers
    check(L % max(S, 1) == 0,
          f"n_layers={L} must divide over {S} pipeline stages")
    lps = L // max(S, 1)
    if config.n_experts > 0:
        moe_flags = [config.is_moe_layer(i) for i in range(L)]
        check(all(moe_flags) or not any(moe_flags),
              "pipeline stacking needs uniform layers (set moe_every=1)")

    names = mesh.axis_names
    sizes = dict(zip(names, mesh.devices.shape))
    dp = "dp" if "dp" in names and sizes.get("dp", 1) > 1 else None

    layer_specs = _single_layer_specs(config, mesh, 0)
    stage_specs = jax.tree_util.tree_map(
        lambda s: P("pp", None, *s), layer_specs,
        is_leaf=lambda s: isinstance(s, P))
    top_specs = {"embed": P(None, None), "ln_f_scale": P(), "ln_f_bias": P(),
                 "stages": stage_specs}
    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), top_specs,
        is_leaf=lambda s: isinstance(s, P))

    def prepare(params):
        layers = [params[f"layer{i}"] for i in range(L)]
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs).reshape(S, lps, *xs[0].shape),
            *layers)
        pparams = {"embed": params["embed"],
                   "ln_f_scale": params["ln_f_scale"],
                   "ln_f_bias": params["ln_f_bias"],
                   "stages": stacked}
        return jax.tree_util.tree_map(jax.device_put, pparams, shardings)

    def stage_fn(lp_stack, xm):
        for j in range(lps):
            lp = jax.tree_util.tree_map(lambda a: a[j], lp_stack)
            xm, _ = _block(xm, lp, config, None, None)
        return xm

    def pipe_forward(pparams, tokens):
        x = pparams["embed"][tokens]
        x = x + _pos_encode(tokens, config.d_model, x.dtype)
        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(dp, None, None)))
        x = pipeline_apply(stage_fn, pparams["stages"], x, mesh,
                           axis="pp", n_microbatches=n_microbatches)
        x = _layernorm(x, pparams["ln_f_scale"], pparams["ln_f_bias"])
        return jnp.einsum("btd,vd->btv", x, pparams["embed"])

    def loss_of(pparams, tokens, targets):
        logits = pipe_forward(pparams, tokens)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
        return jnp.mean(nll)

    tok_sharding = NamedSharding(mesh, P(dp, None))

    def step(pparams, tokens, targets):
        loss, grads = jax.value_and_grad(loss_of)(pparams, tokens, targets)
        new_params = jax.tree_util.tree_map(
            lambda w, g: w - lr * g.astype(w.dtype), pparams, grads)
        return loss, new_params

    jitted = jax.jit(step,
                     in_shardings=(shardings, tok_sharding, tok_sharding),
                     out_shardings=(NamedSharding(mesh, P()), shardings),
                     donate_argnums=(0,))
    return jitted, prepare
