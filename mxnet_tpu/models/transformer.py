"""Transformer LM: the long-context / distributed flagship.

The reference's sequence-model story is the fused cuDNN RNN + bucketing
(src/operator/rnn.cc, example/rnn/word_lm); the TPU-native framework adds a
transformer family designed for the mesh from day one:

- weights carry Megatron-style tp shardings (column/row parallel),
- activations are sharded (dp, sp, -) with explicit constraints,
- attention runs as ring attention over the 'sp' axis for long context
  (parallel/ring_attention.py) or plain attention when sp=1,
- the train step is ONE pjit'd program: loss, psum'd grads (inserted by
  GSPMD), and optimizer update fused.

Pure-jax parameter pytree (not Gluon Blocks) so every tensor can carry a
PartitionSpec; the Gluon layer zoo covers the eager/imperative use case.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

__all__ = ["TransformerConfig", "init_params", "forward", "loss_fn",
           "make_train_step", "param_specs"]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 2048
    max_seq_len: int = 2048
    dtype: Any = None  # e.g. jnp.bfloat16 for MXU-friendly compute
    causal: bool = True
    remat: bool = False  # jax.checkpoint each layer (HBM <-> FLOPs trade)


def _dt(config):
    import jax.numpy as jnp
    return config.dtype or jnp.float32


def _on_tpu() -> bool:
    import jax
    return jax.devices()[0].platform == "tpu"


def init_params(key, config: TransformerConfig) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp
    k = jax.random.split(key, 2 + config.n_layers)
    d, h, f = config.d_model, config.n_heads, config.d_ff
    dt = _dt(config)
    params: Dict[str, Any] = {
        "embed": (jax.random.normal(k[0], (config.vocab_size, d)) * 0.02
                  ).astype(dt),
        "ln_f_scale": jnp.ones((d,), dt),
        "ln_f_bias": jnp.zeros((d,), dt),
    }
    for i in range(config.n_layers):
        kk = jax.random.split(k[2 + i], 6)
        s = 0.02
        params[f"layer{i}"] = {
            "ln1_scale": jnp.ones((d,), dt),
            "ln1_bias": jnp.zeros((d,), dt),
            "w_qkv": (jax.random.normal(kk[0], (d, 3 * d)) * s).astype(dt),
            "wo": (jax.random.normal(kk[1], (d, d)) * s /
                   math.sqrt(2 * config.n_layers)).astype(dt),
            "ln2_scale": jnp.ones((d,), dt),
            "ln2_bias": jnp.zeros((d,), dt),
            "ffn_in": (jax.random.normal(kk[2], (d, f)) * s).astype(dt),
            "ffn_in_b": jnp.zeros((f,), dt),
            "ffn_out": (jax.random.normal(kk[3], (f, d)) * s /
                        math.sqrt(2 * config.n_layers)).astype(dt),
            "ffn_out_b": jnp.zeros((d,), dt),
        }
    return params


def param_specs(config: TransformerConfig, mesh) -> Dict[str, Any]:
    """Megatron-style tp shardings: qkv/ffn_in column-parallel, wo/ffn_out
    row-parallel; embedding sharded over vocab on tp."""
    from jax.sharding import PartitionSpec as P
    has_tp = "tp" in mesh.axis_names
    tp = "tp" if has_tp else None
    vec = P()
    specs: Dict[str, Any] = {
        "embed": P(tp, None),
        "ln_f_scale": vec, "ln_f_bias": vec,
    }
    for i in range(config.n_layers):
        specs[f"layer{i}"] = {
            "ln1_scale": vec, "ln1_bias": vec,
            "w_qkv": P(None, tp),
            "wo": P(tp, None),
            "ln2_scale": vec, "ln2_bias": vec,
            "ffn_in": P(None, tp),
            "ffn_in_b": P(tp),
            "ffn_out": P(tp, None),
            "ffn_out_b": vec,
        }
    return specs


def _layernorm(x, scale, bias, eps=1e-5):
    import jax.numpy as jnp
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mean) / jnp.sqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def _block(x, lp, config: TransformerConfig, mesh, act_spec):
    import jax
    import jax.numpy as jnp
    b, t, d = x.shape
    h = config.n_heads
    hd = d // h

    y = _layernorm(x, lp["ln1_scale"], lp["ln1_bias"])
    qkv = jnp.einsum("btd,de->bte", y, lp["w_qkv"])
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, t, h, hd)
    k = k.reshape(b, t, h, hd)
    v = v.reshape(b, t, h, hd)
    from ..parallel.ring_attention import attention, ring_attention
    if mesh is not None and "sp" in mesh.axis_names and \
            dict(zip(mesh.axis_names, mesh.devices.shape))["sp"] > 1:
        attn = ring_attention(q, k, v, mesh, axis="sp", causal=config.causal)
    elif _on_tpu() and t % 128 == 0 and hd >= 64:
        # single-chip hot path: fused Pallas attention (no (T,T) in HBM)
        from ..ops.pallas_kernels import flash_attention
        attn = flash_attention(q, k, v, causal=config.causal)
    else:
        attn = attention(q, k, v, causal=config.causal)
    attn = attn.reshape(b, t, d)
    x = x + jnp.einsum("btd,de->bte", attn, lp["wo"])
    if act_spec is not None:
        x = jax.lax.with_sharding_constraint(x, act_spec)

    y = _layernorm(x, lp["ln2_scale"], lp["ln2_bias"])
    hdn = jnp.einsum("btd,df->btf", y, lp["ffn_in"]) + lp["ffn_in_b"]
    hdn = jax.nn.gelu(hdn)
    x = x + jnp.einsum("btf,fd->btd", hdn, lp["ffn_out"]) + lp["ffn_out_b"]
    if act_spec is not None:
        x = jax.lax.with_sharding_constraint(x, act_spec)
    return x


def forward(params, tokens, config: TransformerConfig, mesh=None):
    """tokens (B, T) int32 -> logits (B, T, vocab)."""
    import jax
    import jax.numpy as jnp
    act_spec = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        act_spec = NamedSharding(
            mesh, P("dp" if "dp" in sizes else None,
                    "sp" if "sp" in sizes else None, None))
    x = params["embed"][tokens]  # (B, T, D)
    # positions: rotary-free learned-less sinusoidal to stay stateless
    d = config.d_model
    pos = jnp.arange(tokens.shape[1])[:, None]
    dim = jnp.arange(d // 2)[None, :]
    angle = pos / jnp.power(10000.0, 2 * dim / d)
    pe = jnp.concatenate([jnp.sin(angle), jnp.cos(angle)], axis=-1)
    x = x + pe[None].astype(x.dtype)
    if act_spec is not None:
        x = jax.lax.with_sharding_constraint(x, act_spec)

    blk = _block
    if config.remat:
        blk = jax.checkpoint(_block, static_argnums=(2,))

    for i in range(config.n_layers):
        x = blk(x, params[f"layer{i}"], config, mesh, act_spec)
    x = _layernorm(x, params["ln_f_scale"], params["ln_f_bias"])
    logits = jnp.einsum("btd,vd->btv", x, params["embed"])
    return logits


def loss_fn(params, tokens, targets, config: TransformerConfig, mesh=None):
    import jax
    import jax.numpy as jnp
    logits = forward(params, tokens, config, mesh)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll)


def make_train_step(config: TransformerConfig, mesh=None, lr: float = 1e-3):
    """Returns (jitted_step, shard_params_fn). step(params, tokens, targets)
    -> (loss, new_params). One XLA program: fwd+bwd+sgd, GSPMD collectives."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def step(params, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets,
                                                  config, mesh)
        new_params = jax.tree_util.tree_map(
            lambda w, g: w - lr * g.astype(w.dtype), params, grads)
        return loss, new_params

    if mesh is None:
        return jax.jit(step, donate_argnums=(0,)), lambda p: p

    specs = param_specs(config, mesh)
    param_shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda s: isinstance(s, P))
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    tok_sharding = NamedSharding(
        mesh, P("dp" if "dp" in sizes else None,
                "sp" if "sp" in sizes else None))

    def shard_params(params):
        return jax.tree_util.tree_map(jax.device_put, params,
                                      param_shardings)

    jitted = jax.jit(step,
                     in_shardings=(param_shardings, tok_sharding,
                                   tok_sharding),
                     out_shardings=(NamedSharding(mesh, P()),
                                    param_shardings),
                     donate_argnums=(0,))
    return jitted, shard_params
