"""Model families: mesh-native flagships (transformer LM) plus re-exports of
the Gluon vision zoo (ref: python/mxnet/gluon/model_zoo)."""
from . import transformer  # noqa: F401
from ..gluon.model_zoo.vision import (  # noqa: F401
    get_resnet, resnet50_v1, resnet18_v1, resnet101_v1, resnet152_v1,
    alexnet, vgg16, get_model)
