"""Monitor: per-op output inspection for NaN hunting
(ref: python/mxnet/monitor.py + MXExecutorSetMonitorCallback,
src/c_api/c_api_executor.cc:648).

TPU-native: whole-graph compilation means there are no per-op engine
callbacks to hook; instead the Monitor evaluates the executor's internal
outputs on demand (get_internals-style) or wraps eager dispatch. `tic/toc`
semantics match the reference surface.

Jit-native feed (:meth:`Monitor.install_numerics`): the in-graph numerics
plane (``telemetry/numerics.py``, ``MXTPU_NUMERICS``) pushes each sampled
step's per-parameter statistics — grad L2 / abs-max / mean / non-finite
count / update-weight ratio, computed INSIDE the compiled update programs
— into this Monitor's queue, pattern- and activation-gated exactly like
the executor path. The legacy ``tic``/``toc``/``toc_print`` surface is
unchanged; the entries simply come from the plane instead of a host
callback, so they see inside whole-graph jitted programs.
"""
from __future__ import annotations

import re
from typing import Callable, List, Optional, Tuple

import numpy as _np

from .base import MXNetError, check

__all__ = ["Monitor"]


def _default_stat(x) -> "object":
    from .ndarray import array
    return array(_np.asarray([float(_np.abs(x).mean())], dtype=_np.float32))


class Monitor:
    def __init__(self, interval: int, stat_func: Optional[Callable] = None,
                 pattern: str = ".*", sort: bool = False,
                 monitor_all: bool = False):
        self.interval = interval
        self.stat_func = stat_func or (lambda x: _default_stat(x))
        self.re_prog = re.compile(pattern)
        self.sort = sort
        self.monitor_all = monitor_all
        self.step = 0
        self.activated = False
        self.queue: List[Tuple[int, str, object]] = []
        self._exes: List = []

    def install(self, exe) -> None:
        """(ref: monitor.py install_to_executor)"""
        self._exes.append(exe)
        exe.set_monitor_callback(self._stat_helper, self.monitor_all)

    def install_numerics(self) -> "Monitor":
        """Feed this Monitor from the in-graph numerics plane
        (``MXTPU_NUMERICS``): each sampled step's per-parameter stats are
        appended to the ``tic``/``toc`` queue as ``(step,
        "<param>:<stat>", value)`` entries while the Monitor is activated
        and the name matches ``pattern`` — the reference Monitor
        contract, now sourced from inside the compiled update programs.
        Returns self for chaining."""
        from .telemetry import numerics as _numerics
        _numerics.attach_monitor(self)
        return self

    def _stat_helper(self, name, value) -> None:
        if not self.activated or not self.re_prog.match(str(name)):
            return
        from .ndarray.ndarray import NDArray, from_jax
        if not isinstance(value, NDArray):
            value = from_jax(value)
        self.queue.append((self.step, str(name), self.stat_func(value)))

    def tic(self) -> None:
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True

    def toc(self) -> List:
        if not self.activated:
            self.step += 1
            return []
        import logging
        from .ndarray.ndarray import from_jax
        # pull internal outputs from each installed executor
        for exe in self._exes:
            try:
                internals = exe._symbol.get_internals()
                names = internals.list_outputs()
                arg_map = {n: a._data for n, a in exe.arg_dict.items()}
                aux_map = {n: a._data for n, a in exe.aux_dict.items()}
                from .symbol.executor import _walk
                outs = _walk(internals, arg_map, aux_map, False)
                for name, val in zip(names, outs):
                    if self.re_prog.match(name):
                        # stat_func receives an NDArray (the reference
                        # contract: monitor.py stat funcs call .asnumpy())
                        self.queue.append((self.step, name,
                                           self.stat_func(from_jax(val))))
            except Exception as e:
                logging.getLogger("mxnet_tpu").warning(
                    "Monitor: could not evaluate internals of executor "
                    "%r: %s", exe, e)
                continue
        self.activated = False
        res = []
        if self.sort:
            self.queue.sort(key=lambda x: x[1])
        for n, k, v_list in self.queue:
            if not isinstance(v_list, (list, tuple)):
                v_list = [v_list]
            for v in v_list:
                res.append((n, k, str(v.asnumpy() if hasattr(v, "asnumpy")
                                      else v)))
        self.step += 1
        self.queue = []
        return res

    def toc_print(self) -> None:
        for n, k, v in self.toc():
            print(f"Batch: {n:7d} {k:30s} {v}")
