"""Python backend for the native C predict API (src/c_predict_api.cc).

Reference: include/mxnet/c_predict_api.h + src/c_api/c_predict_api.cc —
a standalone, frontend-free predictor over exported models
(symbol JSON + params). The native library embeds CPython and drives the
functions here; buffers cross the boundary as raw float32 pointers
(the reference's mx_float), shapes as uint32 vectors.

Kept deliberately numpy-in/numpy-out so the C side needs no jax or
NDArray knowledge.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .base import MXNetError, check

__all__ = ["Predictor", "load_ndlist"]


def _load_params_bytes(param_bytes: bytes) -> Dict[str, np.ndarray]:
    """Parse a .params payload (arg:/aux: keyed, nd_utils.save layout)."""
    from .ndarray import utils as nd_utils
    import tempfile
    import os
    # nd_utils.load reads from a path; the C API hands us bytes
    with tempfile.NamedTemporaryFile(suffix=".params", delete=False) as f:
        f.write(param_bytes)
        path = f.name
    try:
        loaded = nd_utils.load(path)
    finally:
        os.unlink(path)
    if isinstance(loaded, list):
        raise MXNetError("params file must contain named arrays")
    return {k: v.asnumpy() for k, v in loaded.items()}


class Predictor:
    """One PredictorHandle (ref: c_predict_api.cc PredictorObj)."""

    def __init__(self, symbol_json: str, param_bytes: bytes,
                 dev_type: int = 1, dev_id: int = 0,
                 input_keys: Optional[List[str]] = None,
                 input_shapes: Optional[List[List[int]]] = None,
                 output_keys: Optional[List[str]] = None):
        from .symbol import symbol as sym_mod
        sym = sym_mod.load_json(symbol_json)
        if output_keys:
            internals = sym.get_internals()
            outs = [internals[k if k.endswith("_output") else k + "_output"]
                    for k in output_keys]
            sym = sym_mod.Group(outs) if len(outs) > 1 else outs[0]
        self._sym = sym
        params = _load_params_bytes(param_bytes) if param_bytes else {}
        self._params = {}
        for k, v in params.items():
            name = k.split(":", 1)[1] if ":" in k else k
            self._params[name] = v
        self._input_keys = list(input_keys or [])
        self._input_shapes = {k: tuple(int(d) for d in s)
                              for k, s in zip(self._input_keys,
                                              input_shapes or [])}
        all_inputs = sym.list_inputs()
        for k in self._input_keys:
            check(k in all_inputs,
                  f"input key {k!r} is not an input of the graph "
                  f"({all_inputs})")
        self._inputs: Dict[str, np.ndarray] = {}
        self._outputs: Optional[List[np.ndarray]] = None

    # -- the C API surface ------------------------------------------------
    def set_input(self, key: str, data: np.ndarray) -> None:
        check(key in self._input_keys,
              f"unknown input {key!r}; declared inputs: {self._input_keys}")
        want = self._input_shapes.get(key)
        data = np.ascontiguousarray(data, dtype=np.float32)
        if want and int(np.prod(want)) != data.size:
            raise MXNetError(
                f"input {key!r}: got {data.size} elements, expected "
                f"shape {want}")
        self._inputs[key] = data.reshape(want) if want else data
        self._outputs = None

    def reshaped(self, input_keys: List[str],
                 input_shapes: List[List[int]]) -> "Predictor":
        """(ref: MXPredReshape) — a NEW predictor with re-declared input
        shapes, sharing the graph and weights; the original handle stays
        fully usable with its own shapes (the reference contract)."""
        clone = Predictor.__new__(Predictor)
        clone._sym = self._sym
        clone._params = self._params
        clone._input_keys = list(input_keys)
        clone._input_shapes = {k: tuple(int(d) for d in s)
                               for k, s in zip(input_keys, input_shapes)}
        all_inputs = self._sym.list_inputs()
        for k in clone._input_keys:
            check(k in all_inputs,
                  f"input key {k!r} is not an input of the graph "
                  f"({all_inputs})")
        clone._inputs = {}
        clone._outputs = None
        return clone

    def forward(self) -> None:
        missing = [k for k in self._input_keys if k not in self._inputs]
        check(not missing, f"inputs not set: {missing}")
        from .ndarray import ndarray as _nd
        from .symbol.executor import eval_symbol
        arrays = {k: _nd.array(v) for k, v in self._inputs.items()}
        param_nd = {k: _nd.array(v) for k, v in self._params.items()
                    if k not in arrays}
        outs = eval_symbol(self._sym, list(arrays.keys()),
                           list(arrays.values()), param_nd)
        if not isinstance(outs, list):
            outs = [outs]
        self._outputs = [np.asarray(o.asnumpy(), dtype=np.float32)
                         for o in outs]

    def num_outputs(self) -> int:
        return len(self._sym.list_outputs())

    def get_output_shape(self, index: int) -> List[int]:
        check(self._outputs is not None, "call forward() first")
        check(0 <= index < len(self._outputs), f"bad output index {index}")
        return list(self._outputs[index].shape)

    def get_output(self, index: int) -> np.ndarray:
        check(self._outputs is not None, "call forward() first")
        check(0 <= index < len(self._outputs), f"bad output index {index}")
        return self._outputs[index]


def load_ndlist(nd_bytes: bytes):
    """(ref: MXNDListCreate) — returns (names, arrays) from a saved
    NDArray file. Arrays are coerced to float32 C-contiguous because the
    C side (MXNDListGet) exposes the raw buffer as mx_float*."""
    arrs = _load_params_bytes(nd_bytes)
    names = list(arrs.keys())
    return names, [np.ascontiguousarray(arrs[n], dtype=np.float32)
                   for n in names]
