"""In-graph numerics observability: tensor stats, non-finite provenance,
loss-scale timeline.

The measurement plane covers time (tracer / step breakdown), memory
(live-byte ledger) and cross-rank comm (collective ledger); this module is
the axis that decides whether training *works* — the numbers themselves.
The finiteness sentinel (``Trainer.update_with_sentinel``) can skip a bad
step but cannot name which parameter went non-finite, and the legacy
``Monitor`` surface (``mxnet_tpu/monitor.py``, the reference's
``MXExecutorSetMonitorCallback``) is a host-side callback that cannot see
inside whole-graph jitted programs. Three layers:

**In-graph tensor stats** (``MXTPU_NUMERICS=on[,every=N][,stats=...]
[,pattern=RE]``): on a sampled step the grouped-update bucket programs
(``optimizer/grouped.py``) emit one extra ``(n_params, 6)`` f32 output per
bucket — per-parameter grad/weight sum-of-squares, update sum-of-squares,
grad abs-max, grad mean and non-finite element count, computed from the
SAME traced values the update consumes. A sampled step therefore costs
O(buckets) extra program *outputs* and **zero extra dispatches**; the
device arrays ride the step's existing single flag+loss transfer
(``fit.FitLoop`` fetches them together). An unsampled step costs nothing
on device, and with the plane off the whole hook is one cached flag check
(the tracer discipline). The classic per-parameter fallback path computes
the same matrix with one small dedicated program
(:func:`fallback_collect`) — stats coverage survives a sentinel decline.

**Non-finite provenance**: when a sentinel-skipped step fires with the
plane armed, :func:`nonfinite_step` answers the question the sentinel
can't — *which parameter*: a per-bucket non-finite count pass (one
dispatch) locates the guilty bucket(s), a per-parameter pass inside the
first guilty bucket (one more dispatch) names the first offending
parameter, and a forensics record (``numerics_<pid>_<n>.json``,
tmp+rename, the memory-dump anatomy) lands in ``MXTPU_MEM_DUMP_DIR`` with
the offenders, their recent stats history, the loss-scale timeline and
the last trace window; the culprit is named in an ERROR log. Extra host
syncs happen only on the (already-lost) skipped step — clean steps keep
the sentinel+loss single-transfer contract. Under distributed ZeRO the
shard-local offender lists and stats ride the existing byte channel
(``cross_process_allgather_object`` — recorded in the collective
ledger), so every rank reports the same global verdict.

**Loss-scale timeline**: ``fit.FitLoop`` records every backoff/regrowth
event (step, old→new scale, trigger) through :func:`note_loss_scale` —
recorded even with the plane off, because the trajectory was previously
unobservable (only the final scale was checkpointed). Lands in
``FitResult.numerics["loss_scale_events"]`` and the ``mxtpu_loss_scale``
gauge.

Everything surfaces where the other planes surface: ``FitResult.numerics``
(per-stat recent window + timeline + dumps), ``mxtpu_numerics_*`` registry
gauges, Perfetto ``"C"`` counters (``grad_norm`` / ``update_ratio`` /
``loss_scale``, category ``numerics``) per sampled step,
``tools/trace_report.py`` columns, and the rewired :class:`~mxnet_tpu
.monitor.Monitor` facade (``Monitor.install_numerics``) whose legacy
``tic``/``toc`` queue is fed from here — jit-native, same API.

The plane is numerically inert: stats are additional pure outputs of the
same traced update math — training trajectories are bitwise identical
with it on or off (test-pinned, the PR 6/9/12 discipline).
"""
from __future__ import annotations

import functools
import itertools
import json
import math
import os
import re
import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError, env

__all__ = ["NumericsSpec", "NumericsPlane", "plane", "spec", "enabled",
           "mark_step", "collect_spec", "fallback_collect", "record_step",
           "note_loss_scale", "nonfinite_step", "summary", "reset_run",
           "attach_monitor", "RAW_FIELDS", "STATS"]

#: columns of the raw per-parameter stat matrix the bucket programs emit,
#: in order — grouped.py builds rows in exactly this layout
RAW_FIELDS = ("grad_sumsq", "weight_sumsq", "update_sumsq",
              "absmax", "mean", "nonfinite")

#: publishable derived stats (the ``stats=`` grammar tokens)
STATS = ("l2", "absmax", "mean", "nonfinite", "update_ratio")

#: recent sampled-step records retained (the FitResult window)
RECENT = 64
#: per-parameter stat history depth (what a provenance dump replays)
HIST = 16
#: provenance bisect bucket width (params per stage-1 bucket)
PROV_BUCKET = 16

_dump_seq = itertools.count(1)
_xchg_seq = itertools.count(1)


class NumericsSpec:
    """Parsed ``MXTPU_NUMERICS`` grammar: cadence, stat subset, name
    filter. Immutable; identity-compared by the env cache."""
    __slots__ = ("every", "stats", "pattern", "raw")

    def __init__(self, every: int, stats: Tuple[str, ...],
                 pattern: Optional["re.Pattern"], raw: str):
        self.every = every
        self.stats = stats
        self.pattern = pattern
        self.raw = raw

    def sampled(self, step: int) -> bool:
        return step % self.every == 0

    def wants(self, name: str) -> bool:
        return self.pattern is None or \
            self.pattern.match(str(name)) is not None


def _parse(raw: Optional[str]) -> Optional[NumericsSpec]:
    """Strict ``MXTPU_NUMERICS`` parse — a typo'd request to measure must
    not silently measure nothing (the MXTPU_PROFILE discipline). A spec
    made only of modifiers (``every=``, ``stats=``, ``pattern=``) implies
    ``on``. The pattern must not contain commas (they delimit tokens)."""
    s = (raw or "").strip()
    if not s:
        return None
    want_on = None
    saw_modifier = False
    every, stats, pattern = 1, tuple(STATS), None
    for tok in s.split(","):
        tok = tok.strip()
        if not tok:
            continue
        low = tok.lower()
        if low in ("on", "1", "true", "all"):
            want_on = True
        elif low in ("off", "0", "false"):
            want_on = False
        elif "=" in tok:
            saw_modifier = True
            key, _, val = tok.partition("=")
            key, val = key.strip().lower(), val.strip()
            if key == "every":
                try:
                    every = int(val)
                except ValueError:
                    raise MXNetError(
                        f"MXTPU_NUMERICS: every={val!r} is not an int")
                if every < 1:
                    raise MXNetError(
                        f"MXTPU_NUMERICS: every must be >= 1, got {every}")
            elif key == "stats":
                names = tuple(t.strip() for t in val.split("|") if t.strip())
                if not names:
                    raise MXNetError(
                        "MXTPU_NUMERICS: stats= needs at least one stat, "
                        "e.g. stats=l2|update_ratio")
                bad = [n for n in names if n not in STATS]
                if bad:
                    raise MXNetError(
                        f"MXTPU_NUMERICS: unknown stat(s) {bad} "
                        f"(known: {', '.join(STATS)})")
                stats = names
            elif key == "pattern":
                if not val:
                    raise MXNetError(
                        "MXTPU_NUMERICS: pattern= needs a regex")
                try:
                    pattern = re.compile(val)
                except re.error as e:
                    raise MXNetError(
                        f"MXTPU_NUMERICS: bad pattern {val!r}: {e}")
            else:
                raise MXNetError(
                    f"MXTPU_NUMERICS: unknown key {key!r} "
                    "(known: every, stats, pattern)")
        else:
            raise MXNetError(
                f"MXTPU_NUMERICS: unknown token {tok!r} (known: on, off, "
                "every=N, stats=a|b, pattern=RE)")
    if want_on is False or (want_on is None and not saw_modifier):
        return None
    return NumericsSpec(every, stats, pattern, s)


# raw env string -> parsed spec, cached: the off path is one environ
# lookup + a string compare per call (the collective-ledger discipline);
# strict-parse errors still raise on every call with a bad value
_cache_lock = threading.Lock()
_cached: Optional[Tuple[Optional[str], Optional[NumericsSpec]]] = None


def spec() -> Optional[NumericsSpec]:
    """The active plane spec, or None when off. Cached against the raw
    env string so tests may monkeypatch ``MXTPU_NUMERICS`` mid-process."""
    global _cached
    raw = env.raw("MXTPU_NUMERICS")
    c = _cached
    if c is not None and c[0] == raw:
        return c[1]
    parsed = _parse(raw)
    with _cache_lock:
        _cached = (raw, parsed)
    return parsed


def enabled() -> bool:
    return spec() is not None


def _log():
    from ..log import get_logger
    return get_logger("mxnet_tpu.telemetry")


class NumericsPlane:
    """Per-process numerics state: the sampling clock, the recent-record
    window, per-parameter stat history, the loss-scale timeline, attached
    Monitor facades, and the provenance dump bookkeeping. ``reset_run``
    re-arms it per fit (the ``reset_pressure_state`` discipline)."""

    def __init__(self):
        self._lock = threading.RLock()
        self.records: deque = deque(maxlen=RECENT)
        self.loss_scale_events: deque = deque(maxlen=512)
        self.nonfinite_steps: List[int] = []
        self.culprits: List[str] = []
        self.dump_paths: List[str] = []
        self.samples = 0
        self._hist: Dict[str, deque] = {}
        self._monitors: List[weakref.ref] = []
        # sampling clock: FitLoop marks the real step; bare Trainer loops
        # fall back to an internal counter. Consume-once per step so a
        # fused decline + classic fallback can't double-sample one step.
        self._ext_step: Optional[int] = None
        self._ext_consumed = True
        self._auto_step = -1
        self.last_step: Optional[int] = None

    # -- clock ----------------------------------------------------------
    def mark(self, step: int) -> None:
        with self._lock:
            self._ext_step = int(step)
            self._ext_consumed = False

    def consume(self, s: NumericsSpec) -> Optional[NumericsSpec]:
        """One sampling decision per step: the first collector (the
        grouped update, or the FitLoop fallback after a decline) takes
        it; later calls within the same marked step get None."""
        with self._lock:
            if self._ext_step is not None:
                if self._ext_consumed:
                    return None
                self._ext_consumed = True
                step = self._ext_step
            else:
                self._auto_step += 1
                step = self._auto_step
            self.last_step = step
        return s if s.sampled(step) else None

    # -- listeners ------------------------------------------------------
    def attach_monitor(self, mon) -> None:
        with self._lock:
            self._monitors = [r for r in self._monitors
                              if r() is not None and r() is not mon]
            self._monitors.append(weakref.ref(mon))

    def _feed_monitors(self, per_param: Dict[str, Dict[str, Any]]) -> None:
        with self._lock:
            refs = list(self._monitors)
        for ref in refs:
            mon = ref()
            if mon is None:
                with self._lock:
                    try:
                        self._monitors.remove(ref)
                    except ValueError:
                        pass
                continue
            if not getattr(mon, "activated", False):
                continue
            try:
                for name, d in per_param.items():
                    if mon.re_prog.match(name):
                        for stat, val in d.items():
                            mon.queue.append(
                                (mon.step, f"{name}:{stat}", val))
            except Exception:
                pass  # a broken listener must not take down training

    # -- run lifecycle --------------------------------------------------
    def reset_run(self) -> None:
        with self._lock:
            self.records.clear()
            self.loss_scale_events.clear()
            self.nonfinite_steps = []
            self.culprits = []
            self.dump_paths = []
            self.samples = 0
            self._hist.clear()
            self._ext_step = None
            self._ext_consumed = True
            self._auto_step = -1
            self.last_step = None

    def history(self, name: str) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._hist.get(name, ())]


_PLANE = NumericsPlane()


def plane() -> NumericsPlane:
    return _PLANE


def reset_run() -> None:
    """Re-arm the plane for a fresh run (``fit.FitLoop`` calls this at
    fit start, like ``memory.reset_pressure_state`` /
    ``collective.reset_health``). Also the strict-parse checkpoint: a
    typo'd ``MXTPU_NUMERICS`` raises HERE, before any step runs."""
    spec()
    _PLANE.reset_run()


def mark_step(step: int) -> None:
    """Pin the plane's sampling clock to the loop owner's step counter
    (``fit.FitLoop`` calls this each step). One cached flag check when
    the plane is off."""
    if spec() is None:
        return
    _PLANE.mark(step)


def collect_spec() -> Optional[NumericsSpec]:
    """The Trainer's hook, called once per update: the active spec when
    THIS step is sampled (consume-once), else None. With the plane off
    this is one cached flag check — no clock reads, no device work."""
    s = spec()
    if s is None:
        return None
    return _PLANE.consume(s)


def attach_monitor(mon) -> None:
    """Register a legacy :class:`~mxnet_tpu.monitor.Monitor` as a plane
    listener: sampled-step per-parameter stats are pushed into its
    ``tic``/``toc`` queue (pattern- and activation-gated), so the
    reference Monitor API keeps working against whole-graph jitted
    programs."""
    _PLANE.attach_monitor(mon)


# ---------------------------------------------------------------------------
# Per-parameter fallback stats (the classic non-grouped update path)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=64)
def _fallback_fn(n: int):
    """One jitted program computing the RAW_FIELDS matrix over ``n``
    (weight, grad) pairs — the fallback when the grouped bucket programs
    (which embed the same stats for free) declined. ``update_sumsq`` is 0
    here: the update has not been computed yet on this path."""
    import jax
    import jax.numpy as jnp

    def fn(pairs):
        rows = []
        for w, g in pairs:
            gf = g.astype(jnp.float32)
            wf = w.astype(jnp.float32)
            rows.append(jnp.stack([
                jnp.sum(gf * gf),
                jnp.sum(wf * wf),
                jnp.zeros((), jnp.float32),
                jnp.max(jnp.abs(gf)) if g.size else
                jnp.zeros((), jnp.float32),
                jnp.mean(gf) if g.size else jnp.zeros((), jnp.float32),
                jnp.sum(~jnp.isfinite(g)).astype(jnp.float32),
            ]))
        return jnp.stack(rows)
    return jax.jit(fn)


def fallback_collect(trainer) -> Optional[list]:
    """Sampled-step stats for the per-parameter update path: one small
    dedicated dispatch over every live (weight, grad) pair, parked on
    ``trainer.last_numerics_stats`` so the caller fetches the device
    arrays together with the flag+loss transfer. Returns the parked list
    or None (off / unsampled / nothing live)."""
    s = collect_spec()
    if s is None:
        return None
    names, pairs = [], []
    for p in getattr(trainer, "_params", ()):
        if getattr(p, "grad_req", "null") == "null" or p._grad is None:
            continue
        names.append(p.name)
        pairs.append((p._data._data, p._grad._data))
    if not pairs:
        return None
    mat = _fallback_fn(len(pairs))(tuple(pairs))
    out = [(tuple(names), mat)]
    trainer.last_numerics_stats = out
    return out


# ---------------------------------------------------------------------------
# Publication
# ---------------------------------------------------------------------------

def _gauges():
    from .registry import default_registry
    reg = default_registry()
    return (
        reg.gauge("mxtpu_numerics_grad_norm",
                  "Global gradient L2 norm at the last sampled numerics "
                  "step (MXTPU_NUMERICS)."),
        reg.gauge("mxtpu_numerics_update_ratio",
                  "Global update/weight L2 ratio at the last sampled "
                  "numerics step."),
    )


def _loss_scale_gauge():
    from .registry import default_registry
    return default_registry().gauge(
        "mxtpu_loss_scale",
        "Current dynamic loss scale (fit.FitLoop; every backoff/regrowth "
        "event lands in FitResult.numerics['loss_scale_events']).")


def _derive(row, stats: Sequence[str]) -> Dict[str, Any]:
    """One parameter's published stat dict from its raw matrix row."""
    g2, w2, u2, amax, mean, nf = (float(v) for v in row)
    d: Dict[str, Any] = {}
    if "l2" in stats:
        d["l2"] = math.sqrt(g2) if g2 >= 0 else float("nan")
    if "absmax" in stats:
        d["absmax"] = amax
    if "mean" in stats:
        d["mean"] = mean
    if "nonfinite" in stats:
        d["nonfinite"] = int(nf)
    if "update_ratio" in stats and u2 > 0 and w2 > 0:
        d["update_ratio"] = math.sqrt(u2 / w2)
    return d


def record_step(step: int, items, loss_scale: Optional[float] = None,
                finite: bool = True, trainer=None) -> Optional[dict]:
    """Publish one sampled step's host-fetched stats: ``items`` is a list
    of ``(param_names, matrix)`` pairs (the matrix rows follow
    ``RAW_FIELDS``). Computes the global grad norm / update ratio, the
    pattern-filtered per-parameter stat dicts, feeds the gauges, Perfetto
    counters, stat history and attached Monitors, and appends the record
    to the recent window. Under a distributed ZeRO plane the shard-local
    stats are allgathered over the byte channel first (a collective,
    recorded in the collective ledger) so every rank publishes the same
    global numbers."""
    import numpy as _np
    s = spec()
    if s is None:
        return None
    zp = getattr(trainer, "_zero", None) if trainer is not None else None
    distributed = bool(zp and getattr(zp, "distributed", False))
    if not items and not distributed:
        return None
    if distributed:
        from ..parallel.collectives import cross_process_allgather_object
        shipped = [(list(n), _np.asarray(m, dtype=_np.float64).tolist())
                   for n, m in items]
        gathered = cross_process_allgather_object(
            shipped, f"numst{next(_xchg_seq)}_")
        items = [(tuple(n), m) for part in gathered for n, m in part]
        if not items:
            return None  # every shard empty this step: nothing to record
    g2 = w2 = u2 = 0.0
    nonfinite_params = 0
    per_param: Dict[str, Dict[str, Any]] = {}
    for names, mat in items:
        mat = _np.asarray(mat, dtype=_np.float64)
        for j, name in enumerate(names):
            row = mat[j]
            g2 += float(row[0])
            w2 += float(row[1])
            u2 += float(row[2])
            if int(row[5]) > 0:
                nonfinite_params += 1
            if s.wants(name):
                per_param[str(name)] = _derive(row, s.stats)
    grad_norm = math.sqrt(g2) if g2 >= 0 else float("nan")
    # the fallback path cannot know the would-be update (it runs before
    # the per-param step): u2 == 0 there, and a fabricated 0.0 ratio
    # would read as "updates stopped" — publish None instead
    update_ratio = math.sqrt(u2 / w2) if (u2 > 0 and w2 > 0) else None
    rec = {"step": int(step), "grad_norm": grad_norm,
           "update_ratio": update_ratio, "finite": bool(finite),
           "nonfinite_params": int(nonfinite_params),
           "per_param": per_param}
    if loss_scale is not None:
        rec["loss_scale"] = float(loss_scale)
    with _PLANE._lock:
        _PLANE.records.append(rec)
        _PLANE.samples += 1
        for name, d in per_param.items():
            h = _PLANE._hist.get(name)
            if h is None:
                h = _PLANE._hist[name] = deque(maxlen=HIST)
            h.append(dict(d, step=int(step)))
    try:
        gn, ur = _gauges()
        gn.set(grad_norm if math.isfinite(grad_norm) else -1.0)
        if update_ratio is not None:
            ur.set(update_ratio)
        if loss_scale is not None:
            _loss_scale_gauge().set(float(loss_scale))
    except Exception:
        pass
    try:
        from .tracer import tracer as _tr
        if _tr.enabled and math.isfinite(grad_norm):
            _tr.counter_event("grad_norm", grad_norm, category="numerics")
            if update_ratio is not None:
                _tr.counter_event("update_ratio", update_ratio,
                                  category="numerics")
        if _tr.enabled and loss_scale is not None:
            _tr.counter_event("loss_scale", float(loss_scale),
                              category="numerics")
    except Exception:
        pass
    _PLANE._feed_monitors(per_param)
    return rec


def note_loss_scale(step: int, old: float, new: float,
                    trigger: str) -> None:
    """Record one dynamic-loss-scale transition (``fit.FitLoop`` calls on
    every backoff and regrowth). Recorded with the plane off too — the
    timeline is how a mixed-precision run is graded, and it costs one
    list append."""
    ev = {"step": int(step), "old": float(old), "new": float(new),
          "trigger": str(trigger)}
    with _PLANE._lock:
        _PLANE.loss_scale_events.append(ev)
    try:
        _loss_scale_gauge().set(float(new))
    except Exception:
        pass
    try:
        from .tracer import tracer as _tr
        # counter gated on the PLANE, not just the tracer: a plane-off
        # trace must stay byte-identical to pre-plane output (the
        # trace_report omission contract); the timeline/gauge above are
        # the plane-off surfaces
        if _tr.enabled and spec() is not None:
            _tr.counter_event("loss_scale", float(new),
                              category="numerics")
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Non-finite provenance
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=64)
def _bucket_count_fn(layout: Tuple[int, ...]):
    """Stage 1: per-BUCKET non-finite element totals over a flat grad
    list chunked by ``layout``, in ONE dispatch."""
    import jax
    import jax.numpy as jnp

    def fn(*gs):
        out, off = [], 0
        for n in layout:
            tot = jnp.zeros((), jnp.int32)
            for g in gs[off:off + n]:
                tot = tot + jnp.sum(~jnp.isfinite(g)).astype(jnp.int32)
            out.append(tot)
            off += n
        return jnp.stack(out)
    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _param_count_fn(n: int):
    """Stage 2: per-PARAMETER non-finite counts inside one guilty
    bucket."""
    import jax
    import jax.numpy as jnp

    def fn(*gs):
        return jnp.stack([jnp.sum(~jnp.isfinite(g)).astype(jnp.int32)
                          for g in gs])
    return jax.jit(fn)


def _provenance_scan(named_grads) -> Tuple[Optional[dict], List[dict],
                                           List[int]]:
    """The bisect: per-bucket counts → per-param counts inside each
    guilty bucket. Returns (culprit, offenders, bucket_counts) where the
    culprit is the first (parameter-order) offender."""
    import jax
    if not named_grads:
        return None, [], []
    buckets = [named_grads[i:i + PROV_BUCKET]
               for i in range(0, len(named_grads), PROV_BUCKET)]
    layout = tuple(len(b) for b in buckets)
    flat = [g for b in buckets for (_i, _n, g) in b]
    bcounts = [int(c) for c in jax.device_get(
        _bucket_count_fn(layout)(*flat))]
    offenders: List[dict] = []
    for b, bucket in enumerate(buckets):
        if bcounts[b] == 0:
            continue
        pcounts = jax.device_get(
            _param_count_fn(len(bucket))(*[g for _i, _n, g in bucket]))
        for (idx, name, g), c in zip(bucket, pcounts):
            if int(c) > 0:
                offenders.append({"index": int(idx), "name": str(name),
                                  "nonfinite": int(c),
                                  "size": int(g.size)})
    offenders.sort(key=lambda o: o["index"])
    culprit = offenders[0] if offenders else None
    return culprit, offenders, bcounts


def _dump_path() -> str:
    d = str(env.get("MXTPU_MEM_DUMP_DIR") or "") or "."
    try:
        os.makedirs(d, exist_ok=True)
    except OSError:
        d = "."
    return os.path.join(
        d, f"numerics_{os.getpid()}_{next(_dump_seq)}.json")


def nonfinite_step(step: int, trainer,
                   loss_scale: Optional[float] = None) -> Optional[str]:
    """The provenance pass for one sentinel-skipped step: localize the
    first offending parameter (per-bucket counts → per-param bisect),
    write the forensics record and name the culprit in an ERROR log.
    Returns the dump path (None when the plane is off). Runs AFTER the
    skip verdict is host-known, so its extra syncs cost nothing a clean
    step pays. Under distributed ZeRO each rank scans its reduced shard
    and the offender lists are merged over the byte channel, so every
    rank names the same global culprit."""
    s = spec()
    if s is None:
        return None
    zp = getattr(trainer, "_zero", None)
    distributed = bool(zp and getattr(zp, "distributed", False))
    named = []
    for i, p in enumerate(getattr(trainer, "_params", ())):
        if getattr(p, "grad_req", "null") == "null" or p._grad is None:
            continue
        if distributed and i not in zp.local_indices():
            # non-local grads are unreduced between reduce-scatter and
            # update — only the local shard carries the global sums
            continue
        named.append((i, p.name, p._grad._data))
    culprit, offenders, bcounts = _provenance_scan(named)
    if distributed:
        from ..parallel.collectives import cross_process_allgather_object
        merged = cross_process_allgather_object(
            offenders, f"numprov{next(_xchg_seq)}_")
        offenders = sorted((o for part in merged for o in part),
                           key=lambda o: o["index"])
        culprit = offenders[0] if offenders else culprit
    try:
        from .registry import default_registry
        default_registry().counter(
            "mxtpu_numerics_nonfinite_steps_total",
            "Training steps the sentinel skipped that the numerics plane "
            "ran a provenance pass on.").inc()
    except Exception:
        pass
    trace_window: List[dict] = []
    try:
        from .tracer import tracer as _tr
        trace_window = _tr.events()[-200:]
    except Exception:
        pass
    with _PLANE._lock:
        _PLANE.nonfinite_steps.append(int(step))
        if culprit is not None:
            _PLANE.culprits.append(culprit["name"])
        recent = [dict(r) for r in _PLANE.records]
        ls_events = [dict(e) for e in _PLANE.loss_scale_events]
    payload = {
        "reason": "nonfinite_gradients",
        "time_unix": time.time(),
        "pid": os.getpid(),
        "step": int(step),
        "loss_scale": loss_scale,
        "culprit": culprit,
        "offending_params": [
            dict(o, history=_PLANE.history(o["name"]))
            for o in offenders[:20]],
        "bucket_nonfinite_counts": bcounts,
        "recent_records": recent,
        "loss_scale_events": ls_events,
        "trace_window": trace_window,
    }
    path = _dump_path()
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1, default=str)
        os.replace(tmp, path)
        with _PLANE._lock:
            _PLANE.dump_paths.append(path)
    except Exception as e:
        path = None
        try:
            _log().error("numerics: forensics dump failed (%s)", e)
        except Exception:
            pass
    try:
        if culprit is not None:
            _log().error(
                "numerics: non-finite gradients at step %d — first "
                "offending parameter %r (%d/%d non-finite elements)%s",
                step, culprit["name"], culprit["nonfinite"],
                culprit["size"],
                f" — forensics dump {path}" if path else "")
        else:
            _log().error(
                "numerics: step %d skipped as non-finite but no offending "
                "gradient found on this rank%s", step,
                " (another rank's shard carries the poison)"
                if distributed else "")
    except Exception:
        pass
    return path


# ---------------------------------------------------------------------------
# Summary (FitResult.numerics)
# ---------------------------------------------------------------------------

def summary() -> Optional[dict]:
    """The ``FitResult.numerics`` payload: recent sampled records, the
    loss-scale timeline, non-finite provenance results. None when the
    plane is off AND no loss-scale event fired (nothing to report)."""
    s = spec()
    with _PLANE._lock:
        events = [dict(e) for e in _PLANE.loss_scale_events]
        if s is None and not events:
            return None
        recent = [dict(r) for r in _PLANE.records]
        out = {
            "enabled": s is not None,
            "every": s.every if s is not None else None,
            "stats": list(s.stats) if s is not None else [],
            "samples": _PLANE.samples,
            "recent": recent,
            "loss_scale_events": events,
            "nonfinite_steps": list(_PLANE.nonfinite_steps),
            "culprits": list(_PLANE.culprits),
            "dumps": list(_PLANE.dump_paths),
        }
    if recent:
        out["grad_norm"] = recent[-1]["grad_norm"]
        out["update_ratio"] = recent[-1]["update_ratio"]
    return out
