"""Device-memory observability: live-byte ledger, per-program attribution,
pressure forensics.

The time axis of the measurement plane (tracer / step breakdown / registry)
landed in earlier subsystems; this module is the *memory* axis. Three
layers, coarsest first:

**Live-byte ledger** (:class:`MemoryLedger`): every framework-owned device
allocation is registered by its OWNER at the moment it happens —
``gluon.Parameter`` data/grad buffers, optimizer state and f32 masters
(per-param ``Updater`` path and the grouped/donated fast path),
``Trainer``'s flat ``_gbkt`` gradient-bucket wire buffers,
``DeviceStagingIter``'s staged-ahead batches, serving signature caches and
AOT bundles — keyed by category, with the byte count derived from the
array's shape/dtype. That makes the ledger *exact by construction* for the
tracked categories on every backend including CPU (where PJRT reports no
``memory_stats`` and the polled gauges used to read 0), so tier-1 can
enforce it. On backends that do report ``memory_stats`` the ledger is a
lower bound of ``bytes_in_use`` (XLA temps/activations are not live
framework objects); :func:`reconcile` cross-checks the two.

**Static per-program attribution**: the one category the ledger cannot see
live — activation/workspace memory inside compiled programs — is accounted
statically. Every ``CachedOp`` / grouped-optimizer signature can report
its compiled ``memory_analysis()`` (argument/output/temp/alias bytes),
recorded here per program (:func:`record_program`) and summed into
registry gauges, so "how much workspace does this program need" is a
queryable number per signature instead of an OOM stack trace.

**Pressure forensics**: :func:`dump_forensics` writes the black-box
recording — ranked ledger categories, top live buffers with owners,
per-program temp bytes, backend memory_stats and the recent trace window —
to a JSON file. It fires on allocation failure (``RESOURCE_EXHAUSTED``,
via :func:`oom_guard`), on the live watermark exceeding
``MXTPU_MEM_BUDGET`` (checked per step by ``fit.FitLoop``), and on the
deterministic ``mem_pressure@N[:BYTES]`` chaos event, so the dump path is
testable on CPU.

Ledger mutations are rare (allocation-time, not per-op) and O(1); nothing
here touches the hot dispatch path.
"""
from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..base import env

__all__ = ["CATEGORIES", "MemoryLedger", "ledger", "nd_bytes",
           "compiled_memory_stats", "record_program", "merge_program",
           "get_program",
           "program_report", "program_total", "dump_forensics",
           "check_pressure", "oom_guard", "maybe_dump_oom", "is_oom",
           "budget_bytes", "reset_pressure_state", "reconcile"]

#: ledger categories, in the order forensics ranks ties
CATEGORIES = ("params", "grads", "grad_buckets", "optimizer", "masters",
              "staging", "kvstore", "serving_cache", "aot_bundles", "other")

_KEYS = itertools.count(1)


def nd_bytes(x) -> int:
    """Device bytes of an NDArray / jax array / numpy array, derived from
    shape x itemsize (exact for dense buffers; a row_sparse NDArray counts
    its value and index buffers)."""
    try:
        indices = getattr(x, "_indices", None)
        arr = getattr(x, "_data", x)
        n = int(arr.size) * int(arr.dtype.itemsize)
        if indices is not None:
            idx = getattr(indices, "_data", indices)
            n += int(idx.size) * int(idx.dtype.itemsize)
        return n
    except Exception:
        return 0


class MemoryLedger:
    """Thread-safe category/owner-keyed byte ledger with watermarks.

    Entries are ``(category, key) -> (nbytes, owner)``; :meth:`set`
    replaces in place (re-allocation, dtype cast), :meth:`drop` frees.
    Owners that cannot call drop deterministically attach a
    ``weakref.finalize`` via :meth:`attach` so the entry dies with the
    buffer's owning object. Besides the live totals the ledger keeps a
    process-lifetime peak and a resettable *window* peak — ``fit.FitLoop``
    opens a window per step, giving per-step ``peak_bytes``/``delta_bytes``.
    """

    def __init__(self):
        # RLock, not Lock: drops run from weakref.finalize, which cyclic
        # GC may fire synchronously on THIS thread while it already holds
        # the lock (a dict insert in set() allocates, allocation can
        # collect a dead cycle owning a Parameter whose finalizer calls
        # drop) — a plain Lock would self-deadlock. Same reasoning as
        # cached_op._track_lock.
        self._lock = threading.RLock()
        self._entries: Dict[Tuple[str, Any], Tuple[int, str]] = {}
        self._by_cat: Dict[str, int] = {}
        self._total = 0
        self._peak = 0
        self._win_base = 0
        self._win_peak = 0

    # -- mutation -------------------------------------------------------
    def _bump(self, category: str, delta: int) -> None:
        # caller holds the lock
        self._by_cat[category] = self._by_cat.get(category, 0) + delta
        self._total += delta
        if self._total > self._peak:
            self._peak = self._total
        if self._total > self._win_peak:
            self._win_peak = self._total

    def set(self, category: str, key, nbytes: int, owner: str = "") -> None:
        """Register (or resize) one live allocation."""
        nbytes = int(nbytes)
        with self._lock:
            old = self._entries.get((category, key))
            self._entries[(category, key)] = (nbytes, owner)
            self._bump(category, nbytes - (old[0] if old else 0))

    def drop(self, category: str, key) -> None:
        with self._lock:
            old = self._entries.pop((category, key), None)
            if old is not None:
                self._bump(category, -old[0])

    def drop_owner(self, category: str, owner_prefix: str) -> None:
        """Free every entry in ``category`` whose owner starts with
        ``owner_prefix`` (cache-granular cleanup)."""
        self.drop_matching(lambda cat, _key, own:
                           cat == category and own.startswith(owner_prefix))

    def drop_matching(self, predicate: Callable[[str, Any, str], bool]
                      ) -> None:
        """Free every entry for which ``predicate(category, key, owner)``
        is true — the one place bulk cleanup mutates the accounting."""
        with self._lock:
            doomed = [k for k, (_, own) in self._entries.items()
                      if predicate(k[0], k[1], own)]
            for k in doomed:
                nbytes, _ = self._entries.pop(k)
                self._bump(k[0], -nbytes)

    def attach(self, category: str, nbytes: int, owner: str, obj,
               key=None):
        """Register an allocation and free it automatically when ``obj``
        is garbage-collected. Returns the entry key."""
        if key is None:
            key = ("auto", next(_KEYS))
        self.set(category, key, nbytes, owner)
        try:
            weakref.finalize(obj, self.drop, category, key)
        except TypeError:
            pass  # un-weakref-able owner: entry lives for the process
        return key

    # -- inspection -----------------------------------------------------
    def live_bytes(self, category: Optional[str] = None,
                   owner_prefix: Optional[str] = None) -> int:
        with self._lock:
            if category is None:
                return self._total
            if owner_prefix is None:
                return self._by_cat.get(category, 0)
            return sum(n for (cat, _), (n, own) in self._entries.items()
                       if cat == category and own.startswith(owner_prefix))

    def snapshot(self) -> Dict[str, int]:
        """Live bytes per category (only categories with bytes)."""
        with self._lock:
            return {c: n for c, n in sorted(self._by_cat.items()) if n}

    @property
    def peak_bytes(self) -> int:
        return self._peak

    def begin_window(self) -> int:
        """Open a watermark window (one per step); returns live bytes."""
        with self._lock:
            self._win_base = self._total
            self._win_peak = self._total
            return self._total

    def window_stats(self) -> Tuple[int, int]:
        """(peak, delta) bytes since :meth:`begin_window`."""
        with self._lock:
            return self._win_peak, self._total - self._win_base

    def top(self, n: int = 20) -> List[Dict[str, Any]]:
        """The ``n`` largest live allocations, ranked."""
        with self._lock:
            items = [{"category": cat, "owner": own, "bytes": size}
                     for (cat, _), (size, own) in self._entries.items()]
        items.sort(key=lambda e: -e["bytes"])
        return items[:n]

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            total, peak = self._total, self._peak
            by_cat = {c: n for c, n in sorted(self._by_cat.items()) if n}
        return {"live_bytes": total, "peak_bytes": peak,
                "by_category": by_cat,
                "budget_bytes": budget_bytes() or None}


_LEDGER = MemoryLedger()
_metrics_installed = False
_install_lock = threading.Lock()


def ledger() -> MemoryLedger:
    """The process-wide ledger (installs registry gauges on first use)."""
    _install_metrics()
    return _LEDGER


def _install_metrics() -> None:
    global _metrics_installed
    with _install_lock:
        if _metrics_installed:
            return
        _metrics_installed = True
    try:
        from .registry import default_registry
        reg = default_registry()
        reg.callback_gauge(
            "mxtpu_mem_live_bytes", _LEDGER.live_bytes,
            "Live framework-attributed device bytes (memory ledger).")
        reg.callback_gauge(
            "mxtpu_mem_peak_bytes", lambda: _LEDGER.peak_bytes,
            "Process-lifetime peak of the memory-ledger total.")
        for cat in CATEGORIES:
            reg.callback_gauge(
                f"mxtpu_mem_{cat}_bytes",
                (lambda c=cat: _LEDGER.live_bytes(c)),
                f"Live device bytes attributed to category '{cat}'.")
        reg.callback_gauge(
            "mxtpu_program_temp_bytes", lambda: _program_total("temp_bytes"),
            "XLA temp (workspace/activation) bytes over recorded compiled "
            "programs (static memory_analysis attribution).")
        reg.callback_gauge(
            "mxtpu_program_argument_bytes",
            lambda: _program_total("argument_bytes"),
            "XLA argument bytes over recorded compiled programs.")
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Owner hooks. Never raise: observability must not take down training.
# ---------------------------------------------------------------------------

def _param_key(p) -> Optional[int]:
    key = getattr(p, "_mem_key", None)
    if key is None:
        key = next(_KEYS)
        try:
            p._mem_key = key
        except Exception:
            return None
        weakref.finalize(p, _drop_param_entries, key)
    return key


def _drop_param_entries(key: int) -> None:
    try:
        _LEDGER.drop("params", ("p", key))
        _LEDGER.drop("grads", ("g", key))
    except Exception:
        pass  # interpreter shutdown


def track_param_data(p) -> None:
    """Register (or resize) a Parameter's data buffer."""
    try:
        if p._data is None:
            return
        key = _param_key(p)
        if key is not None:
            _LEDGER.set("params", ("p", key), nd_bytes(p._data),
                        owner=p.name)
    except Exception:
        pass


def track_param_grad(p) -> None:
    try:
        if p._grad is None:
            return
        key = _param_key(p)
        if key is not None:
            _LEDGER.set("grads", ("g", key), nd_bytes(p._grad),
                        owner=p.name)
    except Exception:
        pass


def drop_param_grad(p) -> None:
    try:
        key = getattr(p, "_mem_key", None)
        if key is not None:
            _LEDGER.drop("grads", ("g", key))
    except Exception:
        pass


def _updater_key(updater) -> Optional[int]:
    key = getattr(updater, "_mem_key", None)
    if key is None:
        key = next(_KEYS)
        try:
            updater._mem_key = key
        except Exception:
            return None
        weakref.finalize(updater, _drop_updater_entries, key)
    return key


def _drop_updater_entries(utok: int) -> None:
    try:
        _LEDGER.drop_matching(
            lambda _cat, key, _own: isinstance(key, tuple) and
            len(key) == 2 and key[0] == utok)
    except Exception:
        pass


def drop_updater_states(updater) -> None:
    """Free every optimizer/masters entry of this updater (checkpoint
    restore replaces the state dict wholesale — stale indices the new
    dict lacks must not keep their bytes)."""
    utok = getattr(updater, "_mem_key", None)
    if utok is not None:
        _drop_updater_entries(utok)


def _state_arrays(state) -> List:
    out = []
    if state is None:
        return out
    if isinstance(state, (tuple, list)):
        for s in state:
            out.extend(_state_arrays(s))
    elif hasattr(state, "_data"):
        out.append(state)
    return out


def track_optimizer_state(updater, index, state, param=None,
                          weight=None) -> None:
    """Register one parameter's optimizer state; the f32 master copy of a
    multi-precision state (the ``(inner, w32)`` convention of
    ``create_state_multi_precision``) is split into the ``masters``
    category. The split needs the WEIGHT dtype (mp wraps only non-f32
    weights, and Adam's plain ``(m, v)`` is structurally identical to
    ``(inner, w32)``): resolved from ``param``, the ``weight`` NDArray
    (the kvstore-updater call path, where ``param_dict`` is empty after
    the optimizer pickle round-trip), or ``opt.param_dict``. With no
    dtype source the state lands wholly in ``optimizer`` — the total
    stays exact, only the split degrades."""
    try:
        utok = _updater_key(updater)
        if utok is None:
            return
        opt = updater.optimizer
        if param is None:
            param = getattr(opt, "param_dict", {}).get(index)
        name = getattr(param, "name", str(index))
        wdt = None
        if param is not None and getattr(param, "_data", None) is not None:
            wdt = str(param._data._data.dtype)
        elif weight is not None:
            wdt = str(getattr(weight, "_data", weight).dtype)
        master = None
        inner = state
        if bool(getattr(opt, "multi_precision", False)) and \
                isinstance(state, tuple) and len(state) == 2 and \
                hasattr(state[1], "_data") and \
                wdt is not None and wdt != "float32":
            inner, master = state
        # shard-aware owners: a ZeRO-1 plane stamps the updater with its
        # partition map (parallel/zero.py), and every state entry carries
        # the owning rank — per-rank optimizer/masters bytes become a
        # queryable prefix ('state:zr<r>/<N>:') the 1/N claim is
        # test-enforced against
        shard = ""
        zs = getattr(updater, "_zero_shard", None)
        if zs:
            tag = zs.get(index)
            if tag is not None:
                shard = f"zr{tag}:"
        inner_bytes = sum(nd_bytes(a) for a in _state_arrays(inner))
        _LEDGER.set("optimizer", (utok, index), inner_bytes,
                    owner=f"state:{shard}{name}")
        if master is not None:
            _LEDGER.set("masters", (utok, index), nd_bytes(master),
                        owner=f"master:{shard}{name}")
        else:
            _LEDGER.drop("masters", (utok, index))
    except Exception:
        pass


def drop_optimizer_state(updater, index) -> None:
    """Free one state's entries (sentinel-skipped step rollback)."""
    try:
        utok = getattr(updater, "_mem_key", None)
        if utok is not None:
            _LEDGER.drop("optimizer", (utok, index))
            _LEDGER.drop("masters", (utok, index))
    except Exception:
        pass


def track_ndarray(category: str, nd, owner: str = "") -> None:
    """Register a transient buffer, freed when the NDArray dies (the flat
    ``_gbkt`` gradient-bucket wire buffers)."""
    try:
        _LEDGER.attach(category, nd_bytes(nd), owner, nd)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Sparse embedding plane attribution (parallel/embedding_plane.py): the
# row-wise analog of the ZeRO ``state:zr<r>/<N>:`` owners. Each rank's
# table shard lands under ``params`` as ``emb<r>/<N>:<table>`` and its
# lazily-created row optimizer state under ``optimizer`` as
# ``state:emb<r>/<N>:<table>`` — so "per-rank embedding bytes are exactly
# 1/world" is a ledger query, not an estimate. Stable keys (not weakrefs):
# the plane rebinds shard arrays every step; the entry must track the
# logical shard, not one jax buffer's lifetime.
# ---------------------------------------------------------------------------

def plane_owner(rank: int, world: int, name: str,
                state: bool = False) -> str:
    """The ledger owner string of one plane shard (or its row state)."""
    tag = f"emb{int(rank)}/{int(world)}:{name}"
    return f"state:{tag}" if state else tag


def track_plane_shard(name: str, rank: int, world: int, arr) -> None:
    """Register (or resize after a rebind) one rank's table shard."""
    try:
        _LEDGER.set("params", ("embshard", name, int(rank)),
                    nd_bytes(arr), owner=plane_owner(rank, world, name))
    except Exception:
        pass


def track_plane_state(name: str, rank: int, world: int, arrs) -> None:
    """Register one rank's lazily-created row optimizer state arrays."""
    try:
        _LEDGER.set("optimizer", ("embstate", name, int(rank)),
                    sum(nd_bytes(a) for a in arrs),
                    owner=plane_owner(rank, world, name, state=True))
    except Exception:
        pass


def drop_plane_state(name: str, rank: int, world: int) -> None:
    """Free one rank's row-state entry (sentinel-skip rollback of a step
    that first materialized it)."""
    try:
        _LEDGER.drop("optimizer", ("embstate", name, int(rank)))
    except Exception:
        pass


def drop_plane(name: str) -> None:
    """Free every ledger entry of one plane (table close/re-create)."""
    try:
        _LEDGER.drop_matching(
            lambda _cat, key, _own: isinstance(key, tuple) and len(key) == 3
            and key[0] in ("embshard", "embstate") and key[1] == name)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Static per-program attribution
# ---------------------------------------------------------------------------

_PROGRAMS: Dict[Tuple[str, str], Dict[str, Any]] = {}
_prog_lock = threading.Lock()


def compiled_memory_stats(compiled) -> Optional[Dict[str, int]]:
    """Extract ``memory_analysis()`` from a jax Compiled object into a
    plain int dict; None when the backend reports no analysis. Thin
    memory-fields view over the ONE shared extraction helper
    (``efficiency.compiled_program_stats`` — the cost half lands in the
    same registry records); output byte-identical to the historical
    hand-rolled extraction (regression-pinned)."""
    from .efficiency import MEMORY_FIELDS, compiled_program_stats
    stats = compiled_program_stats(compiled)
    if stats is None or "argument_bytes" not in stats:
        return None
    return {k: stats[k] for k in MEMORY_FIELDS}


def record_program(kind: str, label: str, stats: Dict[str, Any]) -> None:
    """Record one compiled program's static memory footprint, keyed by
    (kind, label) — e.g. ("cached_op", "ResNet:ab12...")."""
    with _prog_lock:
        _PROGRAMS[(kind, label)] = dict(stats)


def merge_program(kind: str, label: str, stats: Dict[str, Any]) -> None:
    """Merge fields into one program's record ATOMICALLY (under the
    registry lock). The memory and cost halves of a record may resolve
    at different times on different threads (``memory_analysis`` on a
    monitoring thread, the efficiency resolver at step end) — a
    read-modify-write outside the lock would let one half clobber the
    other's freshly-added fields."""
    with _prog_lock:
        rec = dict(_PROGRAMS.get((kind, label)) or {})
        rec.update(stats)
        _PROGRAMS[(kind, label)] = rec


def get_program(kind: str, label: str) -> Optional[Dict[str, Any]]:
    with _prog_lock:
        hit = _PROGRAMS.get((kind, label))
    return dict(hit) if hit is not None else None


def program_report(limit: Optional[int] = None) -> List[Dict[str, Any]]:
    """Recorded programs ranked by temp (workspace) bytes."""
    with _prog_lock:
        rows = [{"kind": k, "label": lbl, **st}
                for (k, lbl), st in _PROGRAMS.items()]
    rows.sort(key=lambda r: -int(r.get("temp_bytes", 0)))
    return rows[:limit] if limit else rows


def _program_total(field: str) -> int:
    with _prog_lock:
        return sum(int(st.get(field, 0) or 0) for st in _PROGRAMS.values())


def program_total(field: str) -> int:
    """Sum of one numeric field over every recorded program (the
    ``mxtpu_program_*`` gauges — memory fields here, cost fields via
    ``efficiency``'s gauges)."""
    return _program_total(field)


def register_cache_programs(owner: str, op, stats: Dict[str, dict]) -> None:
    """Ledger the static footprint (temp + output bytes) of a signature
    cache's compiled programs under ``serving_cache``, freed when the
    owning CachedOp dies (model drained/undeployed) and refreshed
    wholesale on each call (evicted signatures drop out)."""
    try:
        # trailing ':' keeps prefix matching exact — owner 'sigcache3'
        # must not also claim 'sigcache30' entries
        _LEDGER.drop_owner("serving_cache", owner + ":")
        for digest, st in stats.items():
            _LEDGER.set("serving_cache", (owner, digest),
                        int(st.get("temp_bytes", 0)) +
                        int(st.get("output_bytes", 0)),
                        owner=f"{owner}:{digest}")
        if not getattr(op, "_mem_finalized", False):
            try:
                op._mem_finalized = True
                weakref.finalize(op, _LEDGER.drop_owner,
                                 "serving_cache", owner + ":")
            except Exception:
                pass
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Pressure monitoring + forensics
# ---------------------------------------------------------------------------

_dump_seq = itertools.count(1)
_budget_exceeded = [False]  # rising-edge latch; re-armed per fit() run


def budget_bytes() -> int:
    """MXTPU_MEM_BUDGET in bytes (0 = no budget)."""
    try:
        return int(env.get("MXTPU_MEM_BUDGET"))
    except (TypeError, ValueError):
        return 0


def reset_pressure_state() -> None:
    """Re-arm the budget-exceeded edge detector (one dump per run)."""
    _budget_exceeded[0] = False


def is_oom(exc: BaseException) -> bool:
    """Does this exception look like a device allocation failure?"""
    text = f"{type(exc).__name__}: {exc}"
    return ("RESOURCE_EXHAUSTED" in text or "Out of memory" in text or
            "out of memory" in text)


def maybe_dump_oom(exc: BaseException, step: Optional[int] = None) -> bool:
    """If ``exc`` is an allocation failure, write the forensics dump
    (best-effort — a failed dump must not mask the OOM) and return True.
    The ONE implementation of the dump-on-OOM protocol: ``oom_guard``
    and ``fit.FitLoop``'s exception path both route here."""
    if not (isinstance(exc, Exception) and is_oom(exc)):
        return False
    try:
        dump_forensics("resource_exhausted", step=step,
                       error=f"{type(exc).__name__}: {exc}")
    except Exception:
        pass
    return True


@contextlib.contextmanager
def oom_guard(step_fn: Optional[Callable[[], Optional[int]]] = None):
    """Re-raises everything, but an allocation failure
    (``RESOURCE_EXHAUSTED``) first triggers a forensics dump — the
    black-box recording written while the evidence is still live."""
    try:
        yield
    except BaseException as e:  # noqa: B902 — inspect, always re-raise
        try:
            step = step_fn() if step_fn is not None else None
        except Exception:
            step = None
        maybe_dump_oom(e, step=step)
        raise


def _dump_path() -> str:
    d = str(env.get("MXTPU_MEM_DUMP_DIR") or "") or "."
    try:
        os.makedirs(d, exist_ok=True)
    except OSError:
        d = "."
    return os.path.join(
        d, f"mem_forensics_{os.getpid()}_{next(_dump_seq)}.json")


def dump_forensics(reason: str, budget: Optional[int] = None,
                   step: Optional[int] = None, path: Optional[str] = None,
                   error: Optional[str] = None) -> str:
    """Write the ranked memory diagnosis to a JSON file and return its
    path: ledger categories and top live buffers (with owners),
    per-program temp bytes, backend ``memory_stats`` and the recent trace
    window — everything needed to name the allocation owners after an
    OOM, without a debugger attached to the dead process."""
    total = _LEDGER.live_bytes()
    by_cat = _LEDGER.snapshot()
    cats = [{"category": c, "bytes": n,
             "share": round(n / total, 4) if total else 0.0}
            for c, n in sorted(by_cat.items(), key=lambda kv: -kv[1])]
    backend = {}
    try:
        from ..storage import memory_stats
        backend = memory_stats() or {}
    except Exception:
        pass
    trace_window: List[dict] = []
    try:
        from .tracer import tracer as _tr
        trace_window = _tr.events()[-200:]
    except Exception:
        pass
    payload = {
        "reason": reason,
        "error": error,
        "time_unix": time.time(),
        "pid": os.getpid(),
        "step": step,
        "budget_bytes": budget if budget is not None else
        (budget_bytes() or None),
        "live_bytes": total,
        "peak_bytes": _LEDGER.peak_bytes,
        "categories": cats,
        "top_buffers": _LEDGER.top(20),
        "programs": program_report(limit=20),
        "backend_memory_stats": backend,
        "trace_window": trace_window,
    }
    if path is None:
        path = _dump_path()
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        # default=str: span args are producer-defined objects; a
        # non-serializable one must degrade to its repr, not lose the dump
        json.dump(payload, f, indent=1, default=str)
    os.replace(tmp, path)
    try:
        from .registry import default_registry
        default_registry().counter(
            "mxtpu_mem_forensics_dumps_total",
            "Memory forensics dumps written, by trigger.",
            label="reason").inc(label_value=reason)
    except Exception:
        pass
    try:
        from ..log import get_logger
        get_logger("mxnet_tpu.telemetry").warning(
            "memory forensics (%s): live %d bytes, peak %d — dumped to %s",
            reason, total, _LEDGER.peak_bytes, path)
    except Exception:
        pass
    return path


def check_pressure(step: Optional[int] = None, plan=None) -> Optional[str]:
    """Per-step watermark check (called by ``fit.FitLoop`` at each step
    end): fires a forensics dump when the deterministic ``mem_pressure``
    chaos event is scheduled at this step, or — on the rising edge only —
    when the step's ledger watermark exceeds ``MXTPU_MEM_BUDGET``.
    Returns the dump path, or None."""
    peak, _ = _LEDGER.window_stats()
    peak = max(peak, _LEDGER.live_bytes())
    dumped = None
    if plan is not None:
        b = None
        try:
            b = plan.mem_pressure_bytes()
        except AttributeError:
            pass
        if b is not None and peak > b:
            dumped = dump_forensics("chaos_mem_pressure", budget=b,
                                    step=step)
    budget = budget_bytes()
    if budget > 0:
        if peak > budget and not _budget_exceeded[0]:
            _budget_exceeded[0] = True
            dumped = dump_forensics("budget_exceeded", budget=budget,
                                    step=step)
        elif peak <= budget:
            _budget_exceeded[0] = False
    return dumped


def reconcile(ctx=None) -> Dict[str, Any]:
    """Cross-check the ledger against the backend allocator where one
    reports (``storage.memory_stats``): the ledger is a lower bound of
    ``bytes_in_use`` (XLA-internal temps are not framework objects).
    Returns {"ledger_bytes", "backend_bytes_in_use", "backend_peak",
    "consistent"}; backend fields are None on host-CPU backends."""
    from ..storage import memory_stats
    stats = memory_stats(ctx)
    in_use = stats.get("bytes_in_use")
    peak = stats.get("peak_bytes_in_use")
    led = _LEDGER.live_bytes()
    consistent = None
    if in_use is not None:
        consistent = led <= int(in_use) * 1.02 + (1 << 20)
    return {"ledger_bytes": led,
            "backend_bytes_in_use": int(in_use) if in_use is not None
            else None,
            "backend_peak": int(peak) if peak is not None else None,
            "consistent": consistent}
