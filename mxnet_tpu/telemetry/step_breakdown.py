"""Per-step time breakdown: where does the step time go?

The first question any training-stack operator asks. ``fit.FitLoop`` (and
anything else that wants it) brackets each step's phases with
:func:`segment`; this module turns the brackets into

- tracer spans (category = segment name) for the chrome trace, and
- per-step **exclusive** second counts per segment — a segment nested
  inside another (h2d staging inside data_wait, a kvstore push inside comm)
  is charged once, to the innermost bracket, so the per-step segment sums
  compare directly against wall-clock step time.

Segments (the canonical set; producers may add their own names):

===============  ======================================================
data_wait        blocked on the input pipeline (iterator next())
h2d              host->device staging of batch arrays
compute          forward + backward + device sync of the loss
megastep         the ONE fused step program under ``MXTPU_MEGASTEP`` —
                 forward + backward + sentinel + update (+ in-graph
                 collectives) in a single dispatch; replaces
                 compute/optimizer/comm for the step and is exempt from
                 the bound detector exactly like ``compute`` (it IS the
                 compute)
optimizer        parameter update (incl. the fused sentinel reduction)
comm             gradient allreduce / kvstore push-pull after backward
comm_overlapped  collectives launched DURING backward by the overlap
                 scheduler (``MXTPU_COMM_OVERLAP``) — nested inside
                 ``compute``, charged exclusively here so overlapped
                 communication is neither double-counted against compute
                 nor silently vanished
checkpoint       checkpoint writes on the step path
===============  ======================================================

The **input-bound / comm-bound detector**: at each step end, any
non-compute segment whose share of wall-clock exceeds
``MXTPU_PROFILE_BOUND_FRAC`` (default 0.4) logs a one-line diagnosis
naming the bound segment, its share, and the first lever to reach for.
When a controller (the autotuner, :mod:`.autotune`) has already pulled
that lever, :meth:`StepBreakdown.note_action` upgrades the line from
diagnosis to "diagnosis → action taken". When the comm-health plane
(:mod:`.collective`, ``MXTPU_COLL_HEALTH``) has attributed collective
entry-time skew to a straggler rank (:meth:`StepBreakdown
.note_comm_health`), a comm-bound diagnosis upgrades to the
**straggler-bound** variant: the time is not wire bandwidth but one
rank arriving late at every collective, and the lever is that rank's
input pipeline / host, not the comm knobs.
"""
from __future__ import annotations

import threading
import time
from collections import defaultdict, deque
from typing import Dict, List, Optional

from ..base import env
from ..log import get_logger
from . import memory as _memory
from .tracer import tracer as _tracer

__all__ = ["SEGMENTS", "StepBreakdown", "segment", "current_breakdown"]

_LOG = get_logger("mxnet_tpu.telemetry")

SEGMENTS = ("data_wait", "h2d", "compute", "megastep", "optimizer",
            "comm", "comm_overlapped", "checkpoint")

#: remedy hint per over-threshold segment (the one-line diagnosis tail)
_ADVICE = {
    "data_wait": "input-bound: add decode threads / PrefetchingIter "
                 "or stage with DeviceStagingIter",
    "h2d": "transfer-bound: overlap H2D with DeviceStagingIter(depth>1)",
    "comm": "comm-bound: enable MXTPU_COMM_OVERLAP / MXTPU_AUTOTUNE, "
            "raise MXTPU_GRAD_BUCKET_MB or enable gradient compression",
    "comm_overlapped": "comm-bound despite overlap: collectives outlast "
                       "backward — raise MXTPU_GRAD_BUCKET_MB or enable "
                       "gradient compression",
    "optimizer": "update-bound: raise MXTPU_OPTIMIZER_AGGREGATION",
    "checkpoint": "ckpt-bound: raise ckpt_every or use async_ckpt=True",
}

_tls = threading.local()


def current_breakdown() -> Optional["StepBreakdown"]:
    """The breakdown collecting on this thread, if any."""
    return getattr(_tls, "active", None)


class _Segment:
    """Context manager: tracer span + exclusive-time charge to the active
    breakdown. Nested segments subtract their time from the enclosing one
    (self-time accounting), so one wall-second is never charged twice."""
    __slots__ = ("_name", "_args", "_t0", "_child")

    def __init__(self, name: str, args: Optional[dict]):
        self._name = name
        self._args = args
        self._child = 0.0

    def __enter__(self):
        bd = getattr(_tls, "active", None)
        if bd is not None:
            bd._stack.append(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *a):
        t1 = time.perf_counter()
        dt = t1 - self._t0
        _tracer.record(self._name, self._name, self._t0, t1, self._args)
        bd = getattr(_tls, "active", None)
        if bd is not None and bd._stack and bd._stack[-1] is self:
            bd._stack.pop()
            bd._charge(self._name, max(dt - self._child, 0.0))
            if bd._stack:
                bd._stack[-1]._child += dt
        return False


class _NoopSegment:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NOOP = _NoopSegment()


def segment(name: str, args: Optional[dict] = None):
    """Bracket one step phase. No-op (no clock reads) unless the tracer is
    enabled or a StepBreakdown is collecting on this thread."""
    if not _tracer.enabled and getattr(_tls, "active", None) is None:
        return _NOOP
    return _Segment(name, args)


class StepBreakdown:
    """Collects per-step exclusive segment seconds and runs the
    input-bound / comm-bound detector.

    Usage (FitLoop does exactly this)::

        bd = StepBreakdown()
        bd.install()                    # this thread's segments charge here
        for batch in it:
            bd.begin_step(step)
            with segment("compute"):
                ...
            bd.end_step()               # detector + per-step record
        bd.uninstall()
        bd.summary()                    # aggregate shares
    """

    #: per-step records retained (aggregates cover the full run)
    RECENT_STEPS = 64
    #: diagnosis strings retained; past this only counters advance
    MAX_DIAGNOSES = 100
    #: per-segment warning cadence after the first few occurrences
    _LOG_EVERY = 100

    def __init__(self, bound_frac: Optional[float] = None,
                 emit_counters: bool = True):
        if bound_frac is None:
            bound_frac = float(env.get("MXTPU_PROFILE_BOUND_FRAC"))
        self.bound_frac = float(bound_frac)
        self._emit_counters = emit_counters
        # bounded recent window; full-run aggregates live in _totals so a
        # 1M-step fit() never accrues a million per-step dicts
        self.steps: deque = deque(maxlen=self.RECENT_STEPS)
        self._totals: Dict[str, float] = defaultdict(float)
        self._wall_total = 0.0
        self._n_steps = 0
        # per-step memory watermarks (parallel to `steps`, NOT folded into
        # the segment records — those are second counts that sum against
        # wall-clock; a byte count in there would break that contract)
        self.mem_steps: deque = deque(maxlen=self.RECENT_STEPS)
        self._mem_peak_run = 0
        self._cur: Dict[str, float] = defaultdict(float)
        self._step_t0: Optional[float] = None
        self._step_id: Optional[int] = None
        self._stack: List[_Segment] = []
        self.diagnoses: List[str] = []
        self._diag_counts: Dict[str, int] = defaultdict(int)
        # segment -> description of the remedy a controller already
        # applied (autotuner lock); upgrades the detector's line from
        # diagnosis to "diagnosis → action taken"
        self.actions: Dict[str, str] = {}
        # last comm-health comparison (telemetry.collective.health_check
        # feeds it): a known straggler turns a comm-bound diagnosis into
        # the straggler-bound variant
        self._comm_health: Optional[Dict[str, object]] = None
        self._last_marked_step = object()  # sentinel: != any step id

    # -- thread binding -------------------------------------------------
    def install(self) -> "StepBreakdown":
        _tls.active = self
        return self

    def uninstall(self) -> None:
        if getattr(_tls, "active", None) is self:
            _tls.active = None

    # -- per-step lifecycle ---------------------------------------------
    def note_action(self, segment_name: str, action: str) -> None:
        """Record that a controller acted on ``segment_name``'s lever
        (e.g. the autotuner locking a bigger gradient bucket). Subsequent
        detector lines for that segment read "… → action taken: …"."""
        self.actions[segment_name] = str(action)

    def note_comm_health(self, info) -> None:
        """Record the latest cross-rank comm-health comparison
        (``telemetry.collective.health_check`` calls this when handed a
        breakdown). A non-None straggler rank re-aims subsequent
        comm-bound diagnoses at that rank instead of the comm knobs."""
        self._comm_health = dict(info) if info else None

    def begin_step(self, step: Optional[int] = None) -> None:
        self._cur = defaultdict(float)
        self._stack = []
        self._step_id = step
        if _tracer.enabled and step != self._last_marked_step:
            # step delimiter in the trace: offline tools
            # (tools/trace_report.py) reconstruct per-step segment tables
            # from these markers without needing the live StepBreakdown.
            # Deduped by id: resume fast-forward replays begin_step with
            # the step frozen at the checkpoint — one marker, not one per
            # replayed batch (the replay's data_wait folds into that
            # step's row, which is the true cost of resuming there)
            self._last_marked_step = step
            _tracer.instant(f"step:{step}", "step")
        _memory.ledger().begin_window()
        self._step_t0 = time.perf_counter()

    def _charge(self, name: str, seconds: float) -> None:
        self._cur[name] += seconds

    def end_step(self) -> Dict[str, float]:
        """Close the step: record wall time, emit tracer counters, run the
        detector. Returns this step's {segment: seconds, 'wall': seconds}."""
        if self._step_t0 is None:
            return {}
        wall = time.perf_counter() - self._step_t0
        rec = dict(self._cur)
        rec["wall"] = wall
        self.steps.append(rec)
        self._n_steps += 1
        self._wall_total += wall
        for name, s in self._cur.items():
            self._totals[name] += s
        if self._emit_counters and _tracer.enabled and wall > 0:
            for name, s in rec.items():
                if name != "wall":
                    _tracer.counter_event(f"step_share:{name}", s / wall)
        # memory axis: the ledger window opened in begin_step closes here.
        # Kept OUT of the segment record (bytes vs seconds); the counter
        # events give Perfetto a per-category memory track aligned with
        # the step markers, and `device_memory_peak` is byte-identical to
        # the per-step record FitResult publishes (test-enforced).
        led = _memory.ledger()
        mem_peak, mem_delta = led.window_stats()
        if mem_peak > self._mem_peak_run:
            self._mem_peak_run = mem_peak
        self.mem_steps.append({"step": self._step_id,
                               "peak_bytes": int(mem_peak),
                               "delta_bytes": int(mem_delta),
                               "live_bytes": int(led.live_bytes())})
        if self._emit_counters and _tracer.enabled:
            _tracer.counter_event("device_memory", led.snapshot(),
                                  category="memory")
            _tracer.counter_event("device_memory_peak", mem_peak,
                                  category="memory")
        self._detect(rec, wall)
        self._step_t0 = None
        return rec

    def _detect(self, rec: Dict[str, float], wall: float) -> None:
        if wall <= 0 or self.bound_frac <= 0:
            return
        for name, s in sorted(rec.items(), key=lambda kv: -kv[1]):
            if name in ("wall", "compute", "megastep"):
                continue
            frac = s / wall
            if frac >= self.bound_frac:
                advice = _ADVICE.get(name, "non-compute bound")
                if name in ("comm", "comm_overlapped"):
                    advice = self._straggler_advice() or advice
                msg = (f"step {self._step_id}: {name} is {frac:.0%} of "
                       f"step time ({s * 1e3:.1f}ms of {wall * 1e3:.1f}ms) "
                       f"— {advice}")
                if name in self.actions:
                    msg += f" → action taken: {self.actions[name]}"
                if len(self.diagnoses) < self.MAX_DIAGNOSES:
                    self.diagnoses.append(msg)
                # a persistently bound run must not warn once per step:
                # first 3 occurrences per segment, then every 100th
                self._diag_counts[name] += 1
                n = self._diag_counts[name]
                if n <= 3 or n % self._LOG_EVERY == 0:
                    if n > 3:
                        msg += f" [{n} occurrences]"
                    _LOG.warning(msg)

    def _straggler_advice(self) -> Optional[str]:
        """The straggler-bound diagnosis tail, when the comm-health plane
        has attributed the comm time to one rank entering collectives
        late — the comm knobs cannot fix a straggler."""
        ch = self._comm_health
        if not ch:
            return None
        rank = ch.get("straggler_rank")
        skew = float(ch.get("max_skew_ms") or 0.0)
        if rank is None or skew <= 0:
            return None
        return (f"straggler-bound: rank {rank} enters collectives up to "
                f"{skew:.1f}ms late (mxtpu_coll_skew_ms) — check that "
                "rank's input pipeline / host load before touching comm "
                "knobs")

    # -- aggregate ------------------------------------------------------
    def memory_summary(self) -> Dict[str, object]:
        """Per-step memory watermarks (bounded recent window) + the run
        peak, from the ledger windows opened/closed around each step."""
        return {"peak_bytes": int(self._mem_peak_run),
                "per_step": [dict(r) for r in self.mem_steps]}

    def summary(self) -> Dict[str, object]:
        """Aggregate over ALL recorded steps (running totals — not just
        the bounded recent window): total seconds and wall-clock shares
        per segment, plus step count and mean step seconds."""
        wall = self._wall_total
        shares = {name: (s / wall if wall > 0 else 0.0)
                  for name, s in self._totals.items()}
        accounted = sum(self._totals.values())
        return {
            "steps": self._n_steps,
            "wall_s": round(wall, 6),
            "mean_step_s": round(wall / self._n_steps, 6)
            if self._n_steps else 0.0,
            "seconds": {k: round(v, 6)
                        for k, v in sorted(self._totals.items())},
            "shares": {k: round(v, 4) for k, v in sorted(shares.items())},
            "accounted_frac": round(accounted / wall, 4) if wall > 0
            else 0.0,
            # recent per-step records (bounded so a 100k-step run's
            # summary stays a summary)
            "per_step": [{k: round(v, 6) for k, v in rec.items()}
                         for rec in self.steps],
            "diagnoses": list(self.diagnoses),
            "actions": dict(self.actions),
            "memory": self.memory_summary(),
        }
