"""Unified telemetry subsystem: tracer, metrics registry, step breakdown.

The reference dedicates a whole subsystem to observability — ``src/profiler/``
with aggregate stats, chrome trace-event dumps and a process-profiler C API
(``MXSetProcessProfilerConfig`` / ``MXDumpProfile``), plus remote profiler
commands shipped over the kvstore command channel
(``KVStoreServerProfilerCommand``, include/mxnet/kvstore.h:49). This package
is the TPU-native generalization; the whole stack reports into it:

- :mod:`.tracer` — thread-safe structured span tracer with a bounded ring
  buffer, category filtering and the ``MXTPU_PROFILE`` env grammar. Near-zero
  overhead when off (one flag check per span).
- :mod:`.chrome_trace` — strict Chrome trace-event JSON exporter (loadable in
  Perfetto / chrome://tracing) plus the validator the test-suite enforces it
  with.
- :mod:`.registry` — shared metrics registry (counters / gauges /
  histograms). ``serving/metrics.py`` is built on these types; CachedOp cache
  traffic, kvstore retries, chaos injections, Trainer dispatch counts, XLA
  compile events and device-memory watermarks all land in the default
  registry.
- :mod:`.step_breakdown` — per-step time accounting (data_wait / h2d /
  compute / optimizer / comm / checkpoint) with the input-bound / comm-bound
  detector. ``fit.FitLoop`` drives it; ``bench.py`` ships the segment shares
  as the ``step_breakdown`` headline row.
- :mod:`.memory` — the memory axis: a live-byte ledger attributing device
  bytes by owner (params / grads / optimizer / masters / staging /
  buckets / serving caches; exact by construction on CPU), static
  per-program ``memory_analysis`` attribution, per-step watermarks in the
  step breakdown + a Perfetto counter track, and ranked OOM-forensics
  dumps (``RESOURCE_EXHAUSTED`` / ``MXTPU_MEM_BUDGET`` / ``mem_pressure``
  chaos).

- :mod:`.collective` — the cross-rank comm axis: a bounded collective
  ledger at every kvstore/ZeRO/byte-channel entry point, the
  desync/straggler health exchange (``MXTPU_COLL_HEALTH``,
  ``mxtpu_coll_skew_ms``/``mxtpu_coll_straggler_rank``), and the
  hung-collective flight recorder (``MXTPU_COLL_TIMEOUT_S``) that names
  the hung ``(kind, key, seq)`` and the absent rank on every surviving
  rank. ``tools/fleet_trace.py`` merges per-rank chrome traces onto one
  clock via the tracer's wall-clock anchor + offset handshake.

- :mod:`.numerics` — the numbers axis: in-graph per-parameter tensor
  statistics emitted by the grouped-update bucket programs themselves
  (``MXTPU_NUMERICS``; zero extra dispatches, stats ride the step's
  existing flag+loss transfer), non-finite provenance naming the exact
  parameter a sentinel-skipped step blew up in (ERROR log +
  ``numerics_<pid>_<n>.json`` forensics), and the dynamic loss-scale
  timeline (``FitResult.numerics["loss_scale_events"]``,
  ``mxtpu_loss_scale``). The legacy ``mxnet_tpu.monitor.Monitor`` is a
  facade over it (``Monitor.install_numerics``).

- :mod:`.efficiency` — the efficiency axis: the ONE shared
  ``cost_analysis``/``memory_analysis`` extraction helper behind
  ``spmd.program_stats`` / ``CachedOp.memory_analysis`` /
  ``grouped.program_memory``, a per-program FLOP/byte cost registry
  (recorded alongside the program-memory registry,
  ``mxtpu_program_{flops,bytes_accessed}``), and the live MFU/goodput
  rollup (``MXTPU_EFFICIENCY``, ``MXTPU_DEVICE_PEAK`` peak table):
  ``FitResult.efficiency``, ``mxtpu_mfu``/``mxtpu_goodput_samples``,
  Perfetto counters (category ``efficiency``), the ``mfu`` column of
  ``tools/trace_report.py``.

- :mod:`.run_report` — the persistent per-run verdict: a versioned
  ``run_<pid>_<ts>.json`` artifact written at fit end
  (``MXTPU_RUN_REPORT_DIR``, tmp+rename + shared ``fault.write_manifest``)
  capturing the config fingerprint, step-time distribution and every
  axis's summary; ``tools/run_compare.py`` diffs two of them into
  per-metric regression verdicts with CI exit codes.

``mxnet_tpu.profiler`` remains the MXNet-compatible facade over this
package, and the kvstore remote profiler command channel
(``KVStore.send_profiler_command``) is served by it, so the controller can
collect per-rank chrome traces without a shared filesystem.
"""
from __future__ import annotations

from .tracer import (Tracer, tracer, span, instant, counter_event, enabled,
                     configure, enable, disable)
from .chrome_trace import (chrome_trace_events, dump_chrome_trace,
                           validate_chrome_trace)
from .registry import (Counter, Gauge, Histogram, MetricsRegistry,
                       default_registry)
from .step_breakdown import (StepBreakdown, segment, current_breakdown,
                             SEGMENTS)
from . import memory
from .memory import (MemoryLedger, ledger as memory_ledger, dump_forensics)
from . import collective
from .collective import (CollectiveLedger,
                         ledger as collective_ledger)
from . import numerics
from .numerics import NumericsPlane, plane as numerics_plane
from . import efficiency
from .efficiency import (EfficiencyRollup, compiled_program_stats,
                         rollup as efficiency_rollup)
from . import run_report
from .run_report import write_run_report, load_run_report

__all__ = [
    "Tracer", "tracer", "span", "instant", "counter_event", "enabled",
    "configure", "enable", "disable",
    "chrome_trace_events", "dump_chrome_trace", "validate_chrome_trace",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "default_registry",
    "StepBreakdown", "segment", "current_breakdown", "SEGMENTS",
    "memory", "MemoryLedger", "memory_ledger", "dump_forensics",
    "collective", "CollectiveLedger", "collective_ledger",
    "numerics", "NumericsPlane", "numerics_plane",
    "efficiency", "EfficiencyRollup", "compiled_program_stats",
    "efficiency_rollup",
    "run_report", "write_run_report", "load_run_report",
]
