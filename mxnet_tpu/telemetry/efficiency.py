"""Efficiency & goodput: per-program FLOP/byte costs, live MFU/roofline.

The measurement plane answers WHERE time goes (tracer / step breakdown),
where BYTES live (memory ledger), whether the FLEET agrees (collective
ledger) and whether the NUMBERS are sane (numerics plane). This module is
the axis the north star is graded on — *is this run as fast as the
hardware allows*: it divides what the hardware DID (XLA cost-model FLOPs
and bytes of the programs actually dispatched each step) by what the
hardware COULD do (a device peak table) and publishes the quotient live.

Three layers:

**Shared program analysis** (:func:`compiled_program_stats`): the ONE
extraction of a jax ``Compiled`` object's ``cost_analysis()`` (flops,
bytes accessed) and ``memory_analysis()`` (argument/output/temp/alias
bytes). ``spmd.program_stats``, ``memory.compiled_memory_stats`` (and
through it ``CachedOp.memory_analysis`` / ``grouped.program_memory``)
all route here — one parser for the backend's two analysis surfaces
instead of three hand-rolled copies. Per-program costs are recorded
alongside the program-memory registry (``memory.record_program``) so
forensics dumps and the ``mxtpu_program_{flops,bytes_accessed}`` gauges
rank programs by compute as well as by workspace.

**Live MFU/goodput rollup** (``MXTPU_EFFICIENCY=on``): dispatch sites
that launch attributable compiled programs — warm :class:`CachedOp`
forward replays, their vjp backward programs, the grouped-optimizer
bucket programs and the fused finiteness reduction — drop a
:func:`note_dispatch` per launch (a list append; with the plane off the
whole hook is one cached env check, the tracer discipline).
``fit.FitLoop`` brackets each step with :func:`begin_step` /
:func:`end_step` the way ``StepBreakdown`` opens its ledger window; at
step end every noted program's cost is resolved — re-lowered ON DEMAND
under the owning trace write-lock exactly like ``memory_analysis``,
cached per signature, so the hot path never lowers — and the step's FLOP
and byte sums divide by the measured wall and the device peak table
(:func:`device_peak`, ``MXTPU_DEVICE_PEAK=flops=F,bw=B``) into MFU,
achieved FLOP/s and bytes/s, the roofline position (compute- vs
bandwidth-bound) and samples/s goodput (non-finite skipped steps produce
no useful samples). Surfaces: ``FitResult.efficiency``, ``mxtpu_mfu`` /
``mxtpu_goodput_samples`` gauges, Perfetto ``"C"`` counters (category
``efficiency``) and the ``mfu`` column of ``tools/trace_report.py``.

Coverage contract: only whole-graph programs are attributed. An
un-hybridized net's per-op dispatches (and the tiny numerics fallback
programs) are invisible to the plane — they are never noted, so they
appear in no counter (``unattributed_dispatches`` counts only NOTED
launches whose cost failed to resolve) and MFU is a silent LOWER bound
there — hybridize the net for full attribution. The plane is
numerically inert:
notes are host-side bookkeeping and resolution is a re-lower (a trace,
never an execute) — bitwise on-vs-off trajectory parity is test-pinned,
as are warm-step dispatch/launch counts.

**Honest peaks**: the peak table comes from ``MXTPU_DEVICE_PEAK``
(strict parse — a typo'd peak raises before step 0, never silently
grades against garbage). Without it, per-backend defaults apply; on CPU
(no meaningful peak exists) every result is marked ``estimate`` until
the operator supplies real numbers.
"""
from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..base import MXNetError, env

__all__ = ["compiled_program_stats", "COST_FIELDS", "MEMORY_FIELDS",
           "spec", "enabled", "device_peak", "note_dispatch",
           "begin_step", "end_step", "reset_run", "summary", "rollup",
           "cost_report"]

#: fields :func:`compiled_program_stats` extracts from ``cost_analysis``
COST_FIELDS = ("flops", "bytes_accessed")
#: fields it extracts from ``memory_analysis`` (the historical
#: ``memory.compiled_memory_stats`` layout, byte-identical)
MEMORY_FIELDS = ("argument_bytes", "output_bytes", "temp_bytes",
                 "alias_bytes", "generated_code_bytes")

#: per-step efficiency records retained (the FitResult window)
RECENT = 64


# ---------------------------------------------------------------------------
# Shared program analysis — the one cost/memory extraction site
# ---------------------------------------------------------------------------

def compiled_program_stats(compiled) -> Optional[Dict[str, Any]]:
    """Extract XLA's ``cost_analysis()`` + ``memory_analysis()`` from a
    jax ``Compiled`` object into one plain dict (:data:`COST_FIELDS` as
    floats, :data:`MEMORY_FIELDS` as ints). Either analysis may be
    absent on a backend — missing halves are simply omitted; None when
    the program reports neither."""
    out: Dict[str, Any] = {}
    if compiled is None:
        return None
    try:
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, list) else dict(ca or {})
    except Exception:
        ca = {}
    if ca:
        out["flops"] = float(ca.get("flops", 0.0))
        out["bytes_accessed"] = float(ca.get("bytes accessed", 0.0))
    try:
        mem = compiled.memory_analysis()
    except Exception:
        mem = None
    if mem is not None:
        def g(name):
            try:
                return int(getattr(mem, name, 0) or 0)
            except Exception:
                return 0

        memd = {"argument_bytes": g("argument_size_in_bytes"),
                "output_bytes": g("output_size_in_bytes"),
                "temp_bytes": g("temp_size_in_bytes"),
                "alias_bytes": g("alias_size_in_bytes"),
                "generated_code_bytes": g("generated_code_size_in_bytes")}
        if any(memd.values()) or hasattr(mem, "temp_size_in_bytes"):
            out.update(memd)
    return out or None


# ---------------------------------------------------------------------------
# MXTPU_EFFICIENCY grammar (strict, cached against the raw string)
# ---------------------------------------------------------------------------

def _parse(raw: Optional[str]) -> bool:
    s = (raw or "").strip()
    if not s:
        return False
    on = False
    for tok in s.split(","):
        tok = tok.strip()
        if not tok:
            continue
        low = tok.lower()
        if low in ("on", "1", "true", "all"):
            on = True
        elif low in ("off", "0", "false"):
            on = False
        else:
            raise MXNetError(
                f"MXTPU_EFFICIENCY: unknown token {tok!r} "
                "(known: on, off)")
    return on


_spec_lock = threading.Lock()
_spec_cached: Optional[Tuple[Optional[str], bool]] = None


def spec() -> bool:
    """True when the plane is armed. Cached against the raw env string —
    the off path is one environ lookup + a compare (the tracer
    discipline); a typo'd value raises on every call."""
    global _spec_cached
    raw = env.raw("MXTPU_EFFICIENCY")
    c = _spec_cached
    if c is not None and c[0] == raw:
        return c[1]
    parsed = _parse(raw)
    with _spec_lock:
        _spec_cached = (raw, parsed)
    return parsed


def enabled() -> bool:
    return spec()


# ---------------------------------------------------------------------------
# Device peak table (MXTPU_DEVICE_PEAK=flops=F,bw=B)
# ---------------------------------------------------------------------------

#: rough per-backend peaks used when the operator declares none.
#: tpu: the one v5e chip this repo's bench measured (73 TFLOP/s
#: demonstrated MXU peak, ~0.9 TB/s measured HBM stream — see
#: docs/ROOFLINE.json); cpu/gpu: placeholders, always marked estimate.
_DEFAULT_PEAKS = {
    "tpu": (73.0e12, 900.0e9),
    "gpu": (50.0e12, 1000.0e9),
    "cpu": (1.0e11, 5.0e10),
}


def _parse_peak(raw: Optional[str]) -> Optional[Tuple[float, float]]:
    s = (raw or "").strip()
    if not s:
        return None
    vals: Dict[str, float] = {}
    for tok in s.split(","):
        tok = tok.strip()
        if not tok:
            continue
        key, sep, val = tok.partition("=")
        key = key.strip().lower()
        if not sep or key not in ("flops", "bw"):
            raise MXNetError(
                f"MXTPU_DEVICE_PEAK: unknown token {tok!r} (grammar: "
                "flops=<FLOP/s>,bw=<bytes/s>, e.g. flops=73e12,bw=9e11)")
        try:
            vals[key] = float(val)
        except ValueError:
            raise MXNetError(
                f"MXTPU_DEVICE_PEAK: {key}={val.strip()!r} is not a "
                "number")
        if vals[key] <= 0:
            raise MXNetError(
                f"MXTPU_DEVICE_PEAK: {key} must be > 0, got {vals[key]}")
    missing = [k for k in ("flops", "bw") if k not in vals]
    if missing:
        raise MXNetError(
            f"MXTPU_DEVICE_PEAK: missing {missing} — both flops= and "
            "bw= are required (MFU against half a peak table grades "
            "against garbage)")
    return vals["flops"], vals["bw"]


_peak_lock = threading.Lock()
_peak_cached: Optional[Tuple[Optional[str],
                             Optional[Tuple[float, float]]]] = None


def device_peak() -> Dict[str, Any]:
    """The active peak table: ``{"flops", "bw", "source", "estimate"}``.
    ``MXTPU_DEVICE_PEAK`` wins (strict parse, ``estimate`` False);
    otherwise the backend default applies and results are marked
    ``estimate`` — a defaulted peak grades the trend, not the truth."""
    global _peak_cached
    raw = env.raw("MXTPU_DEVICE_PEAK")
    c = _peak_cached
    if c is not None and c[0] == raw:
        parsed = c[1]
    else:
        parsed = _parse_peak(raw)
        with _peak_lock:
            _peak_cached = (raw, parsed)
    if parsed is not None:
        return {"flops": parsed[0], "bw": parsed[1], "source": "env",
                "estimate": False}
    backend = "cpu"
    try:
        import jax
        backend = jax.default_backend()
    except Exception:
        pass
    flops, bw = _DEFAULT_PEAKS.get(backend, _DEFAULT_PEAKS["cpu"])
    return {"flops": flops, "bw": bw, "source": f"default:{backend}",
            "estimate": True}


# ---------------------------------------------------------------------------
# The rollup
# ---------------------------------------------------------------------------

def _gauges():
    from .registry import default_registry
    reg = default_registry()
    return (
        reg.gauge("mxtpu_mfu",
                  "Model FLOP utilization of the last efficiency-plane "
                  "step: attributed program FLOPs / wall / device peak "
                  "(MXTPU_EFFICIENCY, MXTPU_DEVICE_PEAK)."),
        reg.gauge("mxtpu_goodput_samples",
                  "Useful samples/s of the last efficiency-plane step "
                  "(non-finite skipped steps produce no useful "
                  "samples)."),
    )


def _install_program_gauges() -> None:
    try:
        from . import memory as _memory
        from .registry import default_registry
        reg = default_registry()
        reg.callback_gauge(
            "mxtpu_program_flops",
            lambda: _memory.program_total("flops"),
            "XLA cost-model FLOPs over recorded compiled programs "
            "(one execution each; the efficiency plane's cost registry).")
        reg.callback_gauge(
            "mxtpu_program_bytes_accessed",
            lambda: _memory.program_total("bytes_accessed"),
            "XLA cost-model bytes accessed over recorded compiled "
            "programs (one execution each).")
    except Exception:
        pass


class EfficiencyRollup:
    """Per-process rollup state: the current step's dispatch notes, the
    resolved per-program cost table, run totals and the bounded recent
    window. ``reset_run`` re-arms it per fit (the
    ``reset_pressure_state`` discipline)."""

    def __init__(self):
        self._lock = threading.Lock()
        # current step: token -> [count, kind, label, resolver]
        self._notes: Dict[Any, list] = {}
        self._step_t0: Optional[float] = None
        # run-lifetime per-program table: token -> dict
        self.programs: Dict[Any, Dict[str, Any]] = {}
        self.recent: deque = deque(maxlen=RECENT)
        self.steps = 0
        self.flops_total = 0.0
        self.bytes_total = 0.0
        self.wall_total = 0.0
        self.samples_total = 0
        self.useful_samples_total = 0
        self.skipped_steps = 0
        self.unresolved_dispatches = 0

    # -- run lifecycle --------------------------------------------------
    def reset(self) -> None:
        with self._lock:
            self._notes = {}
            self._step_t0 = None
            self.programs = {}
            self.recent.clear()
            self.steps = 0
            self.flops_total = 0.0
            self.bytes_total = 0.0
            self.wall_total = 0.0
            self.samples_total = 0
            self.useful_samples_total = 0
            self.skipped_steps = 0
            self.unresolved_dispatches = 0

    # -- per-step -------------------------------------------------------
    def note(self, token, kind: str, label: str,
             resolver: Callable[[], Optional[dict]]) -> None:
        with self._lock:
            if self._step_t0 is None:
                # no open step window (bare Trainer loop / serving
                # process with the plane armed): DROP the note — each
                # resolver closure pins a compiled-program cache entry,
                # so accumulating them with nothing ever closing the
                # window would defeat the LRU bound and grow without end
                return
            ent = self._notes.get(token)
            if ent is None:
                self._notes[token] = [1, kind, label, resolver]
            else:
                ent[0] += 1

    def begin_step(self) -> None:
        with self._lock:
            self._notes = {}
            self._step_t0 = time.perf_counter()

    def end_step(self, step: Optional[int] = None, samples: int = 0,
                 useful: bool = True,
                 tokens_per_sample: Optional[float] = None,
                 wall_s: Optional[float] = None) -> Optional[dict]:
        """Close the step window: resolve every noted program's cost
        (cached per signature — only a first-seen program pays the
        re-lower), divide by the wall and the peak table, publish the
        gauges/counters, and append the step record."""
        with self._lock:
            if self._step_t0 is None:
                return None
            notes = self._notes
            self._notes = {}
            t0 = self._step_t0
            self._step_t0 = None
        if wall_s is None:
            wall_s = time.perf_counter() - t0
        # resolution OUTSIDE the rollup lock: resolvers may take the
        # owning CachedOp's trace write-lock (lock-order discipline)
        flops = byts = 0.0
        dispatches = unresolved = 0
        resolved_rows = []
        for token, (count, kind, label, resolver) in notes.items():
            dispatches += count
            stats = None
            try:
                stats = resolver()
            except Exception:
                stats = None
            if not stats or "flops" not in stats:
                unresolved += count
                continue
            f = float(stats.get("flops", 0.0))
            b = float(stats.get("bytes_accessed", 0.0))
            flops += count * f
            byts += count * b
            resolved_rows.append((token, kind, label, count, f, b))
        peak = device_peak()
        mfu = (flops / wall_s / peak["flops"]) if wall_s > 0 else 0.0
        bw_util = (byts / wall_s / peak["bw"]) if wall_s > 0 else 0.0
        sps = (samples / wall_s) if (wall_s > 0 and useful) else 0.0
        rec = {
            "step": step,
            "wall_s": wall_s,
            "flops": flops,
            "bytes_accessed": byts,
            "mfu": mfu,
            "bw_util": bw_util,
            "achieved_flops_per_s": flops / wall_s if wall_s > 0 else 0.0,
            "achieved_bytes_per_s": byts / wall_s if wall_s > 0 else 0.0,
            "samples_per_s": sps,
            "useful": bool(useful),
            "dispatches": dispatches,
            "unattributed_dispatches": unresolved,
        }
        if tokens_per_sample is not None:
            rec["tokens_per_s"] = sps * float(tokens_per_sample)
        with self._lock:
            for _token, kind, label, count, f, b in resolved_rows:
                # run-lifetime table keyed by (identity, cost), NOT the
                # per-step note token: a token built on id(entry) could
                # alias a later entry after the first is evicted and
                # collected — two indistinguishable (label, cost) rows
                # merging is fine, two different programs merging is not
                pkey = (kind, label, f, b)
                prog = self.programs.get(pkey)
                if prog is None:
                    prog = self.programs[pkey] = {
                        "kind": kind, "label": label, "flops": f,
                        "bytes_accessed": b, "dispatches": 0}
                prog["dispatches"] += count
            self.recent.append(rec)
            self.steps += 1
            self.flops_total += flops
            self.bytes_total += byts
            self.wall_total += wall_s
            self.samples_total += samples
            if useful:
                self.useful_samples_total += samples
            else:
                self.skipped_steps += 1
            self.unresolved_dispatches += unresolved
        try:
            g_mfu, g_sps = _gauges()
            g_mfu.set(mfu)
            g_sps.set(sps)
        except Exception:
            pass
        try:
            from .tracer import tracer as _tr
            if _tr.enabled:
                _tr.counter_event("mfu", mfu, category="efficiency")
                _tr.counter_event("samples_per_s", sps,
                                  category="efficiency")
        except Exception:
            pass
        return rec

    # -- aggregate ------------------------------------------------------
    def summary(self, tokens_per_sample: Optional[float] = None
                ) -> Optional[dict]:
        peak = device_peak()
        with self._lock:
            if not self.steps:
                return None
            wall = self.wall_total
            sps = (self.useful_samples_total / wall) if wall > 0 else 0.0
            mfu = (self.flops_total / wall / peak["flops"]) \
                if wall > 0 else 0.0
            bw_util = (self.bytes_total / wall / peak["bw"]) \
                if wall > 0 else 0.0
            progs = sorted(
                (dict(p) for p in self.programs.values()),
                key=lambda p: -(p["flops"] * p["dispatches"]))
            out = {
                "enabled": True,
                "steps": self.steps,
                "wall_s": round(wall, 6),
                "flops_total": self.flops_total,
                "bytes_total": self.bytes_total,
                "flops_per_step": self.flops_total / self.steps,
                "bytes_per_step": self.bytes_total / self.steps,
                "achieved_flops_per_s": self.flops_total / wall
                if wall > 0 else 0.0,
                "achieved_bytes_per_s": self.bytes_total / wall
                if wall > 0 else 0.0,
                "mfu": mfu,
                "bw_util": bw_util,
                # which ceiling is the run actually pressed against —
                # the standard roofline verdict (whichever utilization
                # is higher is the binding constraint). With NOTHING
                # attributed there is no verdict to give: a definitive
                # "compute_bound" over zero measured FLOPs would be a
                # lie (the un-hybridized-net case)
                "roofline": ("compute_bound" if mfu >= bw_util
                             else "bandwidth_bound")
                if (self.flops_total > 0 or self.bytes_total > 0)
                else "unattributed",
                "samples_per_s": sps,
                "samples_total": self.samples_total,
                "useful_samples_total": self.useful_samples_total,
                "skipped_steps": self.skipped_steps,
                "unattributed_dispatches": self.unresolved_dispatches,
                "peak": dict(peak),
                "estimate": bool(peak["estimate"]),
                "per_program": progs[:20],
                "recent": [dict(r) for r in self.recent],
            }
        if tokens_per_sample is not None:
            out["tokens_per_s"] = sps * float(tokens_per_sample)
            out["tokens_per_sample"] = float(tokens_per_sample)
        return out


_ROLLUP = EfficiencyRollup()
_gauges_installed = [False]


def rollup() -> EfficiencyRollup:
    return _ROLLUP


def reset_run() -> None:
    """Re-arm the rollup for a fresh run (``fit.FitLoop`` calls this at
    fit start). Also the strict-parse checkpoint: a typo'd
    ``MXTPU_EFFICIENCY`` or ``MXTPU_DEVICE_PEAK`` raises HERE, before
    any step runs."""
    on = spec()
    if on:
        device_peak()  # strict-parse the peak table before step 0
        if not _gauges_installed[0]:
            _gauges_installed[0] = True
            _install_program_gauges()
    _ROLLUP.reset()


def note_dispatch(token, kind: str, label: str,
                  resolver: Callable[[], Optional[dict]]) -> None:
    """Record one launch of an attributable compiled program into the
    current step window. ``token`` dedupes repeat launches of the same
    program within a step; ``resolver`` returns the program's cost dict
    (it re-lowers on first call and must cache on its own side — the
    rollup calls it once per step at most). Callers gate on
    :func:`enabled` so the off path never builds the closure."""
    if not spec():
        return
    _ROLLUP.note(token, kind, label, resolver)


def begin_step() -> None:
    if not spec():
        return
    _ROLLUP.begin_step()


def end_step(step: Optional[int] = None, samples: int = 0,
             useful: bool = True,
             tokens_per_sample: Optional[float] = None,
             wall_s: Optional[float] = None) -> Optional[dict]:
    if not spec():
        return None
    return _ROLLUP.end_step(step=step, samples=samples, useful=useful,
                            tokens_per_sample=tokens_per_sample,
                            wall_s=wall_s)


def summary(tokens_per_sample: Optional[float] = None) -> Optional[dict]:
    """The ``FitResult.efficiency`` payload; None when the plane is off
    or no step closed."""
    if not spec():
        return None
    return _ROLLUP.summary(tokens_per_sample=tokens_per_sample)


def cost_report(limit: Optional[int] = None) -> List[Dict[str, Any]]:
    """Recorded programs ranked by cost-model FLOPs (the compute-side
    twin of ``memory.program_report``)."""
    from . import memory as _memory
    rows = [r for r in _memory.program_report(None)
            if float(r.get("flops", 0.0) or 0.0) > 0]
    rows.sort(key=lambda r: -float(r.get("flops", 0.0)))
    return rows[:limit] if limit else rows
