"""Telemetry-driven knob autotuner: close the observability loop.

PR 5 built the measurement plane (tracer, per-step exclusive-time
breakdown, bound detector); this module is the first thing that *acts* on
it. The perf knobs the stack grew — ``MXTPU_GRAD_BUCKET_MB`` (PR 4 bucketed
allreduce), ``MXTPU_OPTIMIZER_AGGREGATION`` (PR 4 multi-tensor updates),
``DeviceStagingIter`` prefetch depth (PR 1) and ``MXTPU_COMM_OVERLAP``
(this PR's comm/backward overlap) — are all *numerically neutral*: any
setting produces bit-identical updates, only the step time changes. That
makes them safe to probe on live training steps: the :class:`AutoTuner`
spends a few instrumented steps per candidate at train start, scores each
candidate with the step-breakdown exclusive-time data the steps already
produce, locks the best configuration for the rest of the run, and records
every decision where an operator can see it:

- each probe step emits a dedicated tracer span (category ``autotune``)
  and the lock decision an ``autotune`` instant event, so the choice is
  visible in the chrome trace (and ``tools/trace_report.py``);
- chosen knob values and per-candidate probe scores land in the shared
  metrics registry (``mxtpu_autotune_*``);
- the full protocol — candidates, scores, the locked config, the margin
  rule — is returned as ``FitResult.tuning_report``;
- the bound detector's one-line diagnosis upgrades from "comm-bound: do X"
  to "comm-bound: do X → action taken: ..." via
  :meth:`~.step_breakdown.StepBreakdown.note_action`.

Grammar (``MXTPU_AUTOTUNE``, strict — typos raise, like ``MXTPU_PROFILE``)::

    on[,probe=N][,warmup=N][,knobs=a|b][,bucket_mb=v|v][,agg=v|v]
      [,prefetch=v|v][,overlap=0|1]

``probe`` measured steps per candidate (default 2) after ``warmup``
unmeasured steps (default 1). ``knobs`` restricts which knobs are probed
(default: all applicable); the per-knob lists override the built-in
candidate values. ``off`` (the default) constructs no tuner and reproduces
untuned behavior exactly.

Candidates are one-factor-at-a-time: a baseline (the operator's current
settings) plus, per knob, each alternative value with every other knob at
baseline. The locked config combines, per knob, the best-scoring variant
of that knob — and only if it beat baseline by more than ``MIN_GAIN``
(3%, a noise fence): measured-equal knobs stay at the operator's values.
One deliberate exception: ``overlap`` is wall-neutral by construction
(the same bucket collectives, launched during backward instead of after
it), so it is instead adopted when the measured *exposed* ``comm`` share
drops by more than ``MIN_GAIN`` — hiding communication under compute is
what the knob is for, the breakdown measures exactly that, and a few
probe steps cannot resolve wall-clock at the fence's resolution anyway
(the reference engine overlaps unconditionally for the same reason).
The same rule drives the knob under ``MXTPU_ZERO=1``: overlap there
moves the plane's reduce-scatter launches into backward and the weight
allgathers in between the shard updates — identical collectives, so the
exposed-``comm``-share signal is again the only honest one, and
``bucket_mb`` keeps its ordinary wall-clock rule (it sizes the
reduce-scatter/allgather buckets exactly as it sizes allreduce ones).
Probing mutates process env vars (the knobs' existing read points pick
the values up per step); the FitLoop restores the operator's environment
when fit() returns — the *decision* persists in the report, the env
mutation does not.
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

from ..base import MXNetError, env
from ..log import get_logger
from .tracer import tracer as _tracer

__all__ = ["AutoTuner", "requested", "parse_spec"]

_LOG = get_logger("mxnet_tpu.autotune")

#: a candidate must beat baseline by this fraction of step time to be
#: locked in — below it the measurement is noise, keep the operator's value
MIN_GAIN = 0.03

_KNOBS = ("bucket_mb", "agg", "prefetch", "overlap")

#: env var behind each env-backed knob
_KNOB_ENV = {"bucket_mb": "MXTPU_GRAD_BUCKET_MB",
             "agg": "MXTPU_OPTIMIZER_AGGREGATION",
             "overlap": "MXTPU_COMM_OVERLAP"}

#: step-breakdown segments each knob's lever acts on (note_action targets)
_KNOB_SEGMENTS = {"bucket_mb": ("comm", "comm_overlapped"),
                  "agg": ("optimizer",),
                  "prefetch": ("data_wait", "h2d"),
                  "overlap": ("comm", "comm_overlapped")}


def _spec() -> str:
    return str(env.get("MXTPU_AUTOTUNE") or "").strip()


def requested() -> bool:
    """True when ``MXTPU_AUTOTUNE`` asks for tuning. Malformed specs raise
    here — at fit() start — not after an hour of silently-untuned steps."""
    raw = _spec()
    if raw.lower() in ("", "off", "0", "false"):
        return False
    parsed = parse_spec(raw)  # typos raise now
    if not parsed["on"]:
        # tokens given but tuning never enabled ('probe=4' without 'on',
        # unless an explicit off token opted out): ambiguous intent —
        # raise rather than silently train untuned
        if any(t.strip().lower() in ("off", "0", "false")
               for t in raw.split(",")):
            return False
        raise MXNetError(
            f"MXTPU_AUTOTUNE={raw!r} configures tuning but never enables "
            "it — start the spec with 'on' (or set 'off' explicitly)")
    return True


def parse_spec(spec: str) -> Dict[str, object]:
    """Parse one MXTPU_AUTOTUNE spec string (module docstring grammar).
    Returns {'on', 'probe', 'warmup', 'knobs', 'values': {knob: [v,...]}}.
    Unknown tokens/keys/values raise MXNetError."""
    out: Dict[str, object] = {"on": False, "probe": 2, "warmup": 1,
                              "knobs": None, "values": {}}
    for tok in (spec or "").split(","):
        tok = tok.strip()
        if not tok:
            continue
        low = tok.lower()
        if low in ("on", "1", "true"):
            out["on"] = True
            continue
        if low in ("off", "0", "false"):
            out["on"] = False
            continue
        if "=" not in tok:
            raise MXNetError(
                f"MXTPU_AUTOTUNE: unknown token {tok!r} (known: on, off, "
                "probe=N, warmup=N, knobs=a|b, bucket_mb=v|v, agg=v|v, "
                "prefetch=v|v, overlap=0|1)")
        key, _, val = tok.partition("=")
        key = key.strip().lower()
        val = val.strip()
        if key in ("probe", "warmup"):
            try:
                n = int(val)
            except ValueError:
                raise MXNetError(
                    f"MXTPU_AUTOTUNE: {key}={val!r} is not an int")
            if key == "probe" and n < 1:
                raise MXNetError("MXTPU_AUTOTUNE: probe must be >= 1")
            if n < 0:
                raise MXNetError(f"MXTPU_AUTOTUNE: {key} must be >= 0")
            out[key] = n
        elif key == "knobs":
            knobs = [k.strip() for k in val.split("|") if k.strip()]
            bad = [k for k in knobs if k not in _KNOBS]
            if bad or not knobs:
                raise MXNetError(
                    f"MXTPU_AUTOTUNE: knobs={val!r} — unknown knob(s) "
                    f"{bad or val!r} (known: {', '.join(_KNOBS)})")
            out["knobs"] = knobs
        elif key in _KNOBS:
            vals: List[float] = []
            for v in val.split("|"):
                v = v.strip()
                try:
                    vals.append(float(v) if key == "bucket_mb" else int(v))
                except ValueError:
                    raise MXNetError(
                        f"MXTPU_AUTOTUNE: {key}={val!r} — {v!r} is not "
                        "numeric")
            if key == "overlap" and any(v not in (0, 1) for v in vals):
                raise MXNetError(
                    f"MXTPU_AUTOTUNE: overlap={val!r} (only 0|1)")
            if any(v < 0 for v in vals) or \
                    (key == "prefetch" and any(v < 1 for v in vals)):
                raise MXNetError(
                    f"MXTPU_AUTOTUNE: {key}={val!r} out of range")
            out["values"][key] = vals
        else:
            raise MXNetError(
                f"MXTPU_AUTOTUNE: unknown key {key!r} (known: probe, "
                f"warmup, knobs, {', '.join(_KNOBS)})")
    return out


class _Candidate:
    __slots__ = ("label", "knob", "knobs", "walls", "segs")

    def __init__(self, label: str, knob: Optional[str], knobs: Dict):
        self.label = label
        self.knob = knob          # the ONE knob varied (None = baseline)
        self.knobs = knobs        # full knob->value config for this probe
        self.walls: List[float] = []
        self.segs: Dict[str, float] = {}

    def score(self) -> float:
        """Best (minimum) measured step wall seconds, inf until measured.
        min, not mean: with only a few probe steps a single scheduler
        hiccup in the mean would swamp the 3% decision fence, while the
        fastest observed step is the config's real floor (timeit's
        rationale)."""
        return min(self.walls) if self.walls else float("inf")

    def seg_share(self, *names: str) -> float:
        w = sum(self.walls)
        c = sum(self.segs.get(n, 0.0) for n in names)
        return (c / w) if w > 0 else 0.0

    def comm_share(self) -> float:
        """Total communication share: exposed + overlapped."""
        return self.seg_share("comm", "comm_overlapped")


class AutoTuner:
    """Probe-then-lock controller driven by the FitLoop.

    The loop calls :meth:`on_step_begin` before each trained step (the
    tuner applies the next candidate's knobs) and :meth:`on_step_end`
    with the step's breakdown record (the tuner scores it). After
    ``candidates * (warmup + probe)`` steps it locks the combined best
    config and goes quiescent; :meth:`report` is the full protocol dump.
    """

    def __init__(self, spec: Optional[str] = None, trainer=None,
                 data_iter=None, registry=None):
        parsed = parse_spec(_spec() if spec is None else spec)
        self.enabled = bool(parsed["on"])
        self.probe = int(parsed["probe"])
        self.warmup = int(parsed["warmup"])
        self._knob_filter = parsed["knobs"]
        self._value_overrides = parsed["values"]
        self._trainer = trainer
        self._data_iter = data_iter
        self._registry = registry
        self.locked = False
        self.locked_at_step: Optional[int] = None
        self.chosen: Dict[str, object] = {}
        self._cands: Optional[List[_Candidate]] = None
        self._idx = 0              # current candidate index
        self._steps_in_cand = 0    # steps taken under current candidate
        self._t0: Optional[float] = None
        self._saved_env: Dict[str, Optional[str]] = {}
        self._saved_depth: Optional[int] = None
        self._baseline: Dict[str, object] = {}
        self._probe_steps_total = 0

    # -- knob plumbing ---------------------------------------------------
    def _current(self, knob: str):
        if knob == "bucket_mb":
            try:
                return float(env.get("MXTPU_GRAD_BUCKET_MB"))
            except (TypeError, ValueError):
                return 0.0
        if knob == "agg":
            from ..optimizer.grouped import aggregation_size
            return aggregation_size()
        if knob == "overlap":
            # THE Trainer parse (strict: typos raise), not a copy of it —
            # a lenient or drifted read here would let the tuner overwrite
            # a value the trainer rejects, masking the error the strict
            # grammar exists to surface. Imported lazily: gluon pulls in
            # telemetry at package import, not the other way around.
            from ..gluon.trainer import _overlap_requested
            return 1 if _overlap_requested() else 0
        if knob == "prefetch":
            return int(getattr(self._data_iter, "depth", 1))
        raise MXNetError(f"unknown knob {knob!r}")

    def _apply(self, knob: str, value) -> None:
        if knob == "prefetch":
            set_depth = getattr(self._data_iter, "set_depth", None)
            if set_depth is not None:
                if self._saved_depth is None:
                    # like the env vars: the operator's depth is restored
                    # when fit() returns, even from a run that ended
                    # mid-probe — only the decision persists
                    self._saved_depth = int(
                        getattr(self._data_iter, "depth", 1))
                set_depth(int(value))
            return
        name = _KNOB_ENV[knob]
        if name not in self._saved_env:
            self._saved_env[name] = env.raw(name)
        if knob == "overlap":
            os.environ[name] = "on" if int(value) else "off"
        elif knob == "bucket_mb":
            os.environ[name] = repr(float(value))
        else:
            os.environ[name] = str(int(value))

    def restore_env(self) -> None:
        """Reinstate the operator's environment — env vars AND the
        staging iterator's depth (FitLoop calls this when fit() returns:
        the decision lives on in the report, the mutations must not leak
        past the run)."""
        for name, old in self._saved_env.items():
            if old is None:
                os.environ.pop(name, None)
            else:
                os.environ[name] = old
        self._saved_env.clear()
        if self._saved_depth is not None:
            set_depth = getattr(self._data_iter, "set_depth", None)
            if set_depth is not None:
                set_depth(self._saved_depth)
            self._saved_depth = None

    # -- candidate plan --------------------------------------------------
    @staticmethod
    def _store_compresses(t) -> bool:
        """True when the trainer's kvstore applies gradient compression.
        Checked WITHOUT forcing lazy store creation (plan build runs
        before the first forward, when deferred-init params have no
        data yet)."""
        if getattr(t, "_compression_params", None):
            return True
        kv = getattr(t, "_kvstore", None)
        if kv is None:
            arg = getattr(t, "_kvstore_arg", None)
            kv = arg if not isinstance(arg, str) else None
        return bool(getattr(kv, "_compressor", None) or
                    getattr(kv, "_compression_params", None))

    def _applicable_knobs(self) -> List[str]:
        knobs = list(self._knob_filter or _KNOBS)
        # prefetch needs a depth-adjustable staging iterator
        if "prefetch" in knobs and \
                getattr(self._data_iter, "set_depth", None) is None:
            knobs.remove("prefetch")
        t = self._trainer
        if t is not None:
            # comm knobs need a kvstore to communicate through
            if not getattr(t, "_kvstore_arg", None):
                for k in ("bucket_mb", "overlap"):
                    if k in knobs:
                        knobs.remove(k)
            elif "bucket_mb" in knobs and self._store_compresses(t):
                # a compressor's per-key error-feedback residual makes
                # the _gbkt key layout part of the numerics: re-bucketing
                # mid-run would break the bitwise-parity premise probing
                # rests on. (overlap stays probe-safe: it reuses the
                # barrier path's exact layout, and per-key compression is
                # launch-order independent.)
                knobs.remove("bucket_mb")
        return knobs

    def _default_values(self, knob: str, cur) -> List:
        if knob == "bucket_mb":
            return [v for v in (4.0, 100.0) if v != cur]
        if knob == "agg":
            return [v for v in (16,) if v != cur]
        if knob == "prefetch":
            return [v for v in (3,) if v != cur]
        if knob == "overlap":
            return [1] if not cur else [0]
        return []

    def _build_plan(self) -> List[_Candidate]:
        self._baseline = {k: self._current(k)
                          for k in self._applicable_knobs()}
        cands = [_Candidate("baseline", None, dict(self._baseline))]
        for knob, cur in self._baseline.items():
            values = self._value_overrides.get(knob)
            values = [v for v in values if v != cur] if values is not None \
                else self._default_values(knob, cur)
            for v in values:
                knobs = dict(self._baseline)
                knobs[knob] = v
                cands.append(_Candidate(f"{knob}={v:g}" if
                                        isinstance(v, float)
                                        else f"{knob}={v}", knob, knobs))
        return cands

    # -- the FitLoop protocol --------------------------------------------
    def on_step_begin(self, step: int) -> None:
        if self.locked or not self.enabled:
            return
        if self._cands is None:
            self._cands = self._build_plan()
            if len(self._cands) <= 1:
                # nothing to vary (no kvstore, no staging iter, overrides
                # all equal to current): lock immediately on baseline
                self._lock(step)
                return
            _LOG.warning(
                "autotune: probing %d candidates x (%d warmup + %d "
                "measured) steps — knobs %s",
                len(self._cands), self.warmup, self.probe,
                sorted(self._baseline))
            self._apply_candidate(self._cands[0])
        elif self._steps_in_cand == 0:
            self._apply_candidate(self._cands[self._idx])
        self._t0 = time.perf_counter()

    def _apply_candidate(self, cand: _Candidate) -> None:
        for knob, value in cand.knobs.items():
            self._apply(knob, value)

    def on_step_end(self, step: int, rec: Dict[str, float],
                    breakdown=None) -> None:
        if self.locked or not self.enabled or self._cands is None \
                or self._t0 is None:
            return
        t1 = time.perf_counter()
        cand = self._cands[self._idx]
        self._steps_in_cand += 1
        self._probe_steps_total += 1
        measured = self._steps_in_cand > self.warmup
        if measured:
            wall = rec.get("wall") or (t1 - self._t0)
            cand.walls.append(wall)
            for name, s in rec.items():
                if name != "wall":
                    cand.segs[name] = cand.segs.get(name, 0.0) + s
        _tracer.record(f"probe:{cand.label}", "autotune", self._t0, t1,
                       {"step": step, "candidate": cand.label,
                        "measured": measured})
        self._t0 = None
        if self._steps_in_cand >= self.warmup + self.probe:
            self._idx += 1
            self._steps_in_cand = 0
            if self._idx >= len(self._cands):
                self._lock(step, breakdown)

    # -- decision --------------------------------------------------------
    def _lock(self, step: int, breakdown=None) -> None:
        self.locked = True
        self.locked_at_step = step
        cands = self._cands or []
        base = cands[0] if cands else None
        base_score = base.score() if base else float("inf")
        self.chosen = dict(self._baseline)
        changed: Dict[str, Dict[str, object]] = {}
        for knob in self._baseline:
            variants = [c for c in cands if c.knob == knob and c.walls]
            if not variants:
                continue
            best = min(variants, key=_Candidate.score)
            if knob == "overlap":
                # overlap is wall-neutral by construction (the SAME
                # bucket collectives, launched during backward instead of
                # after it), so wall time can neither justify NOR veto
                # it: a few probed steps cannot resolve wall deltas at
                # the percent level on a loaded host — a generic wall
                # verdict here would flip the knob on scheduler noise.
                # Hiding exposed comm under compute is what the knob is
                # FOR and the breakdown measures it directly — decide on
                # that signal alone, and only ever toward enabling (the
                # reference engine overlaps unconditionally; re-exposing
                # an operator's hidden comm is never a win). The wall
                # ratio is still recorded for the operator in gain_frac.
                if base is not None and best.knobs[knob] and \
                        not self._baseline[knob] and \
                        base.seg_share("comm") - best.seg_share("comm") \
                        > MIN_GAIN:
                    self.chosen[knob] = best.knobs[knob]
                    changed[knob] = {
                        "from": self._baseline[knob],
                        "to": best.knobs[knob],
                        "gain_frac": round(1.0 - best.score() / base_score,
                                           4) if base_score > 0 else None,
                        "comm_share_from": round(base.seg_share("comm"), 4),
                        "comm_share_to": round(best.seg_share("comm"), 4),
                    }
            elif base_score > 0 and \
                    best.score() < base_score * (1.0 - MIN_GAIN):
                self.chosen[knob] = best.knobs[knob]
                changed[knob] = {
                    "from": self._baseline[knob],
                    "to": best.knobs[knob],
                    "gain_frac": round(1.0 - best.score() / base_score, 4),
                }
        # apply the combined winner for the rest of the run
        for knob, value in self.chosen.items():
            self._apply(knob, value)
        summary = (", ".join(f"{k}: {c['from']}->{c['to']}"
                             for k, c in sorted(changed.items()))
                   or "kept operator settings")
        _LOG.warning("autotune: locked at step %d — %s", step, summary)
        _tracer.instant(
            "autotune:lock " + json.dumps(
                {"step": step, "chosen": self.chosen, "changed": changed},
                sort_keys=True, default=str), "autotune")
        # the bound detector's diagnosis upgrades to "→ action taken" on
        # every segment a changed knob is the lever for
        if breakdown is not None and changed:
            for knob in changed:
                for seg in _KNOB_SEGMENTS.get(knob, ()):
                    breakdown.note_action(
                        seg, f"autotune locked {summary} (step {step})")
        self._export_metrics()

    def _export_metrics(self) -> None:
        try:
            if self._registry is None:
                from .registry import default_registry
                self._registry = default_registry()
            reg = self._registry
            reg.counter(
                "mxtpu_autotune_probe_steps_total",
                "Training steps spent probing autotune candidates."
            ).inc(self._probe_steps_total)
            for knob, value in self.chosen.items():
                reg.gauge(
                    f"mxtpu_autotune_chosen_{knob}",
                    f"Autotuner-locked value of the {knob} knob."
                ).set(float(value))
            for cand in (self._cands or []):
                if not cand.walls:
                    continue
                name = cand.label.replace("=", "_").replace(".", "_") \
                    .replace("-", "m")
                reg.gauge(
                    f"mxtpu_autotune_score_ms_{name}",
                    "Best probed step time (ms) for this autotune "
                    "candidate.").set(round(cand.score() * 1e3, 3))
        except Exception:
            # observability must not take down training
            _LOG.exception("autotune: metrics export failed")

    # -- the protocol dump ----------------------------------------------
    def report(self) -> Dict[str, object]:
        """The full tuning protocol (lands in FitResult.tuning_report)."""
        cands = self._cands or []
        base = cands[0] if cands else None
        base_score = base.score() if base and base.walls else None
        try:
            from ..parallel.zero import zero_requested
            zero_on = zero_requested()
        except Exception:
            zero_on = False
        out: Dict[str, object] = {
            "status": "locked" if self.locked else "probing",
            "probe_steps": self.probe,
            "warmup_steps": self.warmup,
            "min_gain_frac": MIN_GAIN,
            # which comm plane the knobs steered: overlap/bucket_mb tune
            # the ZeRO reduce-scatter+allgather round when the plane is on
            "zero": zero_on,
            "locked_at_step": self.locked_at_step,
            "baseline": dict(self._baseline),
            "chosen": dict(self.chosen),
            "candidates": [
                {"label": c.label,
                 "knobs": dict(c.knobs),
                 "measured_steps": len(c.walls),
                 "best_step_s": round(c.score(), 6) if c.walls else None,
                 "comm_share": round(c.comm_share(), 4) if c.walls
                 else None,
                 "comm_exposed_share": round(c.seg_share("comm"), 4)
                 if c.walls else None,
                 "vs_baseline": round(c.score() / base_score, 4)
                 if (c.walls and base_score) else None}
                for c in cands],
        }
        return out
