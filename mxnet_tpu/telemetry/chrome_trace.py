"""Chrome trace-event JSON exporter + strict validator.

Reference: src/profiler/profiler.cc dumps the trace-event ``JSON Array
Format`` consumed by chrome://tracing; this exporter emits the richer
``JSON Object Format`` ({"traceEvents": [...]}) that Perfetto also loads,
with process/thread metadata events so ranks and thread names label the
tracks.

The validator is the contract the exporter (and every producer routing
through it — serving spans, op dispatch, step breakdown) is held to by the
test-suite: required keys per phase, numeric ``ts``/``dur``, and proper
per-thread span nesting (a thread's "X" spans must form a forest — strictly
nested or disjoint, never partially overlapping).
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Union

from .tracer import Tracer, tracer as _default_tracer

__all__ = ["chrome_trace_events", "dump_chrome_trace",
           "validate_chrome_trace"]

#: phases the exporter may emit / the validator accepts
_PHASES = {"X", "i", "I", "C", "M", "B", "E"}

#: keys every event must carry, plus per-phase requirements
_REQUIRED = {"name", "ph", "ts", "pid", "tid"}


def chrome_trace_events(tr: Optional[Tracer] = None) -> List[Dict[str, Any]]:
    """Serialize a tracer's ring buffer into trace-event dicts, prefixed
    with process/thread metadata events."""
    tr = tr or _default_tracer
    events: List[Dict[str, Any]] = []
    rank = tr.rank
    events.append({"name": "process_name", "ph": "M", "ts": 0.0,
                   "pid": rank, "tid": 0,
                   "args": {"name": f"rank{rank}"}})
    # wall-clock anchor: trace ts 0 == epoch second epoch_t0_s on this
    # rank's clock, which runs clock_offset_ms ahead of rank 0's
    # (telemetry.collective.sync_clocks). tools/fleet_trace.py uses this
    # pair to merge N per-rank traces onto one aligned clock.
    events.append({"name": "clock_sync", "ph": "M", "ts": 0.0,
                   "pid": rank, "tid": 0,
                   "args": {"epoch_t0_s": float(tr.epoch_anchor),
                            "clock_offset_ms":
                            float(getattr(tr, "clock_offset_ms", 0.0))}})
    for tid, tname in sorted(tr.thread_names().items()):
        events.append({"name": "thread_name", "ph": "M", "ts": 0.0,
                       "pid": rank, "tid": tid,
                       "args": {"name": tname}})
    for ev in tr.events():
        out = {"name": ev["name"], "cat": ev.get("cat", "default"),
               "ph": ev.get("ph", "X"), "ts": float(ev["ts"]),
               "pid": int(ev["pid"]), "tid": int(ev["tid"])}
        if out["ph"] == "X":
            out["dur"] = float(ev.get("dur", 0.0))
        if ev.get("ph") == "i":
            out["s"] = ev.get("s", "t")
        if "args" in ev:
            out["args"] = ev["args"]
        events.append(out)
    return events


def dump_chrome_trace(path: Optional[str] = None,
                      tracer: Optional[Tracer] = None) -> Dict[str, Any]:
    """Export the tracer as a chrome-trace object; write JSON to ``path``
    when given. Returns the trace dict (validator-clean by construction)."""
    payload = {"traceEvents": chrome_trace_events(tracer),
               "displayTimeUnit": "ms"}
    if path is not None:
        with open(path, "w") as f:
            json.dump(payload, f)
    return payload


def _fail(msg: str) -> None:
    raise ValueError(f"chrome-trace validation failed: {msg}")


def validate_chrome_trace(trace: Union[str, Dict[str, Any]],
                          require_complete: bool = True) -> List[dict]:
    """Strictly validate a chrome-trace payload; returns the event list.

    Checks (raises ``ValueError`` on the first violation):

    - top level is an object with a ``traceEvents`` list (a JSON string is
      parsed first);
    - every event is an object carrying ``name``/``ph``/``ts``/``pid``/
      ``tid`` with the right types, ``ph`` drawn from the known phase set;
    - ``X`` events carry a numeric non-negative ``dur``;
    - ``C`` events carry an ``args`` object (the sampled values);
    - per (pid, tid), ``X`` spans form a forest: sorted by start time they
      are strictly nested or disjoint — partial overlap on one thread means
      broken instrumentation (a span outlived its parent);
    - ``require_complete``: at least one non-metadata event exists.
    """
    if isinstance(trace, (str, bytes)):
        try:
            trace = json.loads(trace)
        except json.JSONDecodeError as e:
            _fail(f"not valid JSON ({e})")
    if not isinstance(trace, dict):
        _fail(f"top level must be an object, got {type(trace).__name__}")
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        _fail("missing 'traceEvents' list")
    per_thread: Dict[tuple, List[tuple]] = {}
    substantive = 0
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            _fail(f"event {i} is not an object")
        missing = _REQUIRED - set(ev)
        if missing:
            _fail(f"event {i} ({ev.get('name')!r}) missing keys "
                  f"{sorted(missing)}")
        if not isinstance(ev["name"], str):
            _fail(f"event {i}: 'name' must be a string")
        ph = ev["ph"]
        if ph not in _PHASES:
            _fail(f"event {i} ({ev['name']!r}): unknown phase {ph!r}")
        if not isinstance(ev["ts"], (int, float)) or \
                isinstance(ev["ts"], bool):
            _fail(f"event {i} ({ev['name']!r}): 'ts' must be numeric")
        for key in ("pid", "tid"):
            if not isinstance(ev[key], int) or isinstance(ev[key], bool):
                _fail(f"event {i} ({ev['name']!r}): {key!r} must be an int")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or isinstance(dur, bool):
                _fail(f"event {i} ({ev['name']!r}): 'X' event needs a "
                      "numeric 'dur'")
            if dur < 0:
                _fail(f"event {i} ({ev['name']!r}): negative dur {dur}")
            if ev["ts"] < 0:
                _fail(f"event {i} ({ev['name']!r}): negative ts {ev['ts']}")
            per_thread.setdefault((ev["pid"], ev["tid"]), []).append(
                (float(ev["ts"]), float(dur), ev["name"]))
        if ph == "C" and not isinstance(ev.get("args"), dict):
            _fail(f"event {i} ({ev['name']!r}): 'C' event needs an "
                  "'args' object")
        if ph != "M":
            substantive += 1
    # monotonic per-thread nesting: within one thread the span set must be
    # a forest (timer misuse shows up as partial overlap)
    eps = 0.5  # µs slack: perf_counter quantization on coarse clocks
    for (pid, tid), spans in per_thread.items():
        spans.sort(key=lambda s: (s[0], -s[1]))
        stack: List[tuple] = []  # (end_ts, name)
        for ts, dur, name in spans:
            while stack and stack[-1][0] <= ts + eps:
                stack.pop()
            if stack and ts + dur > stack[-1][0] + eps:
                _fail(f"thread ({pid}, {tid}): span {name!r} "
                      f"[{ts:.1f}, {ts + dur:.1f}] partially overlaps "
                      f"enclosing span {stack[-1][1]!r} ending at "
                      f"{stack[-1][0]:.1f}")
            stack.append((ts + dur, name))
    if require_complete and substantive == 0:
        _fail("trace holds no events beyond metadata")
    return events
