"""Fleet-wide communication observability: collective ledger, desync /
straggler detection, hung-collective flight recorder.

The rest of the telemetry plane (tracer / step breakdown / memory ledger)
sees exactly one process; every multi-rank protocol in the stack — the
kvstore push/pull round, the ZeRO-1 reduce-scatter / allgather /
``zero_all_finite`` plane, the coordination-service byte channel — fails
in ways a rank-local view cannot explain: one straggler rank stretches
every collective, a desynced collective ORDER deadlocks the group, one
rank hung in a collective blocks every peer forever with no stack that
names it. The reference ships a distributed profiler over the kvstore
command channel for exactly this reason (PAPER.md §profiler); this module
is the TPU-native equivalent. Three layers:

**Collective ledger** (:class:`CollectiveLedger`): every collective entry
point — ``KVStore`` push/pull, ``zero_reduce_scatter`` /
``zero_allgather`` / ``zero_all_finite``, the coordination-service
``cross_process_exchange_bytes`` / ``barrier`` hops — records
``(seq, kind, key, bytes, rank, t_enter, t_exit)`` into a bounded
per-process ring (``MXTPU_COLL_RING``) with a per-``(kind, key)``
monotone ``seq``. Off by default and near-zero cost when off (the tracer
discipline: one enabled check per entry point, no clock reads, no
allocation); enabled whenever ``MXTPU_COLL_HEALTH`` or
``MXTPU_COLL_TIMEOUT_S`` is armed.

**Desync / straggler detection**: :func:`health_check` exchanges each
rank's recent ledger digest over the coordination-service byte channel
(the transport every CPU-backend collective already rides) and
:func:`compare_digests` diffs them — a mismatch in the ``(kind, key,
seq)`` ORDER between ranks is a desync diagnosis (logged, counted in
``mxtpu_coll_desync_total``, raised under ``strict=True``); per-collective
entry-time skew is attributed per rank (``mxtpu_coll_skew_ms`` /
``mxtpu_coll_straggler_rank`` gauges, ``FitResult.comm_health``, and the
step-breakdown detector's "straggler-bound" diagnosis variant). Entry
times are normalized onto rank 0's clock via the median-of-K round-trip
offset handshake (:func:`sync_clocks`), the same anchor the fleet trace
merge (``tools/fleet_trace.py``) aligns per-rank chrome traces with.

**Hung-collective flight recorder**: with ``MXTPU_COLL_TIMEOUT_S > 0`` a
watchdog thread is armed at each collective entry; a collective still
in flight past the timeout dumps a flight record — the ring, the hung
``(kind, key, seq)``, the peer rank the transport is blocked on
(:func:`note_waiting`, stamped by the byte-channel loop), and every
thread's stack — to the forensics dir (``MXTPU_MEM_DUMP_DIR``,
tmp+rename, like ``memory.dump_forensics``). Every *surviving* rank
names the hung collective and the absent rank; the chaos grammar's
``kv_hang:<rank>@N[:MS]`` drives the whole path deterministically on CPU.

The plane is numerically inert: it reads clocks and writes JSON, never a
gradient — training trajectories are bitwise identical with it on or off
(test-pinned, the PR 6/9 discipline).
"""
from __future__ import annotations

import itertools
import json
import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional

from ..base import MXNetError, env

__all__ = ["CollectiveLedger", "ledger", "enabled", "enter", "exit_",
           "note_waiting", "compare_digests", "health_check",
           "health_summary", "reset_health", "sync_clocks", "timeout_s",
           "health_interval", "ring_capacity", "parse_flight_record",
           "scan_flight_records"]

DEFAULT_RING = 4096

#: ring records serialized into a flight record / digest exchange
_TAIL = 200


def timeout_s() -> float:
    """``MXTPU_COLL_TIMEOUT_S``: hung-collective watchdog timeout in
    seconds (0 = watchdog off). Unparseable values raise — a typo'd
    watchdog request must not silently never fire."""
    try:
        t = float(env.get("MXTPU_COLL_TIMEOUT_S"))
    except (TypeError, ValueError) as e:
        raise MXNetError(
            f"MXTPU_COLL_TIMEOUT_S: not a number: "
            f"{env.raw('MXTPU_COLL_TIMEOUT_S')!r}") from e
    if t < 0:
        raise MXNetError(f"MXTPU_COLL_TIMEOUT_S must be >= 0, got {t}")
    return t


def health_interval() -> int:
    """``MXTPU_COLL_HEALTH``: run the cross-rank comm-health exchange
    every N steps (0 = off). N > 0 also turns the collective ledger on.
    Distributed runs: the exchange is a COLLECTIVE — every rank must
    call it at the same cadence (``fit.FitLoop`` does)."""
    try:
        n = int(env.get("MXTPU_COLL_HEALTH"))
    except (TypeError, ValueError) as e:
        raise MXNetError(
            f"MXTPU_COLL_HEALTH: not an integer: "
            f"{env.raw('MXTPU_COLL_HEALTH')!r}") from e
    if n < 0:
        raise MXNetError(f"MXTPU_COLL_HEALTH must be >= 0, got {n}")
    return n


def ring_capacity() -> int:
    """``MXTPU_COLL_RING``: collective-ledger ring capacity."""
    try:
        n = int(env.get("MXTPU_COLL_RING"))
    except (TypeError, ValueError) as e:
        raise MXNetError(
            f"MXTPU_COLL_RING: not an integer: "
            f"{env.raw('MXTPU_COLL_RING')!r}") from e
    if n < 1:
        raise MXNetError(f"MXTPU_COLL_RING must be >= 1, got {n}")
    return n


class CollectiveLedger:
    """Bounded per-process ring of collective records + the in-flight set
    the watchdog scans.

    A record is ``{seq, kind, key, bytes, rank, t_enter, t_exit,
    waiting_for}`` with times in ``perf_counter`` seconds; the
    perf↔epoch anchor captured at construction converts them to wall
    clock for the cross-rank digest and the flight record. ``seq`` is
    monotone per ``(kind, key)`` — the identity two ranks compare to
    detect a desynced collective order.
    """

    def __init__(self, ring: Optional[int] = None):
        self._lock = threading.Lock()
        self._cap = int(ring) if ring else DEFAULT_RING
        self._ring: deque = deque(maxlen=self._cap)
        self._seq: Dict[tuple, int] = {}
        self._inflight: Dict[int, Dict[str, Any]] = {}
        self._tokens = itertools.count(1)
        self._dropped = 0
        # perf_counter <-> epoch anchor, captured at one instant: every
        # cross-rank time comparison converts through it
        self._perf0 = time.perf_counter()
        self._epoch0 = time.time()
        #: this rank's clock minus rank 0's, in ms (sync_clocks)
        self.clock_offset_ms = 0.0
        self._watchdog: Optional[threading.Thread] = None
        self._watchdog_lock = threading.Lock()
        self.watchdog_fired = 0
        self.flight_records: List[str] = []
        self._forced: Optional[bool] = None
        # raw env strings -> parsed (on, ring_cap, timeout_s): neither
        # enabled() nor enter() may re-run a typed parse per kvstore op
        self._env_cache: Optional[tuple] = None

    def _env_state(self) -> tuple:
        """(plane_on, ring_capacity, timeout_s), parsed once and cached
        against the raw env strings — the hot path pays three environ
        lookups and a tuple compare, not typed parses — while staying
        responsive to env changes (tests monkeypatch these vars
        mid-process). Strict-parse errors still raise on every call."""
        raw = (env.raw("MXTPU_COLL_HEALTH"),
               env.raw("MXTPU_COLL_TIMEOUT_S"),
               env.raw("MXTPU_COLL_RING"))
        c = self._env_cache
        if c is not None and c[0] == raw:
            return c[1]
        t = timeout_s()
        state = (health_interval() > 0 or t > 0, ring_capacity(), t)
        self._env_cache = (raw, state)
        return state

    # -- state ----------------------------------------------------------
    @property
    def enabled(self) -> bool:
        """On when forced programmatically, or when either the health
        exchange or the watchdog is armed (the flight record needs the
        ring, so arming the watchdog turns recording on too)."""
        if self._forced is not None:
            return self._forced
        return self._env_state()[0]

    def force(self, on: Optional[bool]) -> None:
        """Programmatic override: True/False pins the plane on/off
        regardless of env; None restores env-driven behavior."""
        self._forced = on

    def epoch_of(self, t_perf: float) -> float:
        return self._epoch0 + (t_perf - self._perf0)

    def depth(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def dropped(self) -> int:
        return self._dropped

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq.clear()
            self._dropped = 0

    # -- recording ------------------------------------------------------
    def enter(self, kind: str, key, nbytes: int = 0, rank: int = 0) -> int:
        """Open one collective; returns the token :meth:`exit` closes.
        Callers gate on :attr:`enabled` — this method assumes the plane
        is on."""
        _, cap, tmo = self._env_state()
        t0 = time.perf_counter()
        with self._lock:
            if cap != self._cap:
                # a SHRINK evicts the oldest records right here — they
                # count as drops like any ring eviction, never silent
                self._dropped += max(0, len(self._ring) - cap)
                self._cap = cap
                self._ring = deque(self._ring, maxlen=cap)
            ident = (kind, str(key))
            # pop+reinsert keeps dict insertion order == recency, so the
            # bound below always evicts the LONGEST-IDLE identity. The
            # seq map must not grow forever: byte-channel collectives
            # (exchange/barrier/health tags) carry a counter in the KEY,
            # so each is a fresh identity. An identity idle for 4x the
            # ring has left the comparable window anyway — its seq
            # restarting at 0 can no longer desync a digest diff.
            seq = self._seq.pop(ident, -1) + 1
            self._seq[ident] = seq
            limit = 4 * self._cap
            while len(self._seq) > limit:
                del self._seq[next(iter(self._seq))]
            tok = next(self._tokens)
            self._inflight[tok] = {
                "seq": seq, "kind": kind, "key": str(key),
                "bytes": int(nbytes), "rank": int(rank),
                "t_enter": t0, "t_exit": None, "waiting_for": None}
        if tmo > 0:
            self._ensure_watchdog()
        return tok

    def note_waiting(self, tok: int, rank) -> None:
        """Stamp the peer rank the in-flight collective is currently
        blocked on (the byte-channel loop calls this before each blocking
        get) — the flight record's "absent rank"."""
        with self._lock:
            rec = self._inflight.get(tok)
            if rec is not None:
                rec["waiting_for"] = rank

    def exit(self, tok: int) -> None:
        with self._lock:
            rec = self._inflight.pop(tok, None)
            if rec is None:
                return
            rec["t_exit"] = time.perf_counter()
            rec["waiting_for"] = None
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
            self._ring.append(rec)

    # -- inspection -----------------------------------------------------
    def records(self, last_n: int = _TAIL) -> List[Dict[str, Any]]:
        """Completed records (copies), newest last, with epoch-converted
        times alongside the raw perf_counter ones."""
        with self._lock:
            recs = list(self._ring)[-last_n:]
        out = []
        for r in recs:
            d = dict(r)
            d["t_enter_epoch"] = self.epoch_of(r["t_enter"])
            if r["t_exit"] is not None:
                d["dur_ms"] = (r["t_exit"] - r["t_enter"]) * 1e3
            out.append(d)
        return out

    def digest(self, last_n: int = _TAIL) -> List[Dict[str, Any]]:
        """The cross-rank comparison payload: the last ``last_n``
        completed collectives as ``{kind, key, seq, bytes,
        t_enter_epoch}`` with entry times normalized onto rank 0's clock
        (``clock_offset_ms`` subtracted) so peers diff them directly."""
        off_s = self.clock_offset_ms / 1e3
        with self._lock:
            recs = list(self._ring)[-last_n:]
        return [{"kind": r["kind"], "key": r["key"], "seq": r["seq"],
                 "bytes": r["bytes"],
                 "t_enter_epoch": self.epoch_of(r["t_enter"]) - off_s}
                for r in recs]

    def inflight(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(r) for r in self._inflight.values()]

    # -- watchdog -------------------------------------------------------
    def _ensure_watchdog(self) -> None:
        with self._watchdog_lock:
            if self._watchdog is not None and self._watchdog.is_alive():
                return
            self._watchdog = threading.Thread(
                target=self._watch, name="mxtpu-coll-watchdog", daemon=True)
            self._watchdog.start()

    def _watch(self) -> None:
        while True:
            try:
                t = self._env_state()[2]
            except MXNetError:
                t = 0.0  # env mutated to junk mid-run: disarm, don't die
            # poll capped at 250ms: the timeout can SHRINK between
            # wakes (env re-armed tighter), and a sleep sized from the
            # old value would doze through a whole hang window
            time.sleep(min(0.25, max(0.02, (t or 1.0) / 4.0)))
            if t <= 0:
                # disarmed with nothing in flight: exit instead of
                # polling for the process lifetime — the next armed
                # enter() re-spawns. The re-check runs under the spawn
                # lock so an enter() that just re-armed can't see a
                # live thread that then exits.
                with self._watchdog_lock:
                    try:
                        rearmed = self._env_state()[2] > 0
                    except MXNetError:
                        rearmed = False
                    with self._lock:
                        idle = not self._inflight
                    if not rearmed and idle:
                        self._watchdog = None
                        return
                continue
            now = time.perf_counter()
            with self._lock:
                overdue = [r for r in self._inflight.values()
                           if now - r["t_enter"] > t
                           and not r.get("_dumped")]
            if not overdue:
                continue
            try:
                self._dump_flight(overdue, t)
                with self._lock:
                    for r in overdue:
                        r["_dumped"] = True
            except Exception as e:
                # a failed dump (full/unwritable disk) RETRIES on the
                # next wake — marking first would silently lose the one
                # record the recorder exists to write; after 3 failures
                # give up, but the hang is still NAMED in the log
                with self._lock:
                    for r in overdue:
                        r["_fails"] = r.get("_fails", 0) + 1
                        if r["_fails"] >= 3:
                            r["_dumped"] = True
                try:
                    from ..log import get_logger
                    get_logger("mxnet_tpu.telemetry").error(
                        "flight-record dump failed (%s); hung "
                        "collectives: %s", e,
                        [(r["kind"], r["key"], r["seq"])
                         for r in overdue])
                except Exception:
                    pass  # the black box must not take down the run

    def _dump_flight(self, overdue: List[dict], timeout: float) -> str:
        """The flight record: every surviving rank writes one naming the
        hung ``(kind, key, seq)`` and the absent rank, with the ring and
        all-thread stacks — enough to diagnose the hang from disk after
        the group is killed. tmp+rename like ``memory.dump_forensics``."""
        now = time.perf_counter()
        names = {th.ident: th.name for th in threading.enumerate()}
        stacks = {}
        for ident, frame in sys._current_frames().items():
            stacks[names.get(ident, f"thread-{ident}")] = \
                traceback.format_stack(frame)
        hung = []
        absent = None
        for r in sorted(overdue, key=lambda r: -r["t_enter"]):
            hung.append({
                "kind": r["kind"], "key": r["key"], "seq": r["seq"],
                "bytes": r["bytes"], "rank": r["rank"],
                "waiting_for_rank": r["waiting_for"],
                "elapsed_s": round(now - r["t_enter"], 3),
                "t_enter_epoch": self.epoch_of(r["t_enter"])})
            if absent is None and r["waiting_for"] is not None:
                # the most recently entered collective with a named peer
                # is the innermost transport hop — its peer is the rank
                # that never showed up
                absent = r["waiting_for"]
        payload = {
            "reason": "hung_collective",
            "time_unix": time.time(),
            "pid": os.getpid(),
            "rank": hung[0]["rank"] if hung else 0,
            "timeout_s": timeout,
            "absent_rank": absent,
            # when the EARLIEST still-hung collective entered (epoch
            # seconds): with absent_rank these two top-level fields are
            # the machine-readable contract the fleet supervisor
            # (parallel/supervisor.py) keys its shrink decision on —
            # everything else in the record is for humans
            "hung_since": min(self.epoch_of(r["t_enter"])
                              for r in overdue),
            "hung": hung,
            "ring": self.records(_TAIL),
            "thread_stacks": stacks,
        }
        d = str(env.get("MXTPU_MEM_DUMP_DIR") or "") or "."
        try:
            os.makedirs(d, exist_ok=True)
        except OSError:
            d = "."
        path = os.path.join(
            d, f"coll_flight_{os.getpid()}_{next(_dump_seq)}.json")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1, default=str)
        os.replace(tmp, path)
        self.watchdog_fired += 1
        self.flight_records.append(path)
        try:
            from .registry import default_registry
            default_registry().counter(
                "mxtpu_coll_watchdog_fired_total",
                "Hung-collective flight records written "
                "(MXTPU_COLL_TIMEOUT_S watchdog).").inc()
        except Exception:
            pass
        try:
            from ..log import get_logger
            get_logger("mxnet_tpu.telemetry").error(
                "hung collective: %s:%s seq=%d in flight > %gs "
                "(absent rank: %s) — flight record %s",
                hung[0]["kind"], hung[0]["key"], hung[0]["seq"],
                timeout, absent, path)
        except Exception:
            pass
        return path


#: the process-wide ledger
ledger = CollectiveLedger()

_dump_seq = itertools.count(1)
_clk_seq = itertools.count(1)
_health_seq = itertools.count(1)


def enabled() -> bool:
    return ledger.enabled


def enter(kind: str, key, nbytes: int = 0, rank: int = 0) -> int:
    return ledger.enter(kind, key, nbytes, rank)


def exit_(tok: int) -> None:
    ledger.exit(tok)


def note_waiting(tok: int, rank) -> None:
    ledger.note_waiting(tok, rank)


# ---------------------------------------------------------------------------
# Flight-record consumption (the supervisor side of the watchdog)
# ---------------------------------------------------------------------------

def parse_flight_record(path: str) -> Dict[str, Any]:
    """Parse one ``coll_flight_*.json`` dump into the stable supervisor
    schema: ``{path, pid, rank, absent_rank, hung_since, time_unix,
    hung}``. Tolerates pre-``hung_since`` records (PR 12 layout:
    ``hung_since`` comes back None) — the supervisor must be able to read
    a record written by an older surviving rank. Raises
    :class:`MXNetError` on anything that is not a hung-collective flight
    record; an unreadable record must fail the parse, not silently count
    as "no hang"."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, ValueError) as e:
        raise MXNetError(f"flight record {path}: unreadable: {e}") from e
    if payload.get("reason") != "hung_collective":
        raise MXNetError(
            f"flight record {path}: reason is "
            f"{payload.get('reason')!r}, not 'hung_collective'")
    absent = payload.get("absent_rank")
    return {
        "path": path,
        "pid": payload.get("pid"),
        "rank": payload.get("rank"),
        "absent_rank": int(absent) if absent is not None else None,
        "hung_since": payload.get("hung_since"),
        "time_unix": payload.get("time_unix"),
        "hung": payload.get("hung", []),
    }


def scan_flight_records(dump_dir: str,
                        seen: Optional[set] = None) -> List[Dict[str, Any]]:
    """List-and-parse every ``coll_flight_*.json`` under ``dump_dir`` not
    already in ``seen`` (a set of paths the caller owns; updated in
    place). The supervisor polls this between worker waits — records are
    tmp+rename so a listed file always parses; one that still fails is
    skipped this pass and retried on the next (never marked seen)."""
    out: List[Dict[str, Any]] = []
    if not os.path.isdir(dump_dir):
        return out
    for name in sorted(os.listdir(dump_dir)):
        if not (name.startswith("coll_flight_")
                and name.endswith(".json")):
            continue
        path = os.path.join(dump_dir, name)
        if seen is not None and path in seen:
            continue
        try:
            rec = parse_flight_record(path)
        except MXNetError:
            continue
        if seen is not None:
            seen.add(path)
        out.append(rec)
    return out


# ---------------------------------------------------------------------------
# Desync / straggler detection
# ---------------------------------------------------------------------------

_health_lock = threading.Lock()
_last_compare: Optional[Dict[str, Any]] = None
_checks = 0
# watchdog/flight baselines at the last reset_health(): a FitResult's
# comm_health reports THIS run's firings, not the process lifetime's
_baseline = {"fired": 0, "flights": 0}


def reset_health() -> None:
    """Re-arm the health plane for a fresh run (``fit.FitLoop`` calls
    this at fit start, like ``memory.reset_pressure_state``): drops the
    previous run's comparison/check count and snapshots the watchdog
    baselines so :func:`health_summary` describes only this run."""
    global _last_compare, _checks
    with _health_lock:
        _last_compare = None
        _checks = 0
        _baseline["fired"] = ledger.watchdog_fired
        _baseline["flights"] = len(ledger.flight_records)


def compare_digests(per_rank: Dict[int, List[dict]]) -> Dict[str, Any]:
    """Diff per-rank ledger digests: desynced collective order + per-rank
    entry-time skew.

    - **Desync**: restricted to the ``(kind, key, seq)`` identities every
      rank saw, the ORDER must be identical on all ranks — ranks issuing
      the same collectives in different orders is the deadlock-in-waiting
      the reference's dependency engine makes possible. The first
      divergence is named in the diagnosis.
    - **Skew**: for each common identity, each rank's entry lag behind
      the earliest rank, in ms (entry times are already normalized onto
      rank 0's clock by :meth:`CollectiveLedger.digest`). The rank with
      the largest mean lag is the straggler.
    """
    ranks = sorted(int(r) for r in per_rank)
    ids_by_rank = {r: [(d["kind"], d["key"], d["seq"]) for d in per_rank[r]]
                   for r in ranks}
    common = None
    for r in ranks:
        s = set(ids_by_rank[r])
        common = s if common is None else common & s
    common = common or set()
    desync = None
    ref_order = [i for i in ids_by_rank[ranks[0]] if i in common]
    for r in ranks[1:]:
        mine = [i for i in ids_by_rank[r] if i in common]
        if mine != ref_order:
            pos = 0
            for pos, (a, b) in enumerate(zip(ref_order, mine)):
                if a != b:
                    break
            desync = {
                "ranks": [ranks[0], r], "position": pos,
                "expected": list(ref_order[pos])
                if pos < len(ref_order) else None,
                "got": list(mine[pos]) if pos < len(mine) else None}
            break
    times: Dict[tuple, Dict[int, float]] = {}
    for r in ranks:
        for d in per_rank[r]:
            i = (d["kind"], d["key"], d["seq"])
            if i in common:
                times.setdefault(i, {})[r] = float(d["t_enter_epoch"])
    lags: Dict[int, List[float]] = {r: [] for r in ranks}
    for ts in times.values():
        mn = min(ts.values())
        for r, t in ts.items():
            lags[r].append((t - mn) * 1e3)
    skew_by_rank = {}
    for r in ranks:
        ls = lags[r]
        skew_by_rank[r] = {
            "mean_ms": round(sum(ls) / len(ls), 3) if ls else 0.0,
            "max_ms": round(max(ls), 3) if ls else 0.0}
    max_skew = max((v["max_ms"] for v in skew_by_rank.values()),
                   default=0.0)
    straggler = None
    if max_skew > 0:
        straggler = max(ranks, key=lambda r: skew_by_rank[r]["mean_ms"])
    return {"world": len(ranks), "compared": len(common),
            "desync": desync, "skew_ms_by_rank": skew_by_rank,
            "max_skew_ms": max_skew, "straggler_rank": straggler}


def sync_clocks(k: int = 5) -> float:
    """Median-of-K round-trip clock-offset handshake over the
    coordination-service byte channel: estimates this rank's wall clock
    minus rank 0's, in ms. Each round every rank publishes its epoch
    time; a peer reads rank 0's inside a locally-timed window, so
    ``offset ≈ midpoint − rank0_publish`` per round; the median fences
    scheduler noise. The offset lands in the collective ledger (digest
    normalization) AND the tracer's clock anchor, so the fleet trace
    merge (``tools/fleet_trace.py``) aligns per-rank traces onto one
    clock. A COLLECTIVE: every rank must call with the same ``k``.
    Single-process runs return 0.0 without touching the channel."""
    import pickle
    try:
        import jax
        if jax.process_count() <= 1:
            return 0.0
        rank = jax.process_index()
    except Exception:
        return 0.0
    from ..parallel.collectives import cross_process_exchange_bytes
    offsets = []
    base = next(_clk_seq)
    for i in range(int(k)):
        t0 = time.time()
        blobs = cross_process_exchange_bytes(
            pickle.dumps(time.time()), f"clk{base}_{i}")
        t1 = time.time()
        ref_t = pickle.loads(blobs[0])
        offsets.append(((t0 + t1) / 2.0 - ref_t) * 1e3)
    offsets.sort()
    # rank 0 IS the reference clock: estimating its offset against its
    # own publish would bake in ~half the exchange wall time as phantom
    # skew on every digest; it runs the K rounds (collective contract)
    # and pins 0.0
    off = 0.0 if rank == 0 else offsets[len(offsets) // 2]
    ledger.clock_offset_ms = off
    try:
        from .tracer import tracer as _tr
        _tr.clock_offset_ms = off
    except Exception:
        pass
    return off


def health_check(kv=None, breakdown=None, strict: bool = False
                 ) -> Dict[str, Any]:
    """One comm-health round: exchange ledger digests across the worker
    group (``kv.num_workers > 1`` and the coordination channel up; a
    single-worker / simulated-world run compares against itself) and
    publish the diagnosis — skew gauges, desync counter/log, the
    step-breakdown straggler note. ``strict=True`` raises on a desynced
    collective order instead of just diagnosing it.

    Distributed runs: this is a COLLECTIVE (the digest allgather rides
    the byte channel) — every rank must call at the same cadence;
    ``fit.FitLoop`` drives it every ``MXTPU_COLL_HEALTH`` steps."""
    global _checks, _last_compare
    my_rank = int(getattr(kv, "rank", 0) or 0)
    world = int(getattr(kv, "num_workers", 1) or 1)
    my = ledger.digest()
    per_rank = {my_rank: my}
    if kv is not None and world > 1:
        from ..parallel.collectives import cross_process_allgather_object
        outs = cross_process_allgather_object(
            {"rank": my_rank, "digest": my},
            f"health{next(_health_seq)}_")
        per_rank = {int(o["rank"]): o["digest"] for o in outs}
    cmp = compare_digests(per_rank)
    cmp["rank"] = my_rank
    with _health_lock:
        _checks += 1
        _last_compare = cmp
    try:
        from .registry import default_registry
        reg = default_registry()
        reg.gauge("mxtpu_coll_skew_ms",
                  "Max per-collective entry-time skew across ranks at "
                  "the last comm-health check (ms).").set(
            cmp["max_skew_ms"])
        reg.gauge("mxtpu_coll_straggler_rank",
                  "Rank with the largest mean collective entry lag at "
                  "the last comm-health check (-1 = none).").set(
            cmp["straggler_rank"] if cmp["straggler_rank"] is not None
            else -1)
        if cmp["desync"]:
            reg.counter(
                "mxtpu_coll_desync_total",
                "Cross-rank collective-order mismatches diagnosed by "
                "the comm-health exchange.").inc()
    except Exception:
        pass
    if cmp["desync"]:
        msg = (f"collective desync between ranks {cmp['desync']['ranks']}"
               f" at position {cmp['desync']['position']}: expected "
               f"{cmp['desync']['expected']}, got {cmp['desync']['got']}")
        try:
            from ..log import get_logger
            get_logger("mxnet_tpu.telemetry").error(
                "comm health: %s", msg)
        except Exception:
            pass
        if strict:
            raise MXNetError(f"comm health: {msg}")
    if breakdown is not None:
        try:
            breakdown.note_comm_health(cmp)
        except Exception:
            pass
    return cmp


def health_summary() -> Dict[str, Any]:
    """The ``FitResult.comm_health`` payload: the last comparison since
    :func:`reset_health` (or a zero-skew self view when no check ran),
    plus the ledger / watchdog state — watchdog firings and flight
    records are reported relative to the last reset, so one run's
    summary never carries an earlier run's hangs."""
    with _health_lock:
        cmp = dict(_last_compare) if _last_compare else None
        checks = _checks
        fired0 = _baseline["fired"]
        flights0 = _baseline["flights"]
    if cmp is None:
        cmp = compare_digests({0: ledger.digest()})
        cmp["rank"] = 0
    cmp.update({
        "checks": checks,
        "ledger_depth": ledger.depth(),
        "ledger_dropped": ledger.dropped,
        "watchdog_fired": ledger.watchdog_fired - fired0,
        "flight_records": list(ledger.flight_records[flights0:]),
        "clock_offset_ms": round(ledger.clock_offset_ms, 3),
    })
    return cmp
