"""Shared metrics registry: counters, gauges, histograms.

The metric primitives started life inside ``serving/metrics.py`` (one
subsystem's private plane); this module promotes them to the shared layer
every subsystem reports into. ``serving`` builds its ``ServerMetrics`` from
these types unchanged (its Prometheus/JSON expositions stay byte-identical),
while the *default registry* absorbs the counters that used to be scattered
one-off probes:

- CachedOp signature-cache hits/misses/evictions (``cached_op.py``),
- kvstore push/pull transient-error retries (``kvstore.py``),
- chaos injections by kind (``contrib/chaos.py``),
- Trainer update dispatches / allreduce collectives (``gluon/trainer.py``),
- XLA compile events (count + seconds, via ``jax.monitoring`` listeners),
- device-memory watermarks (polled gauges; 0 on backends without
  ``memory_stats``).

Export: :meth:`MetricsRegistry.render_prometheus` /
:meth:`MetricsRegistry.render_json`.
"""
from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Dict, Iterable, List, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "LatencyHistogram",
           "MetricsRegistry", "default_registry",
           "DEFAULT_LATENCY_BUCKETS_MS"]

# log-ish spaced, ms. Chosen to resolve both sub-ms CPU models and
# multi-second cold compiles.
DEFAULT_LATENCY_BUCKETS_MS = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
                              250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0)


def _fmt(v: float) -> str:
    """Prometheus sample value: render integers without the trailing .0."""
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


class Histogram:
    """Thread-safe histogram: cumulative buckets for Prometheus plus a
    bounded raw-sample reservoir for exact recent percentiles."""

    def __init__(self, buckets: Iterable[float] = DEFAULT_LATENCY_BUCKETS_MS,
                 max_samples: int = 8192):
        self.bounds: Tuple[float, ...] = tuple(sorted(buckets))
        self._counts = [0] * (len(self.bounds) + 1)  # last = +Inf overflow
        self._sum = 0.0
        self._count = 0
        self._samples: deque = deque(maxlen=max_samples)
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            i = 0
            for i, b in enumerate(self.bounds):
                if value <= b:
                    break
            else:
                i = len(self.bounds)
            self._counts[i] += 1
            self._sum += value
            self._count += 1
            self._samples.append(value)

    @property
    def count(self) -> int:
        return self._count

    def percentile(self, q: float) -> float:
        """Exact percentile over the sample reservoir (0 when empty)."""
        with self._lock:
            if not self._samples:
                return 0.0
            s = sorted(self._samples)
        k = max(0, min(len(s) - 1, int(round(q / 100.0 * (len(s) - 1)))))
        return float(s[k])

    def snapshot(self) -> dict:
        with self._lock:
            count, total = self._count, self._sum
            s = sorted(self._samples)  # ONE sort for all three percentiles

        def pct(q):
            if not s:
                return 0.0
            k = max(0, min(len(s) - 1, int(round(q / 100.0 * (len(s) - 1)))))
            return round(float(s[k]), 3)

        return {
            "count": count,
            "sum": round(total, 3),
            "mean": round(total / count, 3) if count else 0.0,
            "p50": pct(50),
            "p95": pct(95),
            "p99": pct(99),
        }

    def prometheus_lines(self, name: str, help_: str) -> List[str]:
        with self._lock:
            counts = list(self._counts)
            total, count = self._sum, self._count
        lines = [f"# HELP {name} {help_}", f"# TYPE {name} histogram"]
        cum = 0
        for bound, c in zip(self.bounds, counts):
            cum += c
            lines.append(f'{name}_bucket{{le="{_fmt(bound)}"}} {cum}')
        lines.append(f'{name}_bucket{{le="+Inf"}} {count}')
        lines.append(f"{name}_sum {_fmt(round(total, 6))}")
        lines.append(f"{name}_count {count}")
        return lines


#: serving's historical name for the same type (back-compat alias)
LatencyHistogram = Histogram


class Counter:
    """Monotone counter, optionally labelled (one label dimension)."""

    def __init__(self, label: Optional[str] = None):
        self.label = label
        self._value = 0
        self._labelled: "OrderedDict[str, int]" = OrderedDict()
        self._lock = threading.Lock()

    def inc(self, n: int = 1, label_value: Optional[str] = None) -> None:
        with self._lock:
            self._value += n
            if label_value is not None:
                self._labelled[label_value] = \
                    self._labelled.get(label_value, 0) + n

    @property
    def value(self) -> int:
        return self._value

    def by_label(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._labelled)

    def prometheus_lines(self, name: str, help_: str) -> List[str]:
        lines = [f"# HELP {name} {help_}", f"# TYPE {name} counter"]
        with self._lock:
            if self.label and self._labelled:
                for lv, v in self._labelled.items():
                    lines.append(f'{name}{{{self.label}="{lv}"}} {v}')
            else:
                lines.append(f"{name} {self._value}")
        return lines


class Gauge:
    """Point-in-time value; tracks its high-water mark."""

    def __init__(self):
        self._value = 0.0
        self.peak = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v
            if v > self.peak:
                self.peak = v

    def inc(self, delta: float = 1.0) -> None:
        """Atomic read-modify-write (set(value+1) from two threads loses
        an increment; concurrent workers must use this)."""
        with self._lock:
            self._value += delta
            if self._value > self.peak:
                self.peak = self._value

    def dec(self, delta: float = 1.0) -> None:
        self.inc(-delta)

    @property
    def value(self) -> float:
        return self._value

    def prometheus_lines(self, name: str, help_: str) -> List[str]:
        return [f"# HELP {name} {help_}", f"# TYPE {name} gauge",
                f"{name} {_fmt(self._value)}"]


class _CallbackGauge:
    """Gauge whose value is polled from a callable at export time."""

    def __init__(self, fn: Callable[[], float]):
        self._fn = fn

    @property
    def value(self) -> float:
        try:
            return float(self._fn())
        except Exception:
            return 0.0

    def prometheus_lines(self, name: str, help_: str) -> List[str]:
        return [f"# HELP {name} {help_}", f"# TYPE {name} gauge",
                f"{name} {_fmt(self.value)}"]


class MetricsRegistry:
    """Named metric directory with get-or-create semantics.

    Names follow Prometheus conventions (``mxtpu_<subsystem>_<what>[_total]``).
    Re-requesting an existing name returns the same object; requesting it as
    a different metric type raises — two subsystems silently sharing one
    name with different meanings is the bug this registry exists to stop.
    """

    def __init__(self):
        self._metrics: "OrderedDict[str, tuple]" = OrderedDict()
        self._lock = threading.Lock()

    def _get_or_create(self, name: str, kind: str, help_: str,
                       factory: Callable[[], object]):
        with self._lock:
            hit = self._metrics.get(name)
            if hit is not None:
                if hit[0] != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as {hit[0]}, "
                        f"requested as {kind}")
                return hit[1]
            m = factory()
            self._metrics[name] = (kind, m, help_)
            return m

    def counter(self, name: str, help: str = "",
                label: Optional[str] = None) -> Counter:
        return self._get_or_create(name, "counter", help,
                                   lambda: Counter(label=label))

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, "gauge", help, Gauge)

    def histogram(self, name: str, help: str = "",
                  buckets: Iterable[float] = DEFAULT_LATENCY_BUCKETS_MS
                  ) -> Histogram:
        return self._get_or_create(name, "histogram", help,
                                   lambda: Histogram(buckets=buckets))

    def callback_gauge(self, name: str, fn: Callable[[], float],
                       help: str = "") -> None:
        """Register (or replace) a gauge polled from ``fn`` at export."""
        with self._lock:
            self._metrics[name] = ("gauge", _CallbackGauge(fn), help)

    def get(self, name: str):
        with self._lock:
            hit = self._metrics.get(name)
        return hit[1] if hit is not None else None

    def names(self) -> List[str]:
        with self._lock:
            return list(self._metrics)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()

    # -- export ---------------------------------------------------------
    def render_prometheus(self) -> str:
        with self._lock:
            items = list(self._metrics.items())
        lines: List[str] = []
        for name, (kind, m, help_) in items:
            lines += m.prometheus_lines(name, help_ or name)
        return "\n".join(lines) + ("\n" if lines else "")

    def render_json(self) -> dict:
        with self._lock:
            items = list(self._metrics.items())
        out: Dict[str, object] = {}
        for name, (kind, m, _help) in items:
            if kind == "histogram":
                out[name] = m.snapshot()
            elif kind == "counter" and m.label:
                out[name] = {"total": m.value, "by_label": m.by_label()}
            else:
                out[name] = m.value
        return out

    def render_json_text(self) -> str:
        return json.dumps(self.render_json())


_default = MetricsRegistry()
_runtime_hooks_installed = False
_hooks_lock = threading.Lock()


def default_registry() -> MetricsRegistry:
    """The process-wide registry (installs runtime hooks on first use)."""
    _install_runtime_hooks()
    return _default


def _install_runtime_hooks() -> None:
    """One-time wiring of runtime-level sources: XLA compile events and
    device-memory watermarks. Idempotent, never raises (telemetry must not
    take down training)."""
    global _runtime_hooks_installed
    with _hooks_lock:
        if _runtime_hooks_installed:
            return
        _runtime_hooks_installed = True
    compile_count = _default.counter(
        "mxtpu_xla_compile_total", "XLA compilation events observed "
        "(persistent-cache hits excluded — they are retrievals).")
    compile_secs = _default.counter(
        "mxtpu_xla_compile_seconds_total",
        "Wall-clock seconds spent in XLA compilation (persistent-cache "
        "hits excluded).")
    cache_hits = _default.counter(
        "mxtpu_xla_cache_hits_total",
        "Compiles satisfied by the persistent compilation cache.")
    cache_secs = _default.counter(
        "mxtpu_xla_cache_retrieval_seconds_total",
        "Wall-clock seconds spent retrieving executables from the "
        "persistent compilation cache.")
    try:
        from jax import monitoring as _mon

        # jax fires '/jax/compilation_cache/cache_hits' (or cache_misses)
        # immediately before the corresponding backend_compile_duration
        # event ON THE SAME THREAD; the thread-local carries that verdict
        # across so a persistent-cache HIT is counted as a retrieval, not
        # a compile — the zero-compile cold-start contract is measured on
        # mxtpu_xla_compile_seconds_total staying ~0 (serving/aot.py)
        _pending = threading.local()

        def _on_event(event: str, **kw) -> None:
            if event.endswith("compilation_cache/cache_hits"):
                _pending.verdict = "hit"
            elif event.endswith("compilation_cache/cache_misses"):
                _pending.verdict = "miss"

        def _on_duration(event: str, duration: float, **kw) -> None:
            # '/jax/core/compile/backend_compile_duration' (+ variants)
            # fire once per backend compile OR cache retrieval
            if "compile" not in event:
                return
            if event.endswith("backend_compile_duration"):
                verdict = getattr(_pending, "verdict", None)
                _pending.verdict = None
                if verdict == "hit":
                    cache_hits.inc()
                    cache_secs.inc(max(float(duration), 0.0))
                    span_name, span_cat = "xla_cache_hit", "compile"
                else:
                    compile_count.inc()
                    compile_secs.inc(max(float(duration), 0.0))
                    span_name, span_cat = "xla_compile", "compile"
                from .tracer import tracer as _tr
                if _tr.enabled:
                    import time as _t
                    now = _t.perf_counter()
                    # clamp to tracer birth: a compile that started
                    # before the tracer existed must not emit ts < 0
                    _tr.record(span_name, span_cat,
                               max(now - duration, _tr._t0), now)

        _mon.register_event_listener(_on_event)
        _mon.register_event_duration_secs_listener(_on_duration)
    except Exception:
        pass
    _default.callback_gauge(
        "mxtpu_device_bytes_in_use", _device_bytes_in_use,
        "Live device-memory bytes (0 on backends without memory_stats).")
    _default.callback_gauge(
        "mxtpu_device_peak_bytes", device_memory_watermark,
        "Peak device-memory bytes observed (high-water mark).")


_mem_peak = 0.0


def _device_stats_value(key_candidates: Tuple[str, ...]) -> float:
    try:
        import jax
        total = 0.0
        for d in jax.local_devices():
            stats = getattr(d, "memory_stats", lambda: None)()
            if not stats:
                continue
            for k in key_candidates:
                if k in stats:
                    total += float(stats[k])
                    break
        return total
    except Exception:
        return 0.0


def _ledger_value(attr: str) -> float:
    try:
        from . import memory as _memory
        led = _memory.ledger()
        return float(led.live_bytes() if attr == "live" else led.peak_bytes)
    except Exception:
        return 0.0


def _device_bytes_in_use() -> float:
    v = _device_stats_value(("bytes_in_use", "bytes_in_use_total"))
    if v <= 0:
        # host-CPU backends report no memory_stats: fall back to the
        # framework's own live-byte ledger (exact for tracked categories)
        # so these gauges stop reading 0 where tier-1 runs
        v = _ledger_value("live")
    global _mem_peak
    if v > _mem_peak:
        _mem_peak = v
    return v


def device_memory_watermark() -> float:
    """Peak device bytes seen by any poll (backend-reported peak when
    available, else the max over our own samples and the memory ledger's
    process-lifetime peak)."""
    reported = _device_stats_value(("peak_bytes_in_use",))
    return max(reported, _mem_peak, _ledger_value("peak"),
               _device_bytes_in_use())
