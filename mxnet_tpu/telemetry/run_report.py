"""Persistent run reports: one JSON verdict per training run.

Every axis of the measurement plane (time / memory / comm / numbers /
efficiency) publishes live surfaces that die with the process —
``FitResult``, gauges, traces. The question ROADMAP grades PRs on — *did
the last change make training slower?* — needs the OPPOSITE: a small,
versioned, on-disk artifact per run that a later run (or CI) can diff
against. This module writes it; ``tools/run_compare.py`` (stdlib-only)
diffs two of them into per-metric regression verdicts with a noise fence
and CI exit codes.

``fit.FitLoop`` calls :func:`write_run_report` at fit end whenever
``MXTPU_RUN_REPORT_DIR`` is set. The artifact (``run_<pid>_<ts>.json``,
tmp+rename so a file that exists parses) carries:

- a **fingerprint**: every declared env knob whose value differs from
  its default (the config axes that change trajectories), plus the
  backend/jax identity — so a diff tool can tell "slower" from
  "configured differently";
- the **step-time distribution** (p50/p95/max over the step-breakdown's
  recent window, plus full-run mean);
- a **loss-trajectory digest** (endpoints, extrema, tail, and a stable
  hash of the rounded trajectory — two bitwise-identical runs hash
  equal without shipping a million floats);
- **per-axis summaries**: breakdown shares, memory peaks, comm-health
  skew, numerics globals, and the efficiency rollup (MFU, samples/s,
  per-program FLOP top-list) when those planes ran.

The report directory keeps a shared ``fault.write_manifest`` SHA-256
manifest over its files, so a report that verifies is a report whose
bytes are the writer's bytes (the checkpoint/registry discipline).
"""
from __future__ import annotations

import hashlib
import itertools
import json
import math
import os
import time
from typing import Any, Dict, List, Optional

from ..base import env

__all__ = ["REPORT_FORMAT", "write_run_report", "load_run_report",
           "build_payload", "report_dir", "build_serving_payload",
           "write_serving_report"]

#: bump when the payload layout changes incompatibly; run_compare checks it
REPORT_FORMAT = 1

_seq = itertools.count(1)


def report_dir() -> Optional[str]:
    """The configured report directory (``MXTPU_RUN_REPORT_DIR``), or
    None when run reports are off."""
    d = str(env.get("MXTPU_RUN_REPORT_DIR") or "").strip()
    return d or None


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Linear-interpolated percentile over an ascending list."""
    if not sorted_vals:
        return 0.0
    if len(sorted_vals) == 1:
        return float(sorted_vals[0])
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return float(sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac)


def _step_time_summary(result) -> Optional[Dict[str, Any]]:
    """p50/p95/max over the per-step walls the step breakdown retained
    (bounded recent window — documented in the payload as ``window``),
    plus the full-run mean; falls back to the efficiency rollup's
    per-step walls when breakdown collection was off."""
    walls: List[float] = []
    window_src = None
    bd = getattr(result, "step_breakdown", None)
    if bd and bd.get("per_step"):
        walls = [float(r.get("wall", 0.0)) for r in bd["per_step"]
                 if r.get("wall")]
        window_src = "step_breakdown"
    if not walls:
        eff = getattr(result, "efficiency", None)
        if eff and eff.get("recent"):
            walls = [float(r.get("wall_s", 0.0)) for r in eff["recent"]
                     if r.get("wall_s")]
            window_src = "efficiency"
    if not walls:
        return None
    walls.sort()
    out = {
        "window": len(walls),
        "window_source": window_src,
        "p50_s": round(_percentile(walls, 0.50), 6),
        "p95_s": round(_percentile(walls, 0.95), 6),
        "max_s": round(walls[-1], 6),
    }
    if bd and bd.get("steps"):
        out["steps"] = int(bd["steps"])
        out["mean_s"] = float(bd.get("mean_step_s", 0.0))
    return out


def _loss_digest(losses: List[float]) -> Optional[Dict[str, Any]]:
    if not losses:
        return None
    rounded = [round(float(v), 6) for v in losses]
    # hash over a JSON-safe projection (NaN/inf -> string markers, so
    # bitwise-identical trajectories still hash equal and the digest
    # input is deterministic); the payload itself carries non-finite
    # values as None (RFC 8259 has no NaN token — _json_safe enforces
    # it for the whole report) plus an explicit count
    safe = [v if math.isfinite(v) else repr(v) for v in rounded]
    digest = hashlib.sha256(
        json.dumps(safe).encode()).hexdigest()[:16]
    finite = [v for v in rounded if math.isfinite(v)]
    return {
        "n": len(losses),
        "nonfinite": len(rounded) - len(finite),
        "first": rounded[0],
        "last": rounded[-1],
        "min": min(finite) if finite else None,
        "max": max(finite) if finite else None,
        "tail": rounded[-16:],
        "sha256_16": digest,
    }


def _json_safe(obj):
    """Replace non-finite floats with None everywhere in the payload:
    RFC 8259 JSON has no NaN/Infinity token, and the report is consumed
    by non-Python CI tooling (`jq`) that rejects the whole file on one
    bare ``NaN`` — exactly on the diverged runs the artifact exists to
    catch. Loss divergence stays visible via ``loss.nonfinite``."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    return obj


def _env_fingerprint() -> Dict[str, Any]:
    """Declared env knobs whose live value differs from the declared
    default — the configuration axes that distinguish two runs. A var
    merely SET to its default is not an override, and the report
    directory itself is never one (two runs reporting into different
    directories are not configured differently)."""
    overrides: Dict[str, Any] = {}
    for name, _typ, value, _doc in env.items():
        if name == "MXTPU_RUN_REPORT_DIR":
            continue
        if env.raw(name) is not None and value != env.default_for(name):
            overrides[name] = value
    fp: Dict[str, Any] = {"env_overrides": overrides}
    try:
        import jax
        fp["jax"] = jax.__version__
        fp["backend"] = jax.default_backend()
        fp["device_count"] = jax.device_count()
    except Exception:
        pass
    # topology identity: the (real or simulated) world size this run
    # trained at — run_compare flags a cross-topology comparison instead
    # of silently diffing an N-rank run against an M-rank one
    try:
        from ..parallel import elastic as _elastic
        fp["world_size"] = _elastic.world_for_fingerprint()
    except Exception:
        pass
    return fp


def build_payload(result, extra: Optional[dict] = None) -> Dict[str, Any]:
    """Assemble the report payload from a :class:`~mxnet_tpu.fit
    .FitResult` (any object with its attribute shape works)."""
    bd = getattr(result, "step_breakdown", None) or None
    mem = getattr(result, "memory", None) or None
    ch = getattr(result, "comm_health", None) or None
    num = getattr(result, "numerics", None) or None
    eff = getattr(result, "efficiency", None) or None
    payload: Dict[str, Any] = {
        "format": REPORT_FORMAT,
        "kind": "mxtpu_run_report",
        "time_unix": time.time(),
        "pid": os.getpid(),
        "fingerprint": _env_fingerprint(),
        "run": {
            "status": getattr(result, "status", None),
            "steps": int(getattr(result, "step", 0)),
            "epochs": int(getattr(result, "epoch", 0)),
            "resumed_from": getattr(result, "resumed_from", None),
            "skipped_steps": len(getattr(result, "skipped_steps", []) or []),
            "loss_scale": getattr(result, "loss_scale", None),
        },
        "step_time": _step_time_summary(result),
        "loss": _loss_digest(getattr(result, "losses", []) or []),
    }
    if bd:
        payload["breakdown"] = {
            "shares": bd.get("shares"),
            "accounted_frac": bd.get("accounted_frac"),
            "diagnoses": len(bd.get("diagnoses", [])),
            "actions": bd.get("actions") or {},
        }
    if mem:
        payload["memory"] = {
            "live_bytes": mem.get("live_bytes"),
            "peak_bytes": mem.get("peak_bytes"),
            "by_category": mem.get("by_category"),
        }
    if ch:
        payload["comm_health"] = {
            "max_skew_ms": ch.get("max_skew_ms"),
            "straggler_rank": ch.get("straggler_rank"),
            "desyncs": ch.get("desyncs", ch.get("desync")),
            "watchdog_fired": ch.get("watchdog_fired"),
            "ledger_dropped": ch.get("ledger_dropped"),
        }
    if num:
        payload["numerics"] = {
            "samples": num.get("samples"),
            "grad_norm": num.get("grad_norm"),
            "update_ratio": num.get("update_ratio"),
            "nonfinite_steps": len(num.get("nonfinite_steps", [])),
            "loss_scale_events": len(num.get("loss_scale_events", [])),
        }
    if eff:
        # the full rollup minus the bounded per-step window (the report
        # is a verdict, not a trace; run_compare reads the aggregates)
        payload["efficiency"] = {k: v for k, v in eff.items()
                                 if k != "recent"}
    if extra:
        payload["extra"] = dict(extra)
    return payload


def write_run_report(result, directory: Optional[str] = None,
                     extra: Optional[dict] = None) -> str:
    """Write one run report (tmp+rename) into ``directory`` (default
    ``MXTPU_RUN_REPORT_DIR``) and refresh the directory's shared
    SHA-256 manifest. Returns the report path."""
    d = directory or report_dir()
    if not d:
        raise ValueError(
            "write_run_report: no directory (set MXTPU_RUN_REPORT_DIR "
            "or pass directory=)")
    os.makedirs(d, exist_ok=True)
    payload = build_payload(result, extra=extra)
    ts = int(payload["time_unix"])
    path = os.path.join(d, f"run_{os.getpid()}_{ts}.json")
    while os.path.exists(path):
        # two fits inside one second in one process: disambiguate, never
        # overwrite an earlier run's verdict
        path = os.path.join(
            d, f"run_{os.getpid()}_{ts}_{next(_seq)}.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        # allow_nan=False enforces the _json_safe contract: a stray
        # non-finite float must fail HERE (and be fixed) rather than
        # ship an artifact strict parsers reject
        json.dump(_json_safe(payload), f, indent=1, default=str,
                  allow_nan=False)
    os.replace(tmp, path)
    try:
        from ..fault import write_manifest
        write_manifest(d)
    except Exception:
        pass  # the report itself landed; the manifest is best-effort
    try:
        from .registry import default_registry
        default_registry().counter(
            "mxtpu_run_reports_total",
            "Run reports written at fit end (MXTPU_RUN_REPORT_DIR).").inc()
    except Exception:
        pass
    return path


def build_serving_payload(metrics_json: Dict[str, Any],
                          extra: Optional[dict] = None) -> Dict[str, Any]:
    """Assemble a SERVING-mode report payload from a ModelServer's
    ``metrics_json()`` snapshot. Same kind/format as training reports
    (one reader, one compare tool); the verdict lives under a
    ``"serving"`` section instead of ``step_time``/``loss`` — QPS,
    latency percentiles, and shed counts are what a serving regression
    looks like (``tools/run_compare.py`` diffs them directioned)."""
    lat = (metrics_json.get("latency_ms") or {}).get("total") or {}
    rejected = metrics_json.get("rejected") or {}
    payload: Dict[str, Any] = {
        "format": REPORT_FORMAT,
        "kind": "mxtpu_run_report",
        "time_unix": time.time(),
        "pid": os.getpid(),
        "fingerprint": _env_fingerprint(),
        "run": {"status": "serving", "steps": 0, "epochs": 0},
        "serving": {
            "model": metrics_json.get("model"),
            "uptime_s": metrics_json.get("uptime_s"),
            "qps": metrics_json.get("throughput_rps"),
            "requests_total": metrics_json.get("requests_total"),
            "responses_total": metrics_json.get("responses_total"),
            "shed_total": int(sum(rejected.values())) if rejected else 0,
            "rejected": dict(rejected),
            "latency_ms": {
                "p50": lat.get("p50"),
                "p95": lat.get("p95"),
                "p99": lat.get("p99"),
                "mean": lat.get("mean"),
            },
            "queue_depth_peak": metrics_json.get("queue_depth_peak"),
            "batches_total": metrics_json.get("batches_total"),
            "mean_batch": (metrics_json.get("batch_size") or {}).get(
                "mean"),
        },
    }
    if extra:
        payload["extra"] = dict(extra)
    return payload


def write_serving_report(metrics_json: Dict[str, Any],
                         directory: Optional[str] = None,
                         extra: Optional[dict] = None) -> str:
    """Write one serving-mode run report (tmp+rename + manifest, the
    :func:`write_run_report` conventions). ``ModelServer.stop`` calls
    this automatically on drain when ``MXTPU_RUN_REPORT_DIR`` is set."""
    d = directory or report_dir()
    if not d:
        raise ValueError(
            "write_serving_report: no directory (set MXTPU_RUN_REPORT_DIR "
            "or pass directory=)")
    os.makedirs(d, exist_ok=True)
    payload = build_serving_payload(metrics_json, extra=extra)
    ts = int(payload["time_unix"])
    path = os.path.join(d, f"serve_{os.getpid()}_{ts}.json")
    while os.path.exists(path):
        path = os.path.join(
            d, f"serve_{os.getpid()}_{ts}_{next(_seq)}.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(_json_safe(payload), f, indent=1, default=str,
                  allow_nan=False)
    os.replace(tmp, path)
    try:
        from ..fault import write_manifest
        write_manifest(d)
    except Exception:
        pass
    try:
        from .registry import default_registry
        default_registry().counter(
            "mxtpu_run_reports_total",
            "Run reports written at fit end (MXTPU_RUN_REPORT_DIR).").inc()
    except Exception:
        pass
    return path


def load_run_report(path: str) -> Dict[str, Any]:
    """Load + format-check one report (the run_compare entry point)."""
    with open(path) as f:
        payload = json.load(f)
    if payload.get("kind") != "mxtpu_run_report":
        raise ValueError(f"{path}: not a run report (kind="
                         f"{payload.get('kind')!r})")
    if int(payload.get("format", -1)) > REPORT_FORMAT:
        raise ValueError(
            f"{path}: report format {payload.get('format')} is newer "
            f"than this reader ({REPORT_FORMAT})")
    return payload
