"""Structured span tracer (ref: src/profiler/profiler.h Profiler singleton).

Spans are recorded into a bounded ring buffer as plain dicts
``{name, cat, ts, dur, pid, tid, args}`` with ``ts``/``dur`` in microseconds
since tracer birth — the chrome trace-event "X" phase fields, so export is a
straight serialization (:mod:`.chrome_trace`). ``pid`` is the worker rank
(the reference tags profiler output per process; rank comes from
``MXTPU_WORKER_ID``), ``tid`` a dense per-thread id.

Overhead contract: when tracing is off, :func:`span` costs one attribute
check and returns a shared no-op context manager — no clock reads, no
allocation. The test-suite holds this to <1% on a tight step loop.

``MXTPU_PROFILE`` grammar (comma-separated tokens):

    MXTPU_PROFILE=on                         # everything, default ring
    MXTPU_PROFILE=1,ring=65536               # explicit ring capacity
    MXTPU_PROFILE=on,cat=comm|data_wait      # only these categories
    MXTPU_PROFILE=on,file=/tmp/trace.json    # atexit chrome-trace dump
    MXTPU_PROFILE=off                        # force off (same as unset)

Tokens: ``on``/``1``/``all`` | ``off``/``0`` | ``ring=<int>`` |
``cat=<c1>|<c2>|...`` | ``file=<path>``. Unknown tokens raise — a typo'd
profile request must not silently measure nothing.
"""
from __future__ import annotations

import atexit
import itertools
import os
import threading
import time
from collections import defaultdict, deque
from typing import Any, Dict, List, Optional

from ..base import MXNetError, env

__all__ = ["Tracer", "tracer", "span", "instant", "counter_event",
           "enabled", "configure", "enable", "disable"]

DEFAULT_RING = 65536


class _NoopSpan:
    """Shared do-nothing context manager for the tracing-off fast path."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NOOP = _NoopSpan()


class _Span:
    """One live span; records on exit."""
    __slots__ = ("_tr", "_name", "_cat", "_args", "_t0")

    def __init__(self, tr: "Tracer", name: str, cat: str,
                 args: Optional[dict]):
        self._tr = tr
        self._name = name
        self._cat = cat
        self._args = args
        self._t0 = time.perf_counter()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self._tr.record(self._name, self._cat, self._t0,
                        time.perf_counter(), self._args)
        return False


class Tracer:
    """Thread-safe span recorder with a bounded ring buffer."""

    def __init__(self, ring: int = DEFAULT_RING,
                 rank: Optional[int] = None):
        self._lock = threading.Lock()
        self._on = False
        self._paused = False
        self._categories: Optional[set] = None   # None = all
        self._ring = int(ring)
        self._spans: deque = deque(maxlen=self._ring)
        # perf_counter <-> epoch wall-clock anchor, captured at ONE
        # instant: trace ts 0 corresponds to epoch second _epoch0. The
        # chrome exporter ships it as process metadata ("clock_sync"),
        # which is what lets tools/fleet_trace.py align N per-rank
        # traces onto one clock.
        self._t0 = time.perf_counter()
        self._epoch0 = time.time()
        #: this rank's wall clock minus rank 0's, in ms (set by
        #: telemetry.collective.sync_clocks after the median-of-K
        #: round-trip handshake; 0.0 = unmeasured / reference rank)
        self.clock_offset_ms = 0.0
        self._rank = rank
        self._tids: Dict[int, int] = {}
        self._tid_counter = itertools.count()
        self._dropped = 0
        # aggregate stats (cat::name -> [count, total_ms, min_ms, max_ms]);
        # unbounded by design: the table is O(distinct names), not O(spans)
        self._agg: Dict[str, List[float]] = defaultdict(
            lambda: [0, 0.0, float("inf"), 0.0])
        self._aggregate = False
        self._file: Optional[str] = None

    # -- state ----------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._on and not self._paused

    @property
    def rank(self) -> int:
        if self._rank is None:
            self._rank = int(env.get("MXTPU_WORKER_ID"))
        return self._rank

    @property
    def epoch_anchor(self) -> float:
        """Epoch seconds at trace ts 0 (the wall-clock anchor)."""
        return self._epoch0

    @property
    def ring_capacity(self) -> int:
        return self._ring

    @property
    def dropped(self) -> int:
        """Spans evicted from the ring since the last clear()."""
        return self._dropped

    def enable(self) -> None:
        self._on = True
        self._paused = False

    def disable(self) -> None:
        self._on = False

    def pause(self) -> None:
        self._paused = True

    def resume(self) -> None:
        self._paused = False

    def set_aggregate(self, on: bool) -> None:
        self._aggregate = bool(on)

    def set_categories(self, cats: Optional[set]) -> None:
        self._categories = set(cats) if cats else None

    def set_ring(self, n: int) -> None:
        n = int(n)
        if n < 1:
            raise MXNetError(f"tracer ring capacity must be >= 1, got {n}")
        with self._lock:
            self._ring = n
            self._spans = deque(self._spans, maxlen=n)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._agg.clear()
            self._dropped = 0

    # -- recording ------------------------------------------------------
    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            # racy double-assign is harmless (same ident -> same dict slot)
            tid = self._tids[ident] = next(self._tid_counter)
        return tid

    def wants(self, category: str) -> bool:
        return self.enabled and (self._categories is None or
                                 category in self._categories)

    def span(self, name: str, category: str, args: Optional[dict] = None):
        """Context manager timing one span. The off path allocates
        nothing and never reads the clock."""
        if not self._on or self._paused or (
                self._categories is not None and
                category not in self._categories):
            return _NOOP
        return _Span(self, name, category, args)

    def record(self, name: str, category: str, t_start: float,
               t_end: float, args: Optional[dict] = None) -> None:
        """Record one completed span from perf_counter timestamps."""
        if not self.wants(category):
            return
        ev = {"name": name, "cat": category,
              "ts": (t_start - self._t0) * 1e6,
              "dur": max(t_end - t_start, 0.0) * 1e6,
              "pid": self.rank, "tid": self._tid()}
        if args:
            ev["args"] = args
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self._dropped += 1
            self._spans.append(ev)
            if self._aggregate:
                a = self._agg[f"{category}::{name}"]
                ms = (t_end - t_start) * 1e3
                a[0] += 1
                a[1] += ms
                a[2] = min(a[2], ms)
                a[3] = max(a[3], ms)

    def instant(self, name: str, category: str = "marker") -> None:
        """Instant event (chrome 'i' phase)."""
        if not self.wants(category):
            return
        ev = {"name": name, "cat": category, "ph": "i",
              "ts": (time.perf_counter() - self._t0) * 1e6,
              "pid": self.rank, "tid": self._tid(), "s": "t"}
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self._dropped += 1
            self._spans.append(ev)

    def counter_event(self, name: str, value,
                      category: str = "counter") -> None:
        """Counter sample (chrome 'C' phase -> stacked area in Perfetto).

        ``value`` may be a single number ({"value": v}) or a mapping of
        series name -> number — Perfetto renders a multi-key args object
        as one stacked counter track (the memory ledger's per-category
        track uses this)."""
        if not self.wants(category):
            return
        if isinstance(value, dict):
            args = {str(k): float(v) for k, v in value.items()}
        else:
            args = {"value": float(value)}
        ev = {"name": name, "cat": category, "ph": "C",
              "ts": (time.perf_counter() - self._t0) * 1e6,
              "pid": self.rank, "tid": self._tid(),
              "args": args}
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self._dropped += 1
            self._spans.append(ev)

    # -- inspection -----------------------------------------------------
    def events(self, category: Optional[str] = None) -> List[Dict[str, Any]]:
        """Snapshot of recorded events (copies — safe to mutate)."""
        with self._lock:
            evs = [dict(e) for e in self._spans]
        if category is None:
            return evs
        return [e for e in evs if e.get("cat") == category]

    def thread_names(self) -> Dict[int, str]:
        """tid -> thread name, for chrome metadata events."""
        by_ident = {t.ident: t.name for t in threading.enumerate()}
        return {tid: by_ident.get(ident, f"thread-{tid}")
                for ident, tid in dict(self._tids).items()}

    def aggregate_table(self, reset: bool = False) -> str:
        """Aggregate stats table (ref: AggregateStats dump, profiler.h)."""
        with self._lock:
            rows = sorted(self._agg.items(), key=lambda kv: -kv[1][1])
            lines = [f"{'Name':<50}{'Calls':>8}{'Total(ms)':>12}"
                     f"{'Avg(ms)':>10}{'Min':>10}{'Max':>10}"]
            for name, (count, total, mn, mx) in rows:
                lines.append(f"{name[:50]:<50}{int(count):>8}"
                             f"{total:>12.3f}{total / count:>10.3f}"
                             f"{mn:>10.3f}{mx:>10.3f}")
            if reset:
                self._agg.clear()
        return "\n".join(lines)

    # -- env grammar ----------------------------------------------------
    def configure(self, spec: str) -> None:
        """Apply one MXTPU_PROFILE spec string (see module docstring).

        A spec made only of modifiers (``file=...``, ``cat=...``) implies
        ``on`` — asking for a trace file and getting silence would be the
        silent-measure-nothing failure this grammar exists to prevent."""
        want_on = None
        saw_modifier = False
        for tok in (spec or "").split(","):
            tok = tok.strip()
            if not tok:
                continue
            low = tok.lower()
            if low in ("on", "1", "true", "all"):
                want_on = True
            elif low in ("off", "0", "false"):
                want_on = False
            elif "=" in tok:
                saw_modifier = True
                key, _, val = tok.partition("=")
                key = key.strip().lower()
                val = val.strip()
                if key == "ring":
                    try:
                        self.set_ring(int(val))
                    except ValueError:
                        raise MXNetError(
                            f"MXTPU_PROFILE: ring={val!r} is not an int")
                elif key == "cat":
                    cats = {c.strip() for c in val.split("|") if c.strip()}
                    if not cats:
                        raise MXNetError(
                            "MXTPU_PROFILE: cat= needs at least one "
                            "category, e.g. cat=comm|data_wait")
                    self.set_categories(cats)
                elif key == "file":
                    if not val:
                        raise MXNetError("MXTPU_PROFILE: file= needs a path")
                    self._file = val
                else:
                    raise MXNetError(
                        f"MXTPU_PROFILE: unknown key {key!r} "
                        "(known: ring, cat, file)")
            else:
                raise MXNetError(
                    f"MXTPU_PROFILE: unknown token {tok!r} (known: on, "
                    "off, ring=N, cat=a|b, file=PATH)")
        if want_on is False:
            self.disable()
        elif want_on or saw_modifier:
            self.enable()
            if self._file is not None:
                _register_atexit_dump(self)


# -- module-level singleton + convenience functions -------------------------

tracer = Tracer()

_atexit_registered = False


def _register_atexit_dump(tr: Tracer) -> None:
    global _atexit_registered
    if _atexit_registered:
        return
    _atexit_registered = True

    def _dump():
        if tr._file:
            from .chrome_trace import dump_chrome_trace
            try:
                dump_chrome_trace(tr._file, tracer=tr)
            except Exception:
                pass
    atexit.register(_dump)


def span(name: str, category: str, args: Optional[dict] = None):
    return tracer.span(name, category, args)


def instant(name: str, category: str = "marker") -> None:
    tracer.instant(name, category)


def counter_event(name: str, value,
                  category: str = "counter") -> None:
    tracer.counter_event(name, value, category)


def enabled() -> bool:
    return tracer.enabled


def enable() -> None:
    tracer.enable()


def disable() -> None:
    tracer.disable()


def configure(spec: str) -> None:
    tracer.configure(spec)


_env_spec = env.get("MXTPU_PROFILE")
if _env_spec:
    tracer.configure(_env_spec)
