"""``mx.nd.image`` operators.

Reference: src/operator/image/image_random.cc (to_tensor, normalize, flips,
random brightness/contrast/saturation/hue, color jitter, lighting) and
src/operator/image/resize.cc (_image_resize). The reference draws its
per-call randomness from the engine's PRNG resource
(include/mxnet/resource.h kRandom); here random_* ops are pure functions of
an explicit key split from the global ``mx.random`` stream (rng=True),
reproducible under jit by construction.

All ops accept HWC images or NHWC batches (the reference's 1.5-dev image
ops are HWC-only; batch support matches later upstream and costs nothing
under vmap-free broadcasting).
"""
from __future__ import annotations

import numpy as _np

from .registry import register

# ITU-R BT.601 luma coefficients (ref: image_random-inl.h AdjustSaturationImpl)
_GRAY_COEF = (0.299, 0.587, 0.114)
# AlexNet PCA lighting basis (ref: image_random-inl.h AdjustLightingImpl /
# python RandomLighting defaults)
_EIGVAL = _np.array([55.46, 4.794, 1.148], _np.float32)
_EIGVEC = _np.array([[-0.5675, 0.7192, 0.4009],
                     [-0.5808, -0.0045, -0.8140],
                     [-0.5836, -0.6948, 0.4203]], _np.float32)


def _jnp():
    import jax.numpy as jnp
    return jnp


def _jr():
    import jax.random as jr
    return jr


def _saturate(x, dtype):
    """saturate_cast<DType>: clamp to the integer range for int dtypes."""
    jnp = _jnp()
    dt = _np.dtype(dtype)
    if dt.kind in "ui":
        info = _np.iinfo(dt)
        x = jnp.clip(jnp.rint(x), info.min, info.max)
    return x.astype(dt)


@register("_image_to_tensor", differentiable=False)
def _image_to_tensor(data, **_):
    """HWC [0,255] -> CHW float32 [0,1] (ref: image_random.cc ToTensor)."""
    jnp = _jnp()
    x = data.astype(jnp.float32) / 255.0
    if x.ndim == 3:
        return jnp.transpose(x, (2, 0, 1))
    return jnp.transpose(x, (0, 3, 1, 2))


@register("_image_normalize")
def _image_normalize(data, mean=(0.0,), std=(1.0,), **_):
    """(x - mean) / std per channel on CHW/NCHW float input
    (ref: image_random.cc Normalize)."""
    jnp = _jnp()
    mean = _np.asarray(mean, _np.float32).reshape(-1, 1, 1)
    std = _np.asarray(std, _np.float32).reshape(-1, 1, 1)
    return (data - jnp.asarray(mean)) / jnp.asarray(std)


@register("_image_resize", differentiable=False)
def _image_resize(data, size=(), keep_ratio=False, interp=1, **_):
    """HWC/NHWC resize (ref: resize.cc). size = (), int, or (w, h)."""
    import jax
    jnp = _jnp()
    method = {0: "nearest", 1: "bilinear", 2: "bicubic",
              3: "bicubic", 4: "bicubic"}.get(int(interp), "bilinear")
    batched = data.ndim == 4
    H, W = (data.shape[1], data.shape[2]) if batched else \
        (data.shape[0], data.shape[1])
    if isinstance(size, (int, _np.integer)):
        size = (size,)
    size = tuple(int(s) for s in size)
    if len(size) == 0:
        new_h, new_w = H, W
    elif len(size) == 1:
        if keep_ratio:  # resize short edge to `size`
            if H < W:
                new_h, new_w = size[0], max(1, round(W * size[0] / H))
            else:
                new_h, new_w = max(1, round(H * size[0] / W)), size[0]
        else:
            new_h = new_w = size[0]
    else:
        new_w, new_h = size[0], size[1]
    shape = ((data.shape[0], new_h, new_w, data.shape[3]) if batched
             else (new_h, new_w, data.shape[2]))
    out = jax.image.resize(data.astype(jnp.float32), shape, method)
    return _saturate(out, data.dtype)


def _flip(data, axis_from_last):
    # HWC: W is axis -2, H is axis -3; works for NHWC too.
    return _jnp().flip(data, axis=data.ndim + axis_from_last)


@register("_image_flip_left_right",
          differentiable=False)
def _image_flip_left_right(data, **_):
    return _flip(data, -2)


@register("_image_flip_top_bottom",
          differentiable=False)
def _image_flip_top_bottom(data, **_):
    return _flip(data, -3)


def _random_flip(data, key, axis_from_last):
    jnp = _jnp()
    coin = _jr().bernoulli(key, 0.5)
    return jnp.where(coin, _flip(data, axis_from_last), data)


@register("_image_random_flip_left_right", rng=True,
          differentiable=False)
def _image_random_flip_left_right(data, _key, **_):
    return _random_flip(data, _key, -2)


@register("_image_random_flip_top_bottom", rng=True,
          differentiable=False)
def _image_random_flip_top_bottom(data, _key, **_):
    return _random_flip(data, _key, -3)


def _adjust_brightness(x, alpha, dtype):
    return _saturate(x * alpha, dtype)


def _adjust_contrast(x, alpha, dtype):
    jnp = _jnp()
    # per-image gray mean: reduce H, W (and C) but keep the batch axis so
    # NHWC batches don't mix statistics across images
    spatial = tuple(range(x.ndim - 3, x.ndim - 1))
    if x.shape[-1] == 3:
        coef = jnp.asarray(_GRAY_COEF, jnp.float32)
        gray = jnp.tensordot(x, coef, axes=([-1], [0]))
        gray_mean = jnp.mean(gray, axis=spatial, keepdims=True)[..., None]
    else:
        gray_mean = jnp.mean(x, axis=spatial + (x.ndim - 1,), keepdims=True)
    return _saturate(x * alpha + (1.0 - alpha) * gray_mean, dtype)


def _adjust_saturation(x, alpha, dtype):
    jnp = _jnp()
    if x.shape[-1] != 3:
        return _saturate(x, dtype)
    coef = jnp.asarray(_GRAY_COEF, jnp.float32)
    gray = jnp.tensordot(x, coef, axes=([-1], [0]))[..., None]
    return _saturate(x * alpha + (1.0 - alpha) * gray, dtype)


def _rgb_to_hls(rgb):
    """Vectorised RGB2HLSConvert (ref: image_random-inl.h:783-822)."""
    jnp = _jnp()
    x = rgb / 255.0
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    vmax = jnp.maximum(jnp.maximum(r, g), b)
    vmin = jnp.minimum(jnp.minimum(r, g), b)
    diff = vmax - vmin
    l = (vmax + vmin) * 0.5
    eps = _np.finfo(_np.float32).eps
    safe = diff > eps
    denom = jnp.where(l < 0.5, vmax + vmin, 2.0 - vmax - vmin)
    s = jnp.where(safe, diff / jnp.maximum(denom, eps), 0.0)
    d = 60.0 / jnp.maximum(diff, eps)
    h = jnp.where(vmax == r, (g - b) * d,
                  jnp.where(vmax == g, (b - r) * d + 120.0,
                            (r - g) * d + 240.0))
    h = jnp.where(h < 0, h + 360.0, h)
    h = jnp.where(safe, h, 0.0)
    return h, l, s


def _hls_to_rgb(h, l, s):
    """Vectorised HLS2RGBConvert (ref: image_random-inl.h:824-879)."""
    jnp = _jnp()
    p2 = jnp.where(l <= 0.5, l * (1 + s), l + s - l * s)
    p1 = 2 * l - p2
    hh = jnp.mod(h / 60.0, 6.0)
    sector = jnp.floor(hh).astype(_np.int32)
    frac = hh - sector
    t_up = p1 + (p2 - p1) * frac          # rising edge
    t_down = p1 + (p2 - p1) * (1 - frac)  # falling edge
    # per-sector (r, g, b) from {p1, p2, t_up, t_down}
    def sel(table):
        jnp_ = _jnp()
        out = table[0]
        for i in range(1, 6):
            out = jnp_.where(sector == i, table[i], out)
        return out
    r = sel([p2, t_down, p1, p1, t_up, p2])
    g = sel([t_up, p2, p2, t_down, p1, p1])
    b = sel([p1, p1, t_up, p2, p2, t_down])
    gray = jnp.broadcast_to(l, r.shape)
    mask = s != 0
    r = jnp.where(mask, r, gray)
    g = jnp.where(mask, g, gray)
    b = jnp.where(mask, b, gray)
    return jnp.stack([r * 255.0, g * 255.0, b * 255.0], axis=-1)


def _adjust_hue(x, alpha, dtype):
    jnp = _jnp()
    if x.shape[-1] != 3:
        return _saturate(x, dtype)
    h, l, s = _rgb_to_hls(x.astype(jnp.float32))
    out = _hls_to_rgb(h + alpha * 360.0, l, s)
    return _saturate(out, dtype)


def _uniform_factor(key, min_factor, max_factor):
    return _jr().uniform(key, (), _np.float32, float(min_factor),
                         float(max_factor))


@register("_image_random_brightness",
          rng=True, differentiable=False)
def _image_random_brightness(data, _key, min_factor=0.0, max_factor=0.0, **_):
    jnp = _jnp()
    alpha = _uniform_factor(_key, min_factor, max_factor)
    return _adjust_brightness(data.astype(jnp.float32), alpha, data.dtype)


@register("_image_random_contrast",
          rng=True, differentiable=False)
def _image_random_contrast(data, _key, min_factor=0.0, max_factor=0.0, **_):
    jnp = _jnp()
    alpha = _uniform_factor(_key, min_factor, max_factor)
    return _adjust_contrast(data.astype(jnp.float32), alpha, data.dtype)


@register("_image_random_saturation",
          rng=True, differentiable=False)
def _image_random_saturation(data, _key, min_factor=0.0, max_factor=0.0, **_):
    jnp = _jnp()
    alpha = _uniform_factor(_key, min_factor, max_factor)
    return _adjust_saturation(data.astype(jnp.float32), alpha, data.dtype)


@register("_image_random_hue", rng=True,
          differentiable=False)
def _image_random_hue(data, _key, min_factor=0.0, max_factor=0.0, **_):
    alpha = _uniform_factor(_key, min_factor, max_factor)
    return _adjust_hue(data, alpha, data.dtype)


@register("_image_random_color_jitter",
          rng=True, differentiable=False)
def _image_random_color_jitter(data, _key, brightness=0.0, contrast=0.0,
                               saturation=0.0, hue=0.0, **_):
    """Apply the four jitters in a random order
    (ref: image_random.cc RandomColorJitter)."""
    jr, jnp = _jr(), _jnp()
    keys = jr.split(_key, 5)
    x = data.astype(jnp.float32)
    dtype = data.dtype
    # Random order via random priorities is data-dependent; the reference
    # shuffles op order on the host. Use a fixed traced order but randomly
    # sampled factors — statistically equivalent jitter strength.
    if brightness > 0:
        a = _uniform_factor(keys[0], max(0.0, 1 - brightness), 1 + brightness)
        x = _adjust_brightness(x, a, jnp.float32)
    if contrast > 0:
        a = _uniform_factor(keys[1], max(0.0, 1 - contrast), 1 + contrast)
        x = _adjust_contrast(x, a, jnp.float32)
    if saturation > 0:
        a = _uniform_factor(keys[2], max(0.0, 1 - saturation), 1 + saturation)
        x = _adjust_saturation(x, a, jnp.float32)
    if hue > 0:
        a = _uniform_factor(keys[3], -hue, hue)
        x = _adjust_hue(x, a, jnp.float32)
    return _saturate(x, dtype)


@register("_image_adjust_lighting",
          differentiable=False)
def _image_adjust_lighting(data, alpha=(0.0, 0.0, 0.0), **_):
    """PCA lighting with fixed alphas (ref: image_random.cc AdjustLighting)."""
    jnp = _jnp()
    alpha = _np.asarray(alpha, _np.float32)
    rgb = _EIGVEC @ (alpha * _EIGVAL)
    return _saturate(data.astype(jnp.float32) + jnp.asarray(rgb), data.dtype)


@register("_image_random_lighting", rng=True,
          differentiable=False)
def _image_random_lighting(data, _key, alpha_std=0.05, **_):
    """PCA lighting with alpha ~ N(0, alpha_std)
    (ref: image_random.cc RandomLighting)."""
    jnp = _jnp()
    alpha = _jr().normal(_key, (3,), _np.float32) * float(alpha_std)
    rgb = jnp.asarray(_EIGVEC) @ (alpha * jnp.asarray(_EIGVAL))
    return _saturate(data.astype(jnp.float32) + rgb, data.dtype)
