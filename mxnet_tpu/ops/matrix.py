"""Shape manipulation, joining/splitting, indexing, and matrix products.

Reference: src/operator/tensor/matrix_op.cc (reshape/transpose/slice/...),
indexing_op.cc (take/gather/scatter/one_hot), dot-inl.h (dot/batch_dot),
init_op.cc (*_like). The reference's reshape "magic codes" (0, -1, -2, -3,
-4) are reimplemented exactly since Gluon layers and serialized symbols rely
on them.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from .registry import register, alias


def _jnp():
    import jax.numpy as jnp
    return jnp


def _lax():
    import jax.lax as lax
    return lax


def mx_reshape_shape(src_shape, target):
    """Reference reshape semantics (src/operator/tensor/matrix_op-inl.h):
    0 copy input dim; -1 infer; -2 copy all remaining; -3 merge two dims;
    -4 split one dim into the next two values."""
    target = list(target)
    out = []
    i = 0  # index into src
    j = 0  # index into target
    while j < len(target):
        t = target[j]
        if t > 0:
            out.append(t)
            i += 1
        elif t == 0:
            out.append(src_shape[i])
            i += 1
        elif t == -1:
            out.append(-1)
            i += 1
        elif t == -2:
            out.extend(src_shape[i:])
            i = len(src_shape)
        elif t == -3:
            out.append(src_shape[i] * src_shape[i + 1])
            i += 2
        elif t == -4:
            d1, d2 = target[j + 1], target[j + 2]
            if d1 == -1:
                d1 = src_shape[i] // d2
            if d2 == -1:
                d2 = src_shape[i] // d1
            out.extend([d1, d2])
            i += 1
            j += 2
        else:
            raise MXNetError(f"invalid reshape code {t}")
        j += 1
    # resolve a single -1
    if out.count(-1) > 1:
        raise MXNetError("reshape can infer at most one dimension")
    return tuple(out)


@register("reshape", aliases=("Reshape",))
def _reshape(x, shape=(), reverse: bool = False, **_):
    shp = mx_reshape_shape(x.shape, tuple(shape))
    return x.reshape(shp)


@register("reshape_like")
def _reshape_like(x, y, **_):
    return x.reshape(y.shape)


@register("flatten", aliases=("Flatten",))
def _flatten(x):
    n = 1
    for s in x.shape[1:]:
        n *= s
    return x.reshape((x.shape[0], n))


@register("transpose")
def _transpose(x, axes=()):
    jnp = _jnp()
    return jnp.transpose(x, tuple(axes) if axes else None)


@register("expand_dims")
def _expand_dims(x, axis=0):
    return _jnp().expand_dims(x, axis)


@register("squeeze")
def _squeeze(x, axis=None):
    return _jnp().squeeze(x, axis if axis is None or isinstance(axis, int)
                          else tuple(axis))


@register("swapaxes", aliases=("SwapAxis",))
def _swapaxes(x, dim1=0, dim2=0):
    return _jnp().swapaxes(x, dim1, dim2)


@register("moveaxis")
def _moveaxis(x, source=0, destination=0):
    return _jnp().moveaxis(x, source, destination)


@register("slice", aliases=("crop",))
def _slice(x, begin=(), end=(), step=()):
    slices = []
    step = step or (None,) * len(begin)
    for b, e, s in zip(begin, end, step):
        slices.append(slice(b, e, s))
    return x[tuple(slices)]


@register("slice_axis")
def _slice_axis(x, axis=0, begin=0, end=None):
    idx = [slice(None)] * x.ndim
    idx[axis] = slice(begin, end)
    return x[tuple(idx)]


@register("slice_like")
def _slice_like(x, y, axes=()):
    ax = tuple(axes) if axes else tuple(range(min(x.ndim, y.ndim)))
    idx = [slice(None)] * x.ndim
    for a in ax:
        idx[a] = slice(0, y.shape[a])
    return x[tuple(idx)]


@register("flip", aliases=("reverse",))
def _flip(x, axis=0):
    return _jnp().flip(x, axis if isinstance(axis, int) else tuple(axis))


@register("tile")
def _tile(x, reps=()):
    return _jnp().tile(x, tuple(reps))


@register("repeat")
def _repeat(x, repeats=1, axis=None):
    return _jnp().repeat(x, repeats, axis=axis)


@register("Pad", aliases=("pad",))
def _pad(x, mode="constant", pad_width=(), constant_value=0.0):
    jnp = _jnp()
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)]
    if mode == "constant":
        return jnp.pad(x, pw, mode="constant", constant_values=constant_value)
    if mode == "edge":
        return jnp.pad(x, pw, mode="edge")
    if mode == "reflect":
        return jnp.pad(x, pw, mode="reflect")
    raise MXNetError(f"unsupported pad mode {mode}")


@register("clip")
def _clip(x, a_min=0.0, a_max=0.0):
    return _jnp().clip(x, a_min, a_max)


@register("concat", aliases=("Concat",), variadic=True)
def _concat(*xs, dim=1, num_args=None):
    return _jnp().concatenate(xs, axis=dim)


@register("stack", variadic=True)
def _stack(*xs, axis=0, num_args=None):
    return _jnp().stack(xs, axis=axis)


def _split_outputs(n_inputs, params):
    return int(params.get("num_outputs", 1))


@register("split", aliases=("SliceChannel",), num_outputs=_split_outputs)
def _split(x, num_outputs=1, axis=1, squeeze_axis=False):
    jnp = _jnp()
    parts = jnp.split(x, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if num_outputs > 1 else parts[0]


@register("take")
def _take(x, indices, axis=0, mode="clip"):
    jnp = _jnp()
    idx = indices.astype(_np.int32)
    n = x.shape[axis]
    if mode == "clip":
        idx = jnp.clip(idx, 0, n - 1)
    elif mode == "wrap":
        idx = jnp.mod(idx, n)
    return jnp.take(x, idx, axis=axis)


@register("pick")
def _pick(x, index, axis=-1, keepdims=False, mode="clip"):
    jnp = _jnp()
    ax = axis % x.ndim
    idx = jnp.clip(index.astype(_np.int32), 0, x.shape[ax] - 1)
    idx_e = jnp.expand_dims(idx, ax)
    out = jnp.take_along_axis(x, idx_e, axis=ax)
    return out if keepdims else jnp.squeeze(out, axis=ax)


@register("gather_nd")
def _gather_nd(x, indices):
    idx = tuple(indices.astype(_np.int32))
    return x[idx]


@register("scatter_nd")
def _scatter_nd(data, indices, shape=()):
    jnp = _jnp()
    out = jnp.zeros(tuple(shape), dtype=data.dtype)
    idx = tuple(indices.astype(_np.int32))
    return out.at[idx].set(data)


@register("_scatter_set_nd")
def _scatter_set_nd(lhs, data, indices, shape=()):
    idx = tuple(indices.astype(_np.int32))
    return lhs.at[idx].set(data)


@register("one_hot")
def _one_hot(indices, depth=1, on_value=1.0, off_value=0.0, dtype="float32"):
    jnp = _jnp()
    ind = indices.astype(_np.int32)
    oh = jnp.equal(jnp.expand_dims(ind, -1),
                   jnp.arange(depth, dtype=_np.int32))
    d = jnp.bfloat16 if dtype == "bfloat16" else _np.dtype(dtype)
    return jnp.where(oh, on_value, off_value).astype(d)


@register("where")
def _where(cond, a, b):
    return _jnp().where(cond != 0, a, b)


@register("depth_to_space")
def _depth_to_space(x, block_size=1):
    jnp = _jnp()
    n, c, h, w = x.shape
    b = block_size
    y = x.reshape((n, b, b, c // (b * b), h, w))
    y = jnp.transpose(y, (0, 3, 4, 1, 5, 2))
    return y.reshape((n, c // (b * b), h * b, w * b))


@register("space_to_depth")
def _space_to_depth(x, block_size=1):
    jnp = _jnp()
    n, c, h, w = x.shape
    b = block_size
    y = x.reshape((n, c, h // b, b, w // b, b))
    y = jnp.transpose(y, (0, 3, 5, 1, 2, 4))
    return y.reshape((n, c * b * b, h // b, w // b))


@register("diag")
def _diag(x, k=0, axis1=0, axis2=1):
    jnp = _jnp()
    if x.ndim == 1:
        return jnp.diag(x, k)
    return jnp.diagonal(x, offset=k, axis1=axis1, axis2=axis2)


@register("ravel_multi_index", differentiable=False)
def _ravel_multi_index(data, shape=()):
    jnp = _jnp()
    strides = []
    acc = 1
    for s in reversed(shape):
        strides.append(acc)
        acc *= s
    strides = jnp.array(list(reversed(strides)), dtype=data.dtype)
    return jnp.sum(data * strides[:, None], axis=0)


@register("unravel_index", differentiable=False)
def _unravel_index(data, shape=()):
    jnp = _jnp()
    out = []
    rem = data.astype(_np.int64)
    for s in reversed(shape):
        out.append(rem % s)
        rem = rem // s
    return jnp.stack(list(reversed(out)), axis=0).astype(data.dtype)


# ---------------------------------------------------------------------------
# products — the MXU path. Accumulate in f32 via preferred_element_type when
# inputs are bf16 (TPU-first: keep the systolic array fed, accumulate wide).
# ---------------------------------------------------------------------------

@register("dot")
def _dot(a, b, transpose_a=False, transpose_b=False, forward_stype=None):
    jnp = _jnp()
    x = a.T if transpose_a and a.ndim == 2 else (
        jnp.transpose(a) if transpose_a else a)
    y = b.T if transpose_b and b.ndim == 2 else (
        jnp.transpose(b) if transpose_b else b)
    if x.ndim == 1 and y.ndim == 1:
        return jnp.dot(x, y)
    # reference dot on >2d: contract last axis of a with first axis of b
    return jnp.tensordot(x, y, axes=([x.ndim - 1], [0]))


@register("batch_dot")
def _batch_dot(a, b, transpose_a=False, transpose_b=False, forward_stype=None):
    jnp = _jnp()
    x = jnp.swapaxes(a, -1, -2) if transpose_a else a
    y = jnp.swapaxes(b, -1, -2) if transpose_b else b
    return jnp.matmul(x, y)


@register("matmul")
def _matmul(a, b):
    """numpy/ONNX matmul semantics (batched over leading axes) — the
    target for ONNX MatMul import, which is NOT the reference's tensordot
    'dot' on >2-D inputs."""
    return _jnp().matmul(a, b)


@register("khatri_rao", variadic=True)
def _khatri_rao(*mats):
    """Column-wise Kronecker product (ref: src/operator/contrib/krprod.cc)."""
    out = mats[0]
    for m in mats[1:]:
        out = (out[:, None, :] * m[None, :, :]).reshape((-1, m.shape[1]))
    return out


@register("batch_take")
def _batch_take(a, indices):
    """out[i] = a[i, indices[i]] (ref: tensor/indexing_op.cc batch_take)."""
    jnp = _jnp()
    idx = indices.reshape(-1).astype(jnp.int32)
    rows = jnp.arange(a.shape[0])
    return a[rows, idx]
