"""Correlation, deformable convolution, FFT, and count-sketch ops.

Reference: src/operator/correlation.cc (FlowNet correlation),
src/operator/contrib/deformable_convolution.cc + nn/deformable_im2col.cuh,
src/operator/contrib/fft.cc / ifft.cc (cuFFT C2C, unnormalized),
src/operator/contrib/count_sketch.cc.

TPU redesign notes:
- Correlation: per-displacement shifted products reduced with
  lax.reduce_window — one fused XLA computation, vmapped over the
  displacement grid instead of the reference's per-output-pixel CUDA loop.
- DeformableConvolution: the reference's deformable_im2col gather +
  GEMM becomes bilinear gather (XLA gather) + einsum on the MXU.
- fft/ifft: jnp.fft (XLA FFT HLO) with the reference's interleaved
  real/imag layout and cuFFT's unnormalized scaling convention.
- count_sketch: scatter-add (.at[].add) replaces the atomic-add kernel.
"""
from __future__ import annotations

import math

from ..base import check
from .registry import register


def _jnp():
    import jax.numpy as jnp
    return jnp


def _lax():
    from jax import lax
    return lax


# ---------------------------------------------------------------------------
# Correlation (ref: src/operator/correlation.cc:41-81 CorrelationForward)
# ---------------------------------------------------------------------------

@register("Correlation")
def _correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                 stride2=1, pad_size=0, is_multiply=True):
    """FlowNet correlation layer. data1/data2: (N, C, H, W) ->
    (N, D*D, top_h, top_w) with D = 2*(max_displacement//stride2)+1."""
    import jax
    jnp = _jnp()
    lax = _lax()
    kernel_size = int(kernel_size)
    max_displacement = int(max_displacement)
    stride1, stride2, pad_size = int(stride1), int(stride2), int(pad_size)
    check(kernel_size % 2 == 1, "kernel_size should be odd number")
    N, C, H, W = data1.shape
    kr = (kernel_size - 1) // 2
    border = max_displacement + kr
    Hp, Wp = H + 2 * pad_size, W + 2 * pad_size
    top_h = int(math.ceil(float(Hp - 2 * border) / stride1))
    top_w = int(math.ceil(float(Wp - 2 * border) / stride1))
    check(top_h >= 1 and top_w >= 1,
          "Correlation: input too small for given displacement/kernel")
    r = max_displacement // stride2
    gw = 2 * r + 1

    pad4 = ((0, 0), (0, 0), (pad_size, pad_size), (pad_size, pad_size))
    p1 = jnp.pad(data1, pad4)
    p2 = jnp.pad(data2, pad4)
    # extra max_displacement halo so every shifted view is a static-size
    # slice of one buffer
    halo = ((0, 0), (0, 0), (max_displacement, max_displacement),
            (max_displacement, max_displacement))
    p2big = jnp.pad(p2, halo)

    # displacement grid in the reference's channel order: rows (s2p) outer,
    # cols (s2o) inner (correlation.cc:63-66)
    disp = jnp.asarray([((dy - r) * stride2, (dx - r) * stride2)
                        for dy in range(gw) for dx in range(gw)],
                       dtype=jnp.int32)

    def one(off):
        dy, dx = off[0], off[1]
        shifted = lax.dynamic_slice(
            p2big, (0, 0, max_displacement + dy, max_displacement + dx),
            (N, C, Hp, Wp))
        prod = p1 * shifted if is_multiply else jnp.abs(p1 - shifted)
        csum = jnp.sum(prod, axis=1)          # (N, Hp, Wp)
        # window top-left for output (i, j) is (i*s1 + md, j*s1 + md)
        win = lax.reduce_window(
            csum[:, max_displacement:, max_displacement:], 0.0, lax.add,
            window_dimensions=(1, kernel_size, kernel_size),
            window_strides=(1, stride1, stride1), padding="VALID")
        return win[:, :top_h, :top_w]

    out = jax.vmap(one)(disp)                  # (D*D, N, th, tw)
    out = jnp.transpose(out, (1, 0, 2, 3))
    return out / float(kernel_size * kernel_size * C)


# ---------------------------------------------------------------------------
# DeformableConvolution
# (ref: src/operator/contrib/deformable_convolution-inl.h + the bilinear
#  gather in nn/deformable_im2col.cuh:238-251)
# ---------------------------------------------------------------------------

def _bilinear_gather(img, y, x):
    """Sample img (C, H, W) at fractional (y, x) [each (...,)] with
    zero padding outside — matches deformable_im2col's im2col_bilinear."""
    jnp = _jnp()
    C, H, W = img.shape
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    wy1 = y - y0
    wx1 = x - x0
    out = 0.0
    for oy, wy in ((0, 1.0 - wy1), (1, wy1)):
        for ox, wx in ((0, 1.0 - wx1), (1, wx1)):
            yy = y0 + oy
            xx = x0 + ox
            valid = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
            yi = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
            xi = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
            v = img[:, yi, xi]                 # (C, ...)
            out = out + v * (jnp.where(valid, wy * wx, 0.0))
    return out


@register("_contrib_DeformableConvolution",
          aliases=("DeformableConvolution",))
def _deformable_convolution(data, offset, weight, *maybe_bias, kernel=(),
                            stride=(), dilate=(), pad=(), num_filter=1,
                            num_group=1, num_deformable_group=1,
                            workspace=1024, no_bias=False, layout=None):
    """Deformable conv v1: sampling grid shifted by learned offsets.

    data (N,C,H,W); offset (N, dg*2*K, Ho, Wo) with per-kernel-position
    (h, w) offset pairs; weight (F, C/num_group, kh, kw).
    """
    import jax
    jnp = _jnp()
    kh, kw = (int(k) for k in kernel)
    sh, sw = (int(s) for s in stride) if stride else (1, 1)
    dh, dw = (int(d) for d in dilate) if dilate else (1, 1)
    ph, pw = (int(p) for p in pad) if pad else (0, 0)
    dg = int(num_deformable_group)
    ng = int(num_group)
    N, C, H, W = data.shape
    F = int(num_filter)
    K = kh * kw
    Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    check(offset.shape[1] == dg * 2 * K,
          f"offset channels {offset.shape[1]} != 2*kernel*deformable_group "
          f"{dg * 2 * K}")
    check(C % dg == 0 and C % ng == 0, "channels not divisible by groups")

    # base sampling positions per (K, Ho, Wo)
    ki = jnp.arange(kh).reshape(kh, 1, 1, 1)
    kj = jnp.arange(kw).reshape(1, kw, 1, 1)
    oi = jnp.arange(Ho).reshape(1, 1, Ho, 1)
    oj = jnp.arange(Wo).reshape(1, 1, 1, Wo)
    base_y = jnp.broadcast_to(oi * sh - ph + ki * dh,
                              (kh, kw, Ho, Wo)).reshape(K, Ho, Wo)
    base_x = jnp.broadcast_to(oj * sw - pw + kj * dw,
                              (kh, kw, Ho, Wo)).reshape(K, Ho, Wo)

    off = offset.reshape(N, dg, K, 2, Ho, Wo)
    y = base_y[None, None] + off[:, :, :, 0]   # (N, dg, K, Ho, Wo)
    x = base_x[None, None] + off[:, :, :, 1]

    cg = C // dg

    def per_image(img, yy, xx):
        # img (dg, cg, H, W); yy/xx (dg, K, Ho, Wo)
        def per_group(g_img, g_y, g_x):
            return _bilinear_gather(g_img, g_y, g_x)  # (cg, K, Ho, Wo)
        return jax.vmap(per_group)(img, yy, xx)       # (dg, cg, K, Ho, Wo)

    sampled = jax.vmap(per_image)(
        data.reshape(N, dg, cg, H, W), y, x)          # (N, dg, cg, K, Ho, Wo)
    sampled = sampled.reshape(N, C, K, Ho, Wo)

    # grouped contraction on the MXU: (N, C, K, Ho, Wo) x (F, C/ng, K)
    cpg = C // ng
    fpg = F // ng
    sg = sampled.reshape(N, ng, cpg, K, Ho, Wo)
    wg = weight.reshape(ng, fpg, cpg, K)
    out = jnp.einsum("ngckhw,gfck->ngfhw", sg, wg,
                     preferred_element_type=jnp.float32)
    out = out.reshape(N, F, Ho, Wo).astype(data.dtype)
    if maybe_bias and not no_bias:
        out = out + maybe_bias[0].reshape(1, F, 1, 1)
    return out


# ---------------------------------------------------------------------------
# FFT / IFFT (ref: src/operator/contrib/fft-inl.h — cuFFT C2C FORWARD,
# unnormalized; ifft-inl.h — C2C INVERSE, unnormalized, real part kept)
# ---------------------------------------------------------------------------

@register("_contrib_fft", aliases=("fft",))
def _fft(data, compute_size=128):
    """Real (..., d) -> interleaved complex (..., 2d), unnormalized DFT."""
    jnp = _jnp()
    spec = jnp.fft.fft(data.astype(jnp.float32), axis=-1)
    out = jnp.stack([spec.real, spec.imag], axis=-1)
    return out.reshape(*data.shape[:-1], 2 * data.shape[-1]) \
        .astype(data.dtype)


@register("_contrib_ifft", aliases=("ifft",))
def _ifft(data, compute_size=128):
    """Interleaved complex (..., 2d) -> real (..., d). Matches cuFFT's
    unnormalized inverse: ifft(fft(x)) == x * d."""
    jnp = _jnp()
    check(data.shape[-1] % 2 == 0, "ifft input last dim must be even")
    d = data.shape[-1] // 2
    pairs = data.astype(jnp.float32).reshape(*data.shape[:-1], d, 2)
    spec = pairs[..., 0] + 1j * pairs[..., 1]
    # jnp.fft.ifft normalizes by 1/d; cuFFT INVERSE does not
    out = jnp.fft.ifft(spec, axis=-1).real * d
    return out.astype(data.dtype)


# ---------------------------------------------------------------------------
# count_sketch (ref: src/operator/contrib/count_sketch-inl.h — out[n, h[i]]
# += s[i] * in[n, i])
# ---------------------------------------------------------------------------

@register("_contrib_count_sketch", aliases=("count_sketch",))
def _count_sketch(data, h, s, out_dim=0, processing_batch_size=32):
    """Count-sketch projection to out_dim via hash h and signs s
    (both (1, in_dim) or (in_dim,))."""
    jnp = _jnp()
    out_dim = int(out_dim)
    check(out_dim > 0, "count_sketch requires out_dim > 0")
    in_dim = data.shape[-1]
    hv = h.reshape(-1).astype(jnp.int32)
    sv = s.reshape(-1).astype(data.dtype)
    check(hv.shape[0] == in_dim and sv.shape[0] == in_dim,
          "h/s must have in_dim elements")
    lead = data.shape[:-1]
    flat = data.reshape(-1, in_dim) * sv[None, :]
    out = jnp.zeros((flat.shape[0], out_dim), flat.dtype)
    out = out.at[:, hv].add(flat)
    return out.reshape(*lead, out_dim)
