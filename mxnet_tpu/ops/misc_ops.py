"""Remaining reference ops: add_n, split_v2, legacy Crop, internal
assignment/identity helpers.

References: src/operator/tensor/elemwise_sum.cc (add_n/ElementWiseSum),
src/operator/tensor/matrix_op.cc (_split_v2), src/operator/crop.cc (Crop),
src/operator/tensor/indexing_op.cc (_scatter_set_nd),
src/operator/tensor/matrix_op.cc (_slice_assign/_slice_assign_scalar),
src/operator/tensor/init_op.cc (_zeros_without_dtype),
src/operator/tensor/elemwise_unary_op_basic.cc
(_identity_with_attr_like_rhs), src/operator/nn/concat.cc
(_rnn_param_concat).
"""
from __future__ import annotations

import numpy as _np

from .registry import register


def _jnp():
    import jax.numpy as jnp
    return jnp


@register("add_n", aliases=("ElementWiseSum", "_sum"), variadic=True)
def _add_n(*xs, num_args=None):
    """Elementwise sum of n inputs (ref: elemwise_sum.cc add_n)."""
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


def _split_v2_outputs(n_inputs, params):
    sections = int(params.get("sections", 0))
    if sections > 0:
        return sections
    # indices follow the C++/frontend convention: they INCLUDE the leading
    # 0 boundary (python/mxnet/ndarray/ndarray.py:3989 prepends 0), so
    # num_outputs == len(indices)
    return max(1, len(tuple(params.get("indices", ()))))


@register("_split_v2", num_outputs=_split_v2_outputs)
def _split_v2(x, indices=(), axis=1, squeeze_axis=False, sections=0):
    """Split by equal sections or explicit boundary indices. `indices`
    includes the leading 0 start boundary, matching the reference's
    serialized attrs (ref: matrix_op.cc _split_v2, SplitParam
    matrix_op-inl.h:2532; GetSplitIndices builds [0, ...]).
    """
    jnp = _jnp()
    axis = int(axis)
    if int(sections) > 0:
        parts = jnp.split(x, int(sections), axis=axis)
    else:
        interior = [int(i) for i in indices][1:]  # drop the 0 start boundary
        parts = jnp.split(x, interior, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if len(parts) != 1 else parts[0]


@register("Crop", variadic=True)
def _crop(*inputs, num_args=1, offset=(0, 0), h_w=(0, 0),
          center_crop=False):
    """Legacy NCHW crop (ref: src/operator/crop.cc). With two inputs the
    second is `crop_like` providing the target H/W."""
    from ..base import check
    data = inputs[0]
    if int(num_args) >= 2 and len(inputs) >= 2:
        th, tw = inputs[1].shape[2], inputs[1].shape[3]
    else:
        th, tw = int(h_w[0]), int(h_w[1])
    H, W = data.shape[2], data.shape[3]
    check(th <= H and tw <= W,
          f"Crop: target ({th}, {tw}) larger than input ({H}, {W})")
    if center_crop:
        y0, x0 = (H - th) // 2, (W - tw) // 2
    else:
        y0, x0 = int(offset[0]), int(offset[1])
        check(y0 + th <= H and x0 + tw <= W,
              f"Crop: offset ({y0}, {x0}) + target ({th}, {tw}) exceeds "
              f"input ({H}, {W})")
    return data[:, :, y0:y0 + th, x0:x0 + tw]


@register("_slice_assign", aliases=("slice_assign",))
def _slice_assign(lhs, rhs, begin=(), end=(), step=()):
    """Assign rhs into lhs[begin:end:step] (ref: matrix_op.cc
    _slice_assign)."""
    sl = _make_slices(lhs.shape, begin, end, step)
    return lhs.at[sl].set(rhs)


@register("_slice_assign_scalar", aliases=("slice_assign_scalar",))
def _slice_assign_scalar(lhs, scalar=0.0, begin=(), end=(), step=()):
    sl = _make_slices(lhs.shape, begin, end, step)
    return lhs.at[sl].set(scalar)


def _make_slices(shape, begin, end, step):
    begin, end = tuple(begin), tuple(end)
    step = tuple(step) if step else (1,) * len(begin)
    out = []
    for i in range(len(shape)):
        if i < len(begin):
            b = begin[i]
            e = end[i] if i < len(end) else None
            s = step[i] if i < len(step) and step[i] not in (None, 0) else 1
            b = None if b is None else int(b)
            e = None if e is None else int(e)
            out.append(slice(b, e, int(s)))
        else:
            out.append(slice(None))
    return tuple(out)


@register("_zeros_without_dtype", creation=True, differentiable=False)
def _zeros_without_dtype(shape=(), ctx=None, dtype=None, **_):
    """zeros whose dtype defaults to float32 when unspecified
    (ref: init_op.cc _zeros_without_dtype, used for grad init)."""
    jnp = _jnp()
    dt = _np.dtype("float32") if dtype in (None, "None", -1) else dtype
    return jnp.zeros(tuple(shape), dt)


@register("_identity_with_attr_like_rhs")
def _identity_with_attr_like_rhs(lhs, rhs):
    """Identity on lhs; rhs only contributes shape/stype attrs in the
    reference's graph passes (ref: elemwise_unary_op_basic.cc)."""
    return lhs


@register("_rnn_param_concat", variadic=True)
def _rnn_param_concat(*xs, dim=0, num_args=None):
    """Concat for packing RNN parameters; flattens each input first
    (ref: src/operator/nn/concat.cc _rnn_param_concat — shape inference
    differs from Concat but runtime is 1-D concat)."""
    jnp = _jnp()
    return jnp.concatenate([x.reshape(-1) for x in xs], axis=0)


# SparseEmbedding: the reference's dense-forward / row_sparse-grad embedding
# (src/operator/tensor/indexing_op.cc _contrib_SparseEmbedding). Gradients
# here flow through JAX's gather VJP (scatter-add), so the dense Embedding
# op is semantically equivalent; row_sparse gradient packing happens in the
# optimizer/kvstore layer.
from .registry import alias as _alias  # noqa: E402
_alias("_contrib_SparseEmbedding", "Embedding")


@register("_CrossDeviceCopy")
def _cross_device_copy(data):
    """Cross-device copy marker inserted between ctx_group placements
    (ref: src/operator/cross_device_copy.cc). Under XLA one compiled
    program spans the mesh, so the transfer is a sharding boundary the
    compiler materializes; imperatively it is identity."""
    return data


# Legacy v1 duplicates kept for checkpoint/JSON backcompat (ref:
# src/operator/batch_norm_v1.cc, convolution_v1.cc, pooling_v1.cc — the
# reference retains the pre-NNVM implementations under *_v1 names).
_alias("BatchNorm_v1", "BatchNorm")
_alias("Convolution_v1", "Convolution")
_alias("Pooling_v1", "Pooling")


@register("cast_storage")
def _cast_storage_op(data, stype="default"):
    """Graph-level cast_storage (ref: src/operator/tensor/cast_storage.cc).

    Inside a compiled graph every tensor is dense (XLA has no sparse
    runtime representation), so all stype targets are identity at
    execution time; the op exists so sym.* graphs that change storage
    type bind/compose exactly like the reference. Container-level
    conversion (returning RowSparse/CSR NDArrays) lives in
    mx.nd.cast_storage (ndarray/sparse.py), which shadows this op on the
    imperative frontend."""
    return data


@register("sparse_retain")
def _sparse_retain_op(data, indices):
    """Graph-level sparse_retain (ref: src/operator/tensor/
    sparse_retain.cc): keep the listed rows, zero the rest. Dense
    semantics of the reference kernel; the container-level variant is
    mx.nd.sparse_retain."""
    jnp = _jnp()
    rows = jnp.zeros((data.shape[0],), jnp.bool_)
    rows = rows.at[indices.astype(jnp.int32)].set(True)
    shape = (data.shape[0],) + (1,) * (data.ndim - 1)
    return data * rows.reshape(shape).astype(data.dtype)
