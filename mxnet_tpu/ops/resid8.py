"""8-bit activation residuals: trade backward-pass numerical headroom for
HBM bytes.

Why this exists: the ResNet-50 training step on one v5e chip is
HBM-bandwidth-bound on activation traffic, not compute-bound (README perf
ledger; ~30 TFLOP/s sustained vs ~145 TFLOP/s demonstrated conv peak).
The residuals autodiff saves between forward and backward are
activation-sized tensors read exactly once in backward — storing them as
fp8 (float8_e4m3fn) halves those bytes at small zero-mean rounding error
per element. For CONVOLUTIONS dx needs only the weights and stays exact;
conv dW, the BN backward (which reads fp8 xhat for both its dx and
dgamma), and the ReLU mask see the rounding, which the per-channel
reductions average out over the batch.

Design rules (all enforced here):
- storage-only: fp8 matmul is software-emulated on v5e (~1.8 TFLOP/s
  measured) — residuals are CAST to fp8 on store and back to the compute
  dtype before any FLOP.
- pure casts, no dynamic scales: a per-tensor absmax scale would add a
  full extra read pass over the activation; e4m3's exponent range (±448)
  covers post-BN/ReLU activations without one. Saturation clamps the
  (rare) outliers.
- shared copies: ReLU saves fp8(out) with the same cast expression the
  following Convolution saves for its input, so XLA CSE keeps ONE fp8
  copy per activation.

Enabled by MXNET_RESID_DTYPE=fp8 (read at trace time; see base.env).
Reference analog: none — the reference's closest lever is fp16 training
(src/operator/nn/convolution.cu DType=half); this is the TPU-native
extension of the same memory/precision trade.
"""
from __future__ import annotations

from functools import lru_cache

from ..base import env

__all__ = ["resid_dtype", "conv_resid8", "relu_resid8"]

_NAMES = {"fp8": "float8_e4m3fn", "e4m3": "float8_e4m3fn",
          "e5m2": "float8_e5m2"}


def resid_dtype():
    """The configured residual storage dtype name, or None (disabled)."""
    v = env.get("MXNET_RESID_DTYPE")
    if not v:
        return None
    name = _NAMES.get(v, v)
    if name not in ("float8_e4m3fn", "float8_e5m2"):
        from ..base import MXNetError
        raise MXNetError(
            f"MXNET_RESID_DTYPE={v!r}: expected fp8|e4m3|e5m2")
    return name


@lru_cache(maxsize=None)
def _conv8(cfg, rdt_name):
    """Convolution whose saved input residual is stored 8-bit.

    cfg = (stride, pad, dilate, dn_spec, num_group); the backward
    re-derives both cotangents via jax.vjp of the same conv so dx (which
    needs only weights) is exact and only dW sees the 8-bit input."""
    import jax
    import jax.numpy as jnp
    stride, pad, dilate, dn_spec, groups = cfg
    rdt = jnp.dtype(rdt_name)

    def core(data, weight):
        dn = jax.lax.conv_dimension_numbers(data.shape, weight.shape,
                                            dn_spec)
        return jax.lax.conv_general_dilated(
            data, weight, window_strides=stride,
            padding=[(p, p) for p in pad], rhs_dilation=dilate,
            dimension_numbers=dn, feature_group_count=groups)

    @jax.custom_vjp
    def f(data, weight):
        return core(data, weight)

    def fwd(data, weight):
        # the fp8 cast fuses into whichever elementwise kernel produced
        # `data`; only the 1-byte copy reaches HBM for the backward
        return core(data, weight), (data.astype(rdt), weight)

    def bwd(res, dy):
        xq, w = res
        x = xq.astype(dy.dtype)
        _, vjp = jax.vjp(core, x, w)
        return vjp(dy)

    f.defvjp(fwd, bwd)
    return f


def conv_resid8(data, weight, stride, pad, dilate, dn_spec, groups,
                rdt_name):
    cfg = (tuple(stride), tuple(pad), tuple(dilate), tuple(dn_spec),
           int(groups))
    return _conv8(cfg, rdt_name)(data, weight)


@lru_cache(maxsize=None)
def _relu8(rdt_name):
    """ReLU saving fp8(out): the mask is re-derived as fp8(out) > 0 — the
    cast expression is IDENTICAL to the one the following convolution
    saves for its input, so XLA CSE materializes one fp8 copy serving
    both. (fp8 rounds denormal-small positives to 0; the gradient there
    is the valid 0 subgradient.)"""
    import jax
    import jax.numpy as jnp
    rdt = jnp.dtype(rdt_name)

    @jax.custom_vjp
    def f(x):
        return jnp.maximum(x, 0)

    def fwd(x):
        y = jnp.maximum(x, 0)
        return y, (y.astype(rdt),)

    def bwd(res, dy):
        (yq,) = res
        return (jnp.where(yq > 0, dy, jnp.zeros((), dy.dtype)),)

    f.defvjp(fwd, bwd)
    return f


def relu_resid8(data, rdt_name):
    return _relu8(rdt_name)(data)
