"""8-bit activation residuals: trade backward-pass numerical headroom for
HBM bytes.

Why this exists: the ResNet-50 training step on one v5e chip is
HBM-bandwidth-bound on activation traffic, not compute-bound (README perf
ledger; ~30 TFLOP/s sustained vs ~145 TFLOP/s demonstrated conv peak).
The residuals autodiff saves between forward and backward are
activation-sized tensors read exactly once in backward — storing them as
fp8 (float8_e4m3fn) halves those bytes at small zero-mean rounding error
per element. For CONVOLUTIONS dx needs only the weights and stays exact;
conv dW, the BN backward (which reads fp8 xhat for both its dx and
dgamma), and the ReLU mask see the rounding, which the per-channel
reductions average out over the batch.

Design rules (all enforced here):
- storage-only: fp8 matmul is software-emulated on v5e (~1.8 TFLOP/s
  measured) — residuals are CAST to fp8 on store and back to the compute
  dtype before any FLOP.
- pure casts, no dynamic scales: a per-tensor absmax scale would add a
  full extra read pass over the activation; e4m3's exponent range (±448)
  covers post-BN/ReLU activations without one. Outliers beyond the fp8
  max are explicitly CLAMPED before the cast (``_sat_cast``) — XLA's
  float->fp8 conversion overflows to NaN (e4m3) / inf (e5m2), which
  would otherwise poison dW and, through the relu mask (NaN > 0 is
  False), silently zero gradients.
- shared copies: ReLU saves fp8(out) with the same cast expression the
  following Convolution saves for its input, so XLA CSE keeps ONE fp8
  copy per activation.

Enabled by MXNET_RESID_DTYPE=fp8 (read at trace time; see base.env).
Reference analog: none — the reference's closest lever is fp16 training
(src/operator/nn/convolution.cu DType=half); this is the TPU-native
extension of the same memory/precision trade.
"""
from __future__ import annotations

from functools import lru_cache

from ..base import env

__all__ = ["resid_dtype", "conv_resid8", "relu_resid8", "conv_int8",
           "conv_int8_train"]

_NAMES = {"fp8": "float8_e4m3fn", "e4m3": "float8_e4m3fn",
          "e5m2": "float8_e5m2"}


def _sat_cast(x, rdt):
    """Saturating cast to the fp8 residual dtype.

    float32->fp8 on XLA rounds values beyond the format's max to NaN
    (e4m3fn) or inf (e5m2), not to the max finite value; one NaN in a
    stored residual poisons the whole dW on the next backward. The clip
    fuses into the producing elementwise kernel, so it costs no extra
    HBM pass. Every residual cast in this module (and the BN xhat cast
    in ops/nn.py) must go through here."""
    import jax.numpy as jnp
    m = float(jnp.finfo(rdt).max)
    return jnp.clip(x, -m, m).astype(rdt)


def conv_int8():
    """MXNET_CONV_COMPUTE=int8: run training convolutions int8 on the MXU.

    Unlike residual-width tricks (above), this changes what the FORWARD
    reads: conv inputs are quantized int8 (1 byte/elt instead of 2) with
    a STATIC activation range and per-channel dynamic weight scales, and
    the int8 x int8 -> int32 conv runs at ~1.5x the bf16 MXU rate
    (measured, v5e). Every conv in the repo's flagship models is
    BN-renormalized, so post-BN/ReLU activations are O(1) and a fixed
    range covers them; MXNET_CONV_INT8_RANGE widens it if a model clips.
    """
    return bool(env.get("MXNET_CONV_COMPUTE") == "int8")


def resid_dtype():
    """The configured residual storage dtype name, or None (disabled)."""
    v = env.get("MXNET_RESID_DTYPE")
    if not v:
        return None
    name = _NAMES.get(v, v)
    if name not in ("float8_e4m3fn", "float8_e5m2"):
        from ..base import MXNetError
        raise MXNetError(
            f"MXNET_RESID_DTYPE={v!r}: expected fp8|e4m3|e5m2")
    return name


@lru_cache(maxsize=None)
def _conv8(cfg, rdt_name):
    """Convolution whose saved input residual is stored 8-bit.

    cfg = (stride, pad, dilate, dn_spec, num_group); the backward
    re-derives both cotangents via jax.vjp of the same conv so dx (which
    needs only weights) is exact and only dW sees the 8-bit input."""
    import jax
    import jax.numpy as jnp
    stride, pad, dilate, dn_spec, groups = cfg
    rdt = jnp.dtype(rdt_name)

    def core(data, weight):
        dn = jax.lax.conv_dimension_numbers(data.shape, weight.shape,
                                            dn_spec)
        return jax.lax.conv_general_dilated(
            data, weight, window_strides=stride,
            padding=[(p, p) for p in pad], rhs_dilation=dilate,
            dimension_numbers=dn, feature_group_count=groups)

    @jax.custom_vjp
    def f(data, weight):
        return core(data, weight)

    def fwd(data, weight):
        # the saturating fp8 cast fuses into whichever elementwise
        # kernel produced `data`; only the 1-byte copy reaches HBM for
        # the backward
        return core(data, weight), (_sat_cast(data, rdt), weight)

    def bwd(res, dy):
        xq, w = res
        x = xq.astype(dy.dtype)
        _, vjp = jax.vjp(core, x, w)
        return vjp(dy)

    f.defvjp(fwd, bwd)
    return f


def conv_resid8(data, weight, stride, pad, dilate, dn_spec, groups,
                rdt_name):
    cfg = (tuple(stride), tuple(pad), tuple(dilate), tuple(dn_spec),
           int(groups))
    return _conv8(cfg, rdt_name)(data, weight)


@lru_cache(maxsize=None)
def _conv_i8(cfg, act_range):
    """Training conv computing int8 x int8 -> int32 on the MXU.

    Forward: x quantized with the static ``act_range`` (the quantize
    fuses into x's producer kernel, so the conv READS 1 byte/elt), w
    quantized per-output-channel with dynamic scales (weights are small;
    the absmax reduction is noise). Backward (straight-through through
    both quantizers): dx = conv_T(dy, w) against the EXACT bf16 weights;
    dW reads the saved int8 input (1 byte/elt) dequantized in-kernel.
    """
    import jax
    import jax.numpy as jnp
    stride, pad, dilate, dn_spec, groups = cfg
    s_act = float(act_range) / 127.0

    def _conv(lhs, rhs, preferred=None):
        dn = jax.lax.conv_dimension_numbers(lhs.shape, rhs.shape, dn_spec)
        return jax.lax.conv_general_dilated(
            lhs, rhs, window_strides=stride,
            padding=[(p, p) for p in pad], rhs_dilation=dilate,
            dimension_numbers=dn, feature_group_count=groups,
            preferred_element_type=preferred)

    def _quant_x(x):
        return jnp.clip(jnp.round(x.astype(jnp.float32) * (1.0 / s_act)),
                        -127, 127).astype(jnp.int8)

    def _quant_w(w):
        w32 = w.astype(jnp.float32)
        absmax = jnp.max(jnp.abs(w32.reshape(w32.shape[0], -1)), axis=1)
        sw = jnp.maximum(absmax, 1e-8) / 127.0
        qw = jnp.clip(jnp.round(w32 / sw.reshape((-1,) + (1,) *
                                                 (w32.ndim - 1))),
                      -127, 127).astype(jnp.int8)
        return qw, sw

    def _fwd_val(x, w):
        qx = _quant_x(x)
        qw, sw = _quant_w(w)
        acc = _conv(qx, qw, preferred=jnp.int32)
        ax = dn_spec[2].index("C")
        bshape = tuple(sw.shape[0] if i == ax else 1
                       for i in range(acc.ndim))
        out = acc.astype(jnp.float32) * (sw * s_act).reshape(bshape)
        return out.astype(x.dtype), qx

    @jax.custom_vjp
    def f(x, w):
        return _fwd_val(x, w)[0]

    def fwd(x, w):
        out, qx = _fwd_val(x, w)
        return out, (qx, w)

    def bwd(res, dy):
        qx, w = res
        x = (qx.astype(dy.dtype) * jnp.asarray(s_act, dy.dtype))
        _, vjp = jax.vjp(lambda xx, ww: _conv(xx, ww), x, w)
        return vjp(dy)

    f.defvjp(fwd, bwd)
    return f


def conv_int8_train(data, weight, stride, pad, dilate, dn_spec, groups):
    cfg = (tuple(stride), tuple(pad), tuple(dilate), tuple(dn_spec),
           int(groups))
    rng = float(env.get("MXNET_CONV_INT8_RANGE"))
    return _conv_i8(cfg, rng)(data, weight)


@lru_cache(maxsize=None)
def _relu8(rdt_name):
    """ReLU saving fp8(out): the mask is re-derived as fp8(out) > 0 — the
    cast expression is IDENTICAL to the one the following convolution
    saves for its input, so XLA CSE materializes one fp8 copy serving
    both. (fp8 rounds denormal-small positives to 0; the gradient there
    is the valid 0 subgradient.)"""
    import jax
    import jax.numpy as jnp
    rdt = jnp.dtype(rdt_name)

    @jax.custom_vjp
    def f(x):
        return jnp.maximum(x, 0)

    def fwd(x):
        y = jnp.maximum(x, 0)
        return y, (_sat_cast(y, rdt),)

    def bwd(res, dy):
        (yq,) = res
        return (jnp.where(yq > 0, dy, jnp.zeros((), dy.dtype)),)

    f.defvjp(fwd, bwd)
    return f


def relu_resid8(data, rdt_name):
    return _relu8(rdt_name)(data)
