"""Operator registry + eager compile-and-cache executor.

This is the TPU-native replacement for the reference's NNVM op registry and
imperative dispatch chain (ref: include/mxnet/op_attr_types.h FCompute/
FComputeEx; src/imperative/imperative.cc:87 Imperative::Invoke ->
src/engine/threaded_engine.cc:315 PushAsync -> worker kernels).

Design:
- Every operator is a **pure JAX function** ``fn(*inputs, **params)`` over
  ``jax.Array`` values. This single definition serves all four consumers:
  1. eager NDArray execution (this module: per-(op, params) ``jax.jit``
     with XLA's shape/dtype-keyed compile cache = the reference's
     per-op kernel dispatch, but compiled),
  2. the autograd tape (``jax.vjp`` on the same fn = ref FGradient),
  3. symbolic/CachedOp whole-graph lowering (fns composed then jitted as a
     single HLO module = ref GraphExecutor bulking taken to its limit),
  4. shape/type inference (``jax.eval_shape`` = ref FInferShape/FInferType).
- The "async engine" contract (frontend never blocks, exceptions surface at
  sync points) is inherited from JAX's async dispatch; NaiveEngine debug mode
  (MXNET_ENGINE_TYPE=NaiveEngine, ref src/engine/engine.cc:33-46) is honored
  by blocking after every eager op.

Registered names mirror the reference's op names (elemwise_add, dot,
Convolution, ...) so generated frontend namespaces have the same surface
(ref: python/mxnet/ndarray/register.py codegen).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError, env, hashable_params, coerce_param

__all__ = ["OpDef", "register", "get_op", "list_ops", "invoke_jax",
           "eval_shape", "alias", "register_sparse", "stype_dispatch",
           "storage_fallback_warn", "push_op_islands", "pop_op_islands",
           "op_islands_active"]

_OPS: Dict[str, "OpDef"] = {}


# ---------------------------------------------------------------------------
# op-island mode: bitwise-faithful whole-step traces (MXTPU_MEGASTEP)
# ---------------------------------------------------------------------------
# The eager executor MATERIALIZES every op's outputs (each op is its own
# compiled program), which forbids XLA from fusing across op boundaries —
# in particular from contracting a producer's multiply into a consumer's
# add (FMA, one rounding instead of two). A whole-step trace
# (megastep.py) inlines those same ops into ONE program, where such
# cross-op contraction WOULD flip last bits vs the eager trajectory.
# Island mode restores the eager boundaries structurally: while active
# (megastep's traced body brackets itself with push/pop), every op's
# outputs pass through ``lax.optimization_barrier``, so each op compiles
# as the same isolated fusion region it is eagerly — the fused program
# is the composed step's exact kernels MINUS the per-op dispatches,
# which is precisely the megastep win (launch overhead, not kernel
# algebra) and makes bitwise parity hold by construction.
import threading as _threading

_ISLANDS = _threading.local()


def push_op_islands() -> None:
    _ISLANDS.depth = getattr(_ISLANDS, "depth", 0) + 1


def pop_op_islands() -> None:
    _ISLANDS.depth = getattr(_ISLANDS, "depth", 1) - 1


def op_islands_active() -> bool:
    return getattr(_ISLANDS, "depth", 0) > 0


def _island(out):
    """Barrier one op's outputs (pytree-safe; None leaves pass through)."""
    import jax
    if out is None:
        return out
    if isinstance(out, (tuple, list)):
        typ = type(out)
        return typ(o if o is None else jax.lax.optimization_barrier(o)
                   for o in out)
    return jax.lax.optimization_barrier(out)

# storage-type dispatch table (the FComputeEx + FInferStorageType analog,
# ref: include/mxnet/op_attr_types.h:122,282): (op name, input stypes) →
# kernel over sparse/dense NDArray objects. "*" matches any stype tuple.
_SPARSE_IMPLS: Dict[Tuple[str, Tuple[str, ...]], Callable] = {}
_FALLBACK_WARNED: set = set()


class OpDef:
    """A registered operator.

    Attributes
    ----------
    name : canonical op name (reference-compatible).
    fn : pure function ``fn(*inputs, **params) -> array | tuple``.
    num_outputs : static int, or callable ``(n_inputs, params) -> int``.
    differentiable : participates in autograd recording.
    creation : takes no array inputs (zeros/ones/random...); receives
        ``shape/dtype/ctx`` handling in the frontend wrapper.
    """

    __slots__ = ("name", "fn", "num_outputs", "differentiable", "creation",
                 "namespaces", "_jit_cache", "doc", "variadic", "backward_fn",
                 "rng", "aux_inputs", "dynamic_params")

    def __init__(self, name: str, fn: Callable, num_outputs=1,
                 differentiable: bool = True, creation: bool = False,
                 namespaces: Sequence[str] = ("op",), variadic: bool = False,
                 backward_fn: Optional[Callable] = None, doc: str = "",
                 rng: bool = False, aux_inputs: Sequence[int] = (),
                 dynamic_params: Sequence[str] = ()):
        # float params traced as device scalars instead of baked into the
        # compiled program: a per-step value (Adam's bias-corrected lr_t, a
        # scheduled lr) must NOT key the jit cache, or every step
        # recompiles (measured: eager Adam recompiled 15x/step before this)
        self.dynamic_params = tuple(dynamic_params)
        self.rng = rng
        # input slots that are auxiliary states in symbolic graphs
        # (ref: OperatorProperty::ListAuxiliaryStates — e.g. BatchNorm's
        # moving_mean/moving_var)
        self.aux_inputs = tuple(aux_inputs)
        self.name = name
        self.fn = fn
        self.num_outputs = num_outputs
        self.differentiable = differentiable
        self.creation = creation
        self.namespaces = tuple(namespaces)
        self.variadic = variadic
        self.backward_fn = backward_fn
        self.doc = doc or (fn.__doc__ or "")
        self._jit_cache: Dict[Tuple, Callable] = {}

    # -- eager execution ------------------------------------------------
    def jitted(self, params_key: Tuple, dyn_names: Tuple = ()) -> Callable:
        """One ``jax.jit`` per (op, params); XLA caches per shape/dtype.

        This is the eager hot path: the analog of the reference's per-op
        engine push, except each (op, params, shape, dtype) combination is
        compiled once into a fused XLA executable and then replayed
        (SURVEY.md §7 stage 4: "compile-and-cache tiny HLO modules").

        ``dyn_names``: declared dynamic params bound on this call — their
        VALUES arrive as a traced tuple argument, not in the cache key.
        """
        cache_key = (params_key, dyn_names)
        cached = self._jit_cache.get(cache_key)
        if cached is None:
            import jax
            # strip the trace-time flag suffix (booleans) — only real
            # (name, value) param pairs become kwargs
            kwargs = dict(kv for kv in params_key
                          if isinstance(kv, tuple) and len(kv) == 2)
            fn = self.fn

            def call(dyn_vals, *arrays):
                return fn(*arrays, **kwargs,
                          **dict(zip(dyn_names, dyn_vals)))

            cached = jax.jit(call)
            self._jit_cache[cache_key] = cached
        return cached

    def __call__(self, *inputs, **params):
        return invoke_jax(self, inputs, params)

    def n_out(self, n_inputs: int, params: Dict[str, Any]) -> int:
        if callable(self.num_outputs):
            return self.num_outputs(n_inputs, params)
        return self.num_outputs

    def __repr__(self):
        return f"<OpDef {self.name}>"


def register(name: str, aliases: Sequence[str] = (), **kw) -> Callable:
    """Decorator registering a pure-jax op implementation under ``name``."""

    def deco(fn: Callable) -> Callable:
        opdef = OpDef(name, fn, **kw)
        if name in _OPS:
            raise MXNetError(f"op {name} already registered")
        _OPS[name] = opdef
        for a in aliases:
            _OPS.setdefault(a, opdef)
        return fn

    return deco


def alias(name: str, target: str) -> None:
    _OPS[name] = _OPS[target]


def register_sparse(name: str, stypes: Sequence[str]) -> Callable:
    """Register an FComputeEx kernel for ``name`` with the given input
    storage-type signature, e.g. ``("csr", "default")``. The kernel receives
    the frontend NDArray/sparse objects directly (it owns device dispatch
    and tape recording) and returns NDArray or sparse NDArray outputs
    (ref: op_attr_types.h:282 FComputeEx; DispatchMode::kFComputeEx)."""

    def deco(fn: Callable) -> Callable:
        _SPARSE_IMPLS[(name, tuple(stypes))] = fn
        return fn

    return deco


def stype_dispatch(name: str, stypes: Sequence[str]) -> Optional[Callable]:
    """FInferStorageType analog: pick the FComputeEx kernel for this input
    stype combination, or None → dense fallback
    (DispatchMode::kFComputeFallback). Signature matching: exact tuple,
    then signatures whose tail is "*" (any remaining inputs), then the
    full wildcard ("*",)."""
    stypes = tuple(stypes)
    impl = _SPARSE_IMPLS.get((name, stypes))
    if impl is not None:
        return impl
    for (n, sig), fn in _SPARSE_IMPLS.items():
        if n != name or not sig or sig[-1] != "*":
            continue
        head = sig[:-1]
        if stypes[:len(head)] == head:
            return fn
    return _SPARSE_IMPLS.get((name, ("*",)))


def storage_fallback_warn(name: str, stypes: Sequence[str]) -> None:
    """Log the sparse→dense fallback once per (op, stypes), like the
    reference's LogStorageFallback (src/common/utils.h); silenced by
    MXNET_STORAGE_FALLBACK_LOG_VERBOSE=0 (ref: docs/faq/env_var.md)."""
    key = (name, tuple(stypes))
    if key in _FALLBACK_WARNED:
        return
    _FALLBACK_WARNED.add(key)
    if not env.get("MXNET_STORAGE_FALLBACK_LOG_VERBOSE"):
        return
    import warnings
    warnings.warn(
        f"operator {name} has no sparse kernel for input storage types "
        f"{tuple(stypes)}: falling back to dense compute (inputs densified). "
        "Set MXNET_STORAGE_FALLBACK_LOG_VERBOSE=0 to silence.",
        stacklevel=3)


def get_op(name: str) -> OpDef:
    try:
        return _OPS[name]
    except KeyError:
        raise MXNetError(f"operator {name!r} is not registered") from None


def list_ops() -> List[str]:
    return sorted(_OPS)


def _naive_engine() -> bool:
    return env.get("MXNET_ENGINE_TYPE") == "NaiveEngine"


def normalize_params(params: Dict[str, Any]) -> Dict[str, Any]:
    return {k: coerce_param(v) for k, v in params.items() if v is not None}


def _trace_time_flags() -> Tuple:
    """Env flags read INSIDE op impls at trace time (they change the
    compiled program, so they must be part of the jit-cache key —
    otherwise toggling the flag after first compile is a silent no-op)."""
    return (bool(env.get("MXNET_SAFE_ACCUMULATION")),
            env.get("MXNET_RESID_DTYPE") or "",
            env.get("MXNET_CONV_COMPUTE") or "",
            float(env.get("MXNET_CONV_INT8_RANGE")),
            bool(env.get("MXTPU_FUSED_EPILOGUE")))


def invoke_jax(opdef: OpDef, arrays: Sequence, params: Dict[str, Any]):
    """Execute an op on raw jax arrays through the jit cache.

    Returns whatever the impl returns (array or tuple). Equivalent position in
    the stack to Imperative::InvokeOp (ref src/imperative/imperative.cc:38),
    with the engine push replaced by XLA async dispatch.
    """
    params = normalize_params(params)
    dyn = {}
    if opdef.dynamic_params:
        import numbers
        for n in opdef.dynamic_params:
            # numbers.Real (not just int/float): an lr computed by a
            # numpy-based LRScheduler arrives as np.float32, which is not
            # a python float — missing it would bake the value into the
            # jit-cache key and recompile every step
            if n in params and isinstance(params[n], numbers.Real) \
                    and not isinstance(params[n], bool):
                # plain python float: jit traces it as a WEAK-typed scalar,
                # so `weight - lr * g` keeps the weight's (bf16) dtype —
                # a strong f32 scalar would silently promote the update
                dyn[n] = float(params.pop(n))
    key = hashable_params(params) + _trace_time_flags()
    from .. import profiler as _prof
    profiling = _prof.is_active()
    t0 = __import__("time").perf_counter() if profiling else 0.0
    try:
        out = opdef.jitted(key, tuple(dyn))(tuple(dyn.values()), *arrays)
    except TypeError:
        # Non-jittable param combination (e.g. python callable param):
        # fall back to direct tracing-free eval.
        out = opdef.fn(*arrays, **params, **dyn)
    if op_islands_active():
        out = _island(out)
    if _naive_engine():
        import jax
        jax.block_until_ready(out)
    if profiling:
        _prof.record_span(opdef.name, "operator", t0,
                          __import__("time").perf_counter())
    return out


def eval_shape(opdef: OpDef, in_shapes: Sequence[Tuple[int, ...]],
               in_dtypes: Sequence[Any], params: Dict[str, Any]):
    """Shape/dtype inference via abstract evaluation (ref: FInferShape /
    FInferType attr functions, src/executor/infer_graph_attr_pass.cc)."""
    import jax
    import jax.numpy as jnp
    params = normalize_params(params)
    specs = [jax.ShapeDtypeStruct(tuple(s), jnp.dtype(d))
             for s, d in zip(in_shapes, in_dtypes)]
    out = jax.eval_shape(lambda *xs: opdef.fn(*xs, **params), *specs)
    if not isinstance(out, (tuple, list)):
        out = (out,)
    return [tuple(o.shape) for o in out], [o.dtype for o in out]


def as_tuple_outputs(out) -> Tuple:
    if isinstance(out, (tuple, list)):
        return tuple(out)
    return (out,)
