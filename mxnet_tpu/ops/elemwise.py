"""Elementwise unary/binary operators and their *_scalar forms.

Reference inventory: src/operator/tensor/elemwise_unary_op_basic.cc,
elemwise_binary_op_basic.cc, elemwise_binary_scalar_op_*.cc and the ~200
scalar functors in src/operator/mshadow_op.h. Here each op is a one-line pure
jnp expression; XLA fuses chains of them into single kernels, which subsumes
the reference's mshadow expression templates and its operator_tune.cc
serial-vs-OpenMP autotuner (src/operator/operator_tune.cc) — fusion decisions
belong to the compiler on TPU.
"""
from __future__ import annotations

import numpy as _np

from .registry import register, alias


def _jnp():
    import jax.numpy as jnp
    return jnp


def _jsp():
    import jax.scipy.special as jsp
    return jsp


def _lax():
    import jax.lax as lax
    return lax


# ---------------------------------------------------------------------------
# unary math (ref: elemwise_unary_op_basic.cc, *_trig.cc, *_logexp.cc, *_pow.cc)
# ---------------------------------------------------------------------------

_UNARY = {
    "abs": lambda x: _jnp().abs(x),
    "sign": lambda x: _jnp().sign(x),
    "negative": lambda x: -x,
    "reciprocal": lambda x: 1.0 / x,
    "square": lambda x: x * x,
    "sqrt": lambda x: _jnp().sqrt(x),
    "rsqrt": lambda x: _lax().rsqrt(x),
    "cbrt": lambda x: _jnp().cbrt(x),
    "rcbrt": lambda x: 1.0 / _jnp().cbrt(x),
    "exp": lambda x: _jnp().exp(x),
    "log": lambda x: _jnp().log(x),
    "log10": lambda x: _jnp().log10(x),
    "log2": lambda x: _jnp().log2(x),
    "log1p": lambda x: _jnp().log1p(x),
    "expm1": lambda x: _jnp().expm1(x),
    "sin": lambda x: _jnp().sin(x),
    "cos": lambda x: _jnp().cos(x),
    "tan": lambda x: _jnp().tan(x),
    "arcsin": lambda x: _jnp().arcsin(x),
    "arccos": lambda x: _jnp().arccos(x),
    "arctan": lambda x: _jnp().arctan(x),
    "sinh": lambda x: _jnp().sinh(x),
    "cosh": lambda x: _jnp().cosh(x),
    "tanh": lambda x: _jnp().tanh(x),
    "arcsinh": lambda x: _jnp().arcsinh(x),
    "arccosh": lambda x: _jnp().arccosh(x),
    "arctanh": lambda x: _jnp().arctanh(x),
    "degrees": lambda x: _jnp().degrees(x),
    "radians": lambda x: _jnp().radians(x),
    "floor": lambda x: _jnp().floor(x),
    "ceil": lambda x: _jnp().ceil(x),
    "trunc": lambda x: _jnp().trunc(x),
    "round": lambda x: _jnp().round(x),
    "rint": lambda x: _jnp().rint(x),
    "fix": lambda x: _jnp().fix(x),
    "sigmoid": lambda x: 1.0 / (1.0 + _jnp().exp(-x)),
    "softsign": lambda x: x / (1.0 + _jnp().abs(x)),
    "relu": lambda x: _jnp().maximum(x, 0),
    "erf": lambda x: _jsp().erf(x),
    "erfinv": lambda x: _jsp().erfinv(x),
    "gamma": lambda x: _jnp().exp(_jsp().gammaln(x)),
    "gammaln": lambda x: _jsp().gammaln(x),
    "logical_not": lambda x: (x == 0).astype(x.dtype),
    # int64 in the reference; jax x64 is off, so int32 carries the values
    # (shapes/sizes < 2^31 on one chip) without the truncation warning
    "size_array": lambda x: _jnp().array([x.size], dtype=_np.int32),
    "shape_array": lambda x: _jnp().array(x.shape, dtype=_np.int32),
}

for _name, _fn in _UNARY.items():
    register(_name)(_fn)

@register("copy", aliases=("identity", "_copy"))
def _copy(x):
    # jax arrays are immutable, so sharing the buffer is a safe zero-cost copy
    return x

@register("BlockGrad", aliases=("stop_gradient",))
def _block_grad(x):
    import jax
    return jax.lax.stop_gradient(x)


@register("make_loss")
def _make_loss(x, grad_scale: float = 1.0, **_):
    import jax
    return x  # gradient handled as head grad; MakeLoss marks a loss output


@register("cast", aliases=("Cast",))
def _cast(x, dtype="float32"):
    import jax.numpy as jnp
    d = jnp.bfloat16 if dtype in ("bfloat16",) else _np.dtype(dtype)
    return x.astype(d)


@register("amp_cast")
def _amp_cast(x, dtype="float32"):
    return _cast(x, dtype)


@register("amp_multicast", num_outputs=lambda n, p: n, variadic=True)
def _amp_multicast(*xs, num_outputs=None):
    import jax.numpy as jnp
    widest = jnp.result_type(*[x.dtype for x in xs])
    return tuple(x.astype(widest) for x in xs)


@register("zeros_like")
def _zeros_like(x):
    return _jnp().zeros_like(x)


@register("ones_like")
def _ones_like(x):
    return _jnp().ones_like(x)


@register("gamma_sample_grad_dummy", namespaces=())
def _noop(x):
    return x


# ---------------------------------------------------------------------------
# binary elementwise (same-shape) — ref: elemwise_binary_op_basic.cc
# ---------------------------------------------------------------------------

_BINARY = {
    "elemwise_add": lambda a, b: a + b,
    "elemwise_sub": lambda a, b: a - b,
    "elemwise_mul": lambda a, b: a * b,
    "elemwise_div": lambda a, b: a / b,
    "_maximum": lambda a, b: _jnp().maximum(a, b),
    "_minimum": lambda a, b: _jnp().minimum(a, b),
    "_hypot": lambda a, b: _jnp().hypot(a, b),
    "_power": lambda a, b: _jnp().power(a, b),
    "_mod": lambda a, b: _jnp().mod(a, b),
    "_equal": lambda a, b: (a == b).astype(a.dtype),
    "_not_equal": lambda a, b: (a != b).astype(a.dtype),
    "_greater": lambda a, b: (a > b).astype(a.dtype),
    "_greater_equal": lambda a, b: (a >= b).astype(a.dtype),
    "_lesser": lambda a, b: (a < b).astype(a.dtype),
    "_lesser_equal": lambda a, b: (a <= b).astype(a.dtype),
    "_logical_and": lambda a, b: ((a != 0) & (b != 0)).astype(a.dtype),
    "_logical_or": lambda a, b: ((a != 0) | (b != 0)).astype(a.dtype),
    "_logical_xor": lambda a, b: ((a != 0) ^ (b != 0)).astype(a.dtype),
    "smooth_l1": lambda a, b=None: None,  # replaced below
}
del _BINARY["smooth_l1"]

for _name, _fn in _BINARY.items():
    register(_name)(_fn)

alias("_add", "elemwise_add")
alias("_plus", "elemwise_add")
alias("_sub", "elemwise_sub")
alias("_minus", "elemwise_sub")
alias("_mul", "elemwise_mul")
alias("_div", "elemwise_div")


@register("_scatter_elemwise_div")
def _scatter_elemwise_div(a, b):
    return a / b


@register("smooth_l1")
def _smooth_l1(x, scalar: float = 1.0):
    jnp = _jnp()
    s2 = scalar * scalar
    return jnp.where(jnp.abs(x) < 1.0 / s2,
                     0.5 * s2 * x * x,
                     jnp.abs(x) - 0.5 / s2)


# ---------------------------------------------------------------------------
# scalar forms — ref: elemwise_binary_scalar_op_*.cc
# ---------------------------------------------------------------------------

def _scalar_op(fwd, rev=None):
    def impl(x, scalar: float = 1.0, reverse: bool = False):
        if reverse and rev is not None:
            return rev(x, scalar)
        return fwd(x, scalar)
    return impl


_SCALAR = {
    "_plus_scalar": _scalar_op(lambda x, s: x + s),
    "_minus_scalar": _scalar_op(lambda x, s: x - s),
    "_rminus_scalar": _scalar_op(lambda x, s: s - x, lambda x, s: s - x),
    "_mul_scalar": _scalar_op(lambda x, s: x * s),
    "_div_scalar": _scalar_op(lambda x, s: x / s),
    "_rdiv_scalar": _scalar_op(lambda x, s: s / x, lambda x, s: s / x),
    "_mod_scalar": _scalar_op(lambda x, s: _jnp().mod(x, s)),
    "_rmod_scalar": _scalar_op(lambda x, s: _jnp().mod(s, x),
                               lambda x, s: _jnp().mod(s, x)),
    "_power_scalar": _scalar_op(lambda x, s: _jnp().power(x, s)),
    "_rpower_scalar": _scalar_op(lambda x, s: _jnp().power(s, x),
                                 lambda x, s: _jnp().power(s, x)),
    "_maximum_scalar": _scalar_op(lambda x, s: _jnp().maximum(x, s)),
    "_minimum_scalar": _scalar_op(lambda x, s: _jnp().minimum(x, s)),
    "_hypot_scalar": _scalar_op(lambda x, s: _jnp().hypot(x, s)),
    "_equal_scalar": _scalar_op(lambda x, s: (x == s).astype(x.dtype)),
    "_not_equal_scalar": _scalar_op(lambda x, s: (x != s).astype(x.dtype)),
    "_greater_scalar": _scalar_op(lambda x, s: (x > s).astype(x.dtype),
                                  lambda x, s: (s > x).astype(x.dtype)),
    "_greater_equal_scalar": _scalar_op(lambda x, s: (x >= s).astype(x.dtype),
                                        lambda x, s: (s >= x).astype(x.dtype)),
    "_lesser_scalar": _scalar_op(lambda x, s: (x < s).astype(x.dtype),
                                 lambda x, s: (s < x).astype(x.dtype)),
    "_lesser_equal_scalar": _scalar_op(lambda x, s: (x <= s).astype(x.dtype),
                                       lambda x, s: (s <= x).astype(x.dtype)),
    "_logical_and_scalar": _scalar_op(lambda x, s: ((x != 0) & bool(s)).astype(x.dtype)),
    "_logical_or_scalar": _scalar_op(lambda x, s: ((x != 0) | bool(s)).astype(x.dtype)),
    "_logical_xor_scalar": _scalar_op(lambda x, s: ((x != 0) ^ bool(s)).astype(x.dtype)),
}

for _name, _fn in _SCALAR.items():
    register(_name)(_fn)


@register("_scatter_plus_scalar")
def _scatter_plus_scalar(x, scalar: float = 1.0, reverse: bool = False):
    return x + scalar


@register("_scatter_minus_scalar")
def _scatter_minus_scalar(x, scalar: float = 1.0, reverse: bool = False):
    return x - scalar


@register("hard_sigmoid")
def _hard_sigmoid(data, alpha=0.2, beta=0.5):
    """max(0, min(1, alpha*x + beta)) (ref: mshadow_op.h hard_sigmoid)."""
    jnp = _jnp()
    return jnp.clip(float(alpha) * data + float(beta), 0.0, 1.0)
