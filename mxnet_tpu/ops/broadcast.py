"""Broadcasting binary ops and broadcast shape manipulation.

Reference: src/operator/tensor/elemwise_binary_broadcast_op_*.cc and
broadcast_reduce_op.h. jnp broadcasting matches the reference's numpy-style
semantics directly; XLA handles the implicit-broadcast fusion that the
reference implements with dedicated kernels.
"""
from __future__ import annotations

from .registry import register


def _jnp():
    import jax.numpy as jnp
    return jnp


_BCAST = {
    "broadcast_add": lambda a, b: a + b,
    "broadcast_sub": lambda a, b: a - b,
    "broadcast_mul": lambda a, b: a * b,
    "broadcast_div": lambda a, b: a / b,
    "broadcast_mod": lambda a, b: _jnp().mod(a, b),
    "broadcast_power": lambda a, b: _jnp().power(a, b),
    "broadcast_maximum": lambda a, b: _jnp().maximum(a, b),
    "broadcast_minimum": lambda a, b: _jnp().minimum(a, b),
    "broadcast_hypot": lambda a, b: _jnp().hypot(a, b),
    "broadcast_equal": lambda a, b: (a == b).astype(a.dtype),
    "broadcast_not_equal": lambda a, b: (a != b).astype(a.dtype),
    "broadcast_greater": lambda a, b: (a > b).astype(a.dtype),
    "broadcast_greater_equal": lambda a, b: (a >= b).astype(a.dtype),
    "broadcast_lesser": lambda a, b: (a < b).astype(a.dtype),
    "broadcast_lesser_equal": lambda a, b: (a <= b).astype(a.dtype),
    "broadcast_logical_and": lambda a, b: ((a != 0) & (b != 0)).astype(a.dtype),
    "broadcast_logical_or": lambda a, b: ((a != 0) | (b != 0)).astype(a.dtype),
    "broadcast_logical_xor": lambda a, b: ((a != 0) ^ (b != 0)).astype(a.dtype),
}

for _name, _fn in _BCAST.items():
    register(_name)(_fn)

register("broadcast_plus")(lambda a, b: a + b)
register("broadcast_minus")(lambda a, b: a - b)


@register("broadcast_to")
def _broadcast_to(x, shape=None):
    jnp = _jnp()
    # reference semantics: 0 in target shape means "keep this dim"
    tgt = tuple(s if t == 0 else t for s, t in zip(x.shape, shape)) \
        if len(shape) == x.ndim else tuple(shape)
    return jnp.broadcast_to(x, tgt)


@register("broadcast_like")
def _broadcast_like(x, y, lhs_axes=None, rhs_axes=None):
    return _jnp().broadcast_to(x, y.shape)


@register("broadcast_axis", aliases=("broadcast_axes",))
def _broadcast_axis(x, axis=(), size=()):
    jnp = _jnp()
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(x.shape)
    for a, s in zip(axes, sizes):
        tgt[a] = s
    return jnp.broadcast_to(x, tuple(tgt))
