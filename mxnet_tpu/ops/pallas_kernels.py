"""Pallas TPU kernels for hot ops.

Where the reference hand-writes CUDA (src/operator/*.cu) or leans on cuDNN,
the TPU build leans on XLA — except where fusion across the softmax is
needed: attention. This module provides a fused attention kernel
(flash-style: per-query-block compute with K/V streamed through VMEM, the
(T, T) score matrix never hits HBM), following the playbook in
/opt/skills/guides/pallas_guide.md.

On non-TPU backends the kernel runs in interpret mode (correct, slow) so
the test suite exercises the same code path.
"""
from __future__ import annotations

import functools
import math

import numpy as _np

from ..base import MXNetError
from .registry import register

_BQ = 128  # query block (MXU-aligned)


_INTERPRET_CACHE = {}


def _interpret_mode() -> bool:
    """True when compiled Pallas lowering is unavailable.

    Platform strings are unreliable here (the axon TPU tunnel reports
    'tpu' while a JAX_PLATFORMS=cpu override can still route lowering to
    the CPU rules), so probe the real capability once: compile a trivial
    kernel; any failure means run in interpret mode.
    """
    import jax
    key = jax.default_backend()
    cached = _INTERPRET_CACHE.get(key)
    if cached is None:
        try:
            clean = jax.core.trace_state_clean()
        except Exception:
            clean = True
        if not clean:
            # Inside a trace the probe's pallas_call would be traced INTO
            # the caller's program as a compiled-mode kernel (and fail at
            # the caller's lowering on CPU backends) instead of compiling
            # eagerly. Fall back to the platform heuristic WITHOUT
            # caching; the next untraced call runs the real probe.
            return key != "tpu"
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def _probe(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        try:
            x = jnp.zeros((8, 128), jnp.float32)
            jax.jit(pl.pallas_call(
                _probe,
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype)))(
                    x).block_until_ready()
            cached = False
        except Exception:
            cached = True
        _INTERPRET_CACHE[key] = cached
    return cached


def _interpret_for(x) -> bool:
    """Per-array interpret decision: an array living on a non-TPU device
    lowers with that device's rules regardless of the default backend
    (mx.cpu() context arrays inside a TPU-default process)."""
    try:
        dev = next(iter(x.devices())) if hasattr(x, "devices") else x.device
        if dev.platform != "tpu":
            return True
    except Exception:
        pass
    return _interpret_mode()


@functools.lru_cache(maxsize=None)
def _build_flash(t: int, d: int, causal: bool, scale: float, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    try:
        from jax.experimental.pallas import tpu as pltpu
        vmem = pltpu.VMEM
    except Exception:  # pragma: no cover
        vmem = None

    bq = min(_BQ, t)

    def kernel(q_ref, k_ref, v_ref, o_ref):
        qi = pl.program_id(1)
        q = q_ref[0].astype(jnp.float32)          # (bq, d)
        k = k_ref[0].astype(jnp.float32)          # (t, d)
        v = v_ref[0].astype(jnp.float32)          # (t, d)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (bq, t)
        if causal:
            qpos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, t), 0)
            kpos = jax.lax.broadcasted_iota(jnp.int32, (bq, t), 1)
            logits = jnp.where(qpos >= kpos, logits, -1e30)
        m = jnp.max(logits, axis=-1, keepdims=True)
        p = jnp.exp(logits - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        o = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32) / l
        o_ref[0] = o.astype(o_ref.dtype)

    def call(q, k, v):
        bh = q.shape[0]
        grid = (bh, t // bq if t % bq == 0 else -(-t // bq))
        specs_kv = pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0))
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            grid=grid,
            in_specs=[pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
                      specs_kv, specs_kv],
            out_specs=pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
            interpret=interpret,
        )(q, k, v)

    return call


def flash_attention(q, k, v, causal: bool = False, scale=None):
    """Fused attention. q,k,v: (B, T, H, D) -> (B, T, H, D).

    Forward is the Pallas kernel; backward recomputes through the reference
    jax formulation (jax.custom_vjp) — numerically identical, and XLA fuses
    the recompute well.
    """
    import jax
    import jax.numpy as jnp
    from ..parallel.ring_attention import attention as ref_attention

    b, t, h, d = q.shape
    sc = scale if scale is not None else 1.0 / math.sqrt(d)

    @jax.custom_vjp
    def _op(q, k, v):
        qt = q.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        kt = k.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        vt = v.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        call = _build_flash(t, d, causal, sc, _interpret_for(q))
        o = call(qt, kt, vt)
        return o.reshape(b, h, t, d).transpose(0, 2, 1, 3)

    def fwd(q, k, v):
        return _op(q, k, v), (q, k, v)

    def bwd(res, g):
        q, k, v = res
        _, vjp = jax.vjp(
            lambda q_, k_, v_: ref_attention(q_, k_, v_, causal=causal,
                                             scale=sc), q, k, v)
        return vjp(g)

    _op.defvjp(fwd, bwd)
    return _op(q, k, v)


@register("_contrib_flash_attention", aliases=("flash_attention",))
def _flash_attention_op(q, k, v, causal=False, scale=None):
    return flash_attention(q, k, v, causal=causal, scale=scale)


@register("_contrib_interleaved_matmul_selfatt_qk")
def _interleaved_qk(qkv, heads=1):
    """(ref: src/operator/contrib/transformer.cc interleaved matmul helpers)
    qkv: (T, B, 3*H*D) interleaved; returns (B*H, T, T) scores."""
    import jax.numpy as jnp
    t, b, three_hd = qkv.shape
    d = three_hd // (3 * heads)
    x = qkv.reshape(t, b, heads, 3, d)
    q = x[:, :, :, 0].transpose(1, 2, 0, 3).reshape(b * heads, t, d)
    k = x[:, :, :, 1].transpose(1, 2, 0, 3).reshape(b * heads, t, d)
    return jnp.matmul(q, k.transpose(0, 2, 1)) / math.sqrt(d)


# ---------------------------------------------------------------------------
# Fused bottleneck epilogues: BatchNorm(+residual add)+ReLU consuming the
# convolution output (the ResNet hot path — docs/perf.md roofline: the bf16
# activations materialized BETWEEN the conv and its BN/ReLU/add epilogue are
# the dominant HBM traffic of the train step). Two passes over the conv
# output, both hand-tiled through VMEM:
#   pass 1 (stats):  per-channel sum / sum-of-squares, f32 accumulators
#   pass 2 (apply):  out = relu(norm(x) [+ residual]), written once
# Backward mirrors it (custom_vjp): the ReLU mask is RE-DERIVED from the
# saved output inside both backward passes, so the masked cotangent — an
# activation-sized intermediate the unfused lowering materializes between
# the ReLU backward and the BN reductions — never touches HBM.
# Channel-last (NHWC) only: C rides the 128-lane minor dim.
# ---------------------------------------------------------------------------

_EPILOGUE_VMEM_BUDGET = 10 * 1024 * 1024  # leave headroom in ~16 MB VMEM


def _epilogue_rows(r: int, c: int, n_bufs: int, interpret: bool,
                   itemsize: int = 2) -> int:
    """Row-block size for the (R, C) flattened activation.

    Interpret mode runs one whole-array block (each grid step is a python
    round-trip; correctness is identical and tests stay fast). Compiled
    mode sizes the block so n_bufs double-buffered (BR, C) tiles fit the
    VMEM budget, 8-row (sublane) aligned."""
    if interpret:
        return max(1, r)
    per_row = max(c, 128) * itemsize  # lane-padded row
    br = _EPILOGUE_VMEM_BUDGET // (2 * n_bufs * per_row)
    br = max(8, min(1024, br - br % 8))
    return max(1, min(br, r))


def _row_mask(i, br, r, xb):
    """Zero rows past R (the last block of a non-divisible grid reads
    padding whose contents are unspecified)."""
    import jax
    import jax.numpy as jnp
    rows = i * br + jax.lax.broadcasted_iota(jnp.int32, (br, 1), 0)
    return jnp.where(rows < r, xb, 0.0)


def _bn_stats_call(x2d, interpret):
    """(R, C) -> (2, C) f32: per-channel [sum, sum of squares]."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    r, c = x2d.shape
    br = _epilogue_rows(r, c, 1, interpret, x2d.dtype.itemsize)

    def kernel(x_ref, acc_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        xb = _row_mask(i, br, r, x_ref[...].astype(jnp.float32))
        acc_ref[0:1, :] += jnp.sum(xb, axis=0, keepdims=True)
        acc_ref[1:2, :] += jnp.sum(xb * xb, axis=0, keepdims=True)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((2, c), jnp.float32),
        grid=(pl.cdiv(r, br),),
        in_specs=[pl.BlockSpec((br, c), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((2, c), lambda i: (0, 0)),
        interpret=interpret,
    )(x2d)


def _bn_apply_call(x2d, res2d, coef, interpret):
    """out = relu(x * coef[0] + coef[1] [+ res]), written once."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    r, c = x2d.shape
    has_res = res2d is not None
    br = _epilogue_rows(r, c, 3 if has_res else 2, interpret,
                        x2d.dtype.itemsize)

    def kernel(*refs):
        if has_res:
            x_ref, res_ref, coef_ref, o_ref = refs
        else:
            x_ref, coef_ref, o_ref = refs
        y = x_ref[...].astype(jnp.float32) * coef_ref[0:1, :] \
            + coef_ref[1:2, :]
        if has_res:
            y = y + res_ref[...].astype(jnp.float32)
        o_ref[...] = jnp.maximum(y, 0.0).astype(o_ref.dtype)

    row_spec = pl.BlockSpec((br, c), lambda i: (i, 0))
    coef_spec = pl.BlockSpec((2, c), lambda i: (0, 0))
    in_specs = [row_spec, row_spec, coef_spec] if has_res \
        else [row_spec, coef_spec]
    args = (x2d, res2d, coef) if has_res else (x2d, coef)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((r, c), x2d.dtype),
        grid=(pl.cdiv(r, br),),
        in_specs=in_specs,
        out_specs=row_spec,
        interpret=interpret,
    )(*args)


def _bn_bwd_stats_call(dy2d, out2d, x2d, coef, interpret):
    """(2, C) f32 per-channel [sum g, sum g*xhat] with g = relu-masked dy
    (mask from the saved output — no materialized masked cotangent) and
    xhat = (x - coef[0]) * coef[1]."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    r, c = x2d.shape
    br = _epilogue_rows(r, c, 3, interpret, x2d.dtype.itemsize)

    def kernel(dy_ref, out_ref, x_ref, coef_ref, acc_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        dyb = dy_ref[...].astype(jnp.float32)
        g = jnp.where(out_ref[...] > 0, dyb, 0.0)
        g = _row_mask(i, br, r, g)
        xhat = (x_ref[...].astype(jnp.float32) - coef_ref[0:1, :]) \
            * coef_ref[1:2, :]
        acc_ref[0:1, :] += jnp.sum(g, axis=0, keepdims=True)
        # mask the PRODUCT (where() selects, so Inf/NaN decoded from the
        # last block's unspecified padding rows cannot produce 0*Inf=NaN
        # in the accumulator — g alone being 0 there is not enough)
        acc_ref[1:2, :] += jnp.sum(_row_mask(i, br, r, g * xhat),
                                   axis=0, keepdims=True)

    row_spec = pl.BlockSpec((br, c), lambda i: (i, 0))
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((2, c), jnp.float32),
        grid=(pl.cdiv(r, br),),
        in_specs=[row_spec, row_spec, row_spec,
                  pl.BlockSpec((2, c), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((2, c), lambda i: (0, 0)),
        interpret=interpret,
    )(dy2d, out2d, x2d, coef)


def _bn_bwd_apply_call(dy2d, out2d, x2d, coef, has_res, interpret):
    """dx = coef[2] * (g - coef[3] - xhat * coef[4]); g re-derived from the
    saved output in-kernel; dres (the residual branch cotangent) is g,
    emitted as a second output of the SAME pass."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    r, c = x2d.shape
    br = _epilogue_rows(r, c, 5 if has_res else 4, interpret,
                        x2d.dtype.itemsize)

    def kernel(*refs):
        if has_res:
            dy_ref, out_ref, x_ref, coef_ref, dx_ref, dres_ref = refs
        else:
            dy_ref, out_ref, x_ref, coef_ref, dx_ref = refs
        g = jnp.where(out_ref[...] > 0, dy_ref[...].astype(jnp.float32),
                      0.0)
        xhat = (x_ref[...].astype(jnp.float32) - coef_ref[0:1, :]) \
            * coef_ref[1:2, :]
        dx = coef_ref[2:3, :] * (g - coef_ref[3:4, :]
                                 - xhat * coef_ref[4:5, :])
        dx_ref[...] = dx.astype(dx_ref.dtype)
        if has_res:
            dres_ref[...] = g.astype(dres_ref.dtype)

    row_spec = pl.BlockSpec((br, c), lambda i: (i, 0))
    out_shape = jax.ShapeDtypeStruct((r, c), x2d.dtype)
    out_shapes = (out_shape, out_shape) if has_res else out_shape
    out_specs = (row_spec, row_spec) if has_res else row_spec
    return pl.pallas_call(
        kernel,
        out_shape=out_shapes,
        grid=(pl.cdiv(r, br),),
        in_specs=[row_spec, row_spec, row_spec,
                  pl.BlockSpec((5, c), lambda i: (0, 0))],
        out_specs=out_specs,
        interpret=interpret,
    )(dy2d, out2d, x2d, coef)


@functools.lru_cache(maxsize=None)
def _build_fused_bn_act(eps: float, has_res: bool, interpret: bool):
    """Training-mode fused BN(+add)+ReLU over (R, C) channel-last data
    with a hand-fused backward (jax.custom_vjp).

    Residuals saved for backward: the bf16 input x (conv output), the
    bf16 output (already materialized for the next layer — XLA CSEs the
    two into one buffer) and the per-channel mean/inv/gamma vectors.
    Returns (out, mean, var); mean/var feed running-stat updates only
    (stop-gradient, like the unfused BatchNorm)."""
    import jax
    import jax.numpy as jnp

    def run_fwd(x2d, res2d, g32, beta32):
        n = float(x2d.shape[0])
        sums = _bn_stats_call(x2d, interpret)
        mean = sums[0] / n
        var = jnp.maximum(sums[1] / n - mean * mean, 0.0)
        inv = jax.lax.rsqrt(var + eps)
        scale = inv * g32
        coef = jnp.stack([scale, beta32 - mean * scale])
        out2d = _bn_apply_call(x2d, res2d, coef, interpret)
        return out2d, mean, var, inv

    def run_bwd(x2d, out2d, mean, inv, g32, dy2d):
        n = float(x2d.shape[0])
        sums = _bn_bwd_stats_call(dy2d, out2d, x2d,
                                  jnp.stack([mean, inv]), interpret)
        sum_g, sum_gxhat = sums[0], sums[1]
        coef = jnp.stack([mean, inv, g32 * inv, sum_g / n,
                          sum_gxhat / n])
        outs = _bn_bwd_apply_call(dy2d, out2d, x2d, coef, has_res,
                                  interpret)
        return outs, sum_g, sum_gxhat

    if has_res:
        @jax.custom_vjp
        def f(x2d, res2d, g32, beta32):
            out2d, mean, var, _ = run_fwd(x2d, res2d, g32, beta32)
            return out2d, mean, var

        def fwd(x2d, res2d, g32, beta32):
            out2d, mean, var, inv = run_fwd(x2d, res2d, g32, beta32)
            return (out2d, mean, var), (x2d, out2d, mean, inv, g32)

        def bwd(res, cots):
            x2d, out2d, mean, inv, g32 = res
            (dx, dres), sum_g, sum_gxhat = run_bwd(x2d, out2d, mean, inv,
                                                   g32, cots[0])
            return dx, dres, sum_gxhat, sum_g
    else:
        @jax.custom_vjp
        def f(x2d, g32, beta32):
            out2d, mean, var, _ = run_fwd(x2d, None, g32, beta32)
            return out2d, mean, var

        def fwd(x2d, g32, beta32):
            out2d, mean, var, inv = run_fwd(x2d, None, g32, beta32)
            return (out2d, mean, var), (x2d, out2d, mean, inv, g32)

        def bwd(res, cots):
            x2d, out2d, mean, inv, g32 = res
            dx, sum_g, sum_gxhat = run_bwd(x2d, out2d, mean, inv, g32,
                                           cots[0])
            return dx, sum_gxhat, sum_g

    f.defvjp(fwd, bwd)
    return f


def fused_bn_act(data, residual, gamma32, beta32, eps):
    """Fused training-mode ``BatchNorm [+ add(residual)] + ReLU`` epilogue.

    ``data``: channel-LAST activation (the conv output); ``residual``:
    same shape or None; ``gamma32``/``beta32``: f32 ``(C,)`` vectors.
    Returns ``(out, mean, var)`` with out in data's dtype and f32 batch
    stats. Dispatches compiled Pallas on TPU, interpret mode elsewhere
    (same code path, so CPU tests exercise the real kernels)."""
    c = data.shape[-1]
    x2d = data.reshape(-1, c)
    interpret = _interpret_for(data)
    f = _build_fused_bn_act(float(eps), residual is not None, interpret)
    if residual is not None:
        out2d, mean, var = f(x2d, residual.reshape(-1, c), gamma32, beta32)
    else:
        out2d, mean, var = f(x2d, gamma32, beta32)
    return out2d.reshape(data.shape), mean, var


@register("_contrib_interleaved_matmul_selfatt_valatt")
def _interleaved_valatt(qkv, att, heads=1):
    import jax.numpy as jnp
    t, b, three_hd = qkv.shape
    d = three_hd // (3 * heads)
    x = qkv.reshape(t, b, heads, 3, d)
    v = x[:, :, :, 2].transpose(1, 2, 0, 3).reshape(b * heads, t, d)
    out = jnp.matmul(att, v)  # (B*H, T, D)
    return out.reshape(b, heads, t, d).transpose(2, 0, 1, 3) \
        .reshape(t, b, heads * d)
