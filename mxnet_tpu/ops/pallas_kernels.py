"""Pallas TPU kernels for hot ops.

Where the reference hand-writes CUDA (src/operator/*.cu) or leans on cuDNN,
the TPU build leans on XLA — except where fusion across the softmax is
needed: attention. This module provides a fused attention kernel
(flash-style: per-query-block compute with K/V streamed through VMEM, the
(T, T) score matrix never hits HBM), following the playbook in
/opt/skills/guides/pallas_guide.md.

On non-TPU backends the kernel runs in interpret mode (correct, slow) so
the test suite exercises the same code path.
"""
from __future__ import annotations

import functools
import math

import numpy as _np

from ..base import MXNetError
from .registry import register

_BQ = 128  # query block (MXU-aligned)


_INTERPRET_CACHE = {}


def _interpret_mode() -> bool:
    """True when compiled Pallas lowering is unavailable.

    Platform strings are unreliable here (the axon TPU tunnel reports
    'tpu' while a JAX_PLATFORMS=cpu override can still route lowering to
    the CPU rules), so probe the real capability once: compile a trivial
    kernel; any failure means run in interpret mode.
    """
    import jax
    key = jax.default_backend()
    cached = _INTERPRET_CACHE.get(key)
    if cached is None:
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def _probe(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        try:
            x = jnp.zeros((8, 128), jnp.float32)
            jax.jit(pl.pallas_call(
                _probe,
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype)))(
                    x).block_until_ready()
            cached = False
        except Exception:
            cached = True
        _INTERPRET_CACHE[key] = cached
    return cached


def _interpret_for(x) -> bool:
    """Per-array interpret decision: an array living on a non-TPU device
    lowers with that device's rules regardless of the default backend
    (mx.cpu() context arrays inside a TPU-default process)."""
    try:
        dev = next(iter(x.devices())) if hasattr(x, "devices") else x.device
        if dev.platform != "tpu":
            return True
    except Exception:
        pass
    return _interpret_mode()


@functools.lru_cache(maxsize=None)
def _build_flash(t: int, d: int, causal: bool, scale: float, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    try:
        from jax.experimental.pallas import tpu as pltpu
        vmem = pltpu.VMEM
    except Exception:  # pragma: no cover
        vmem = None

    bq = min(_BQ, t)

    def kernel(q_ref, k_ref, v_ref, o_ref):
        qi = pl.program_id(1)
        q = q_ref[0].astype(jnp.float32)          # (bq, d)
        k = k_ref[0].astype(jnp.float32)          # (t, d)
        v = v_ref[0].astype(jnp.float32)          # (t, d)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (bq, t)
        if causal:
            qpos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, t), 0)
            kpos = jax.lax.broadcasted_iota(jnp.int32, (bq, t), 1)
            logits = jnp.where(qpos >= kpos, logits, -1e30)
        m = jnp.max(logits, axis=-1, keepdims=True)
        p = jnp.exp(logits - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        o = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32) / l
        o_ref[0] = o.astype(o_ref.dtype)

    def call(q, k, v):
        bh = q.shape[0]
        grid = (bh, t // bq if t % bq == 0 else -(-t // bq))
        specs_kv = pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0))
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            grid=grid,
            in_specs=[pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
                      specs_kv, specs_kv],
            out_specs=pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
            interpret=interpret,
        )(q, k, v)

    return call


def flash_attention(q, k, v, causal: bool = False, scale=None):
    """Fused attention. q,k,v: (B, T, H, D) -> (B, T, H, D).

    Forward is the Pallas kernel; backward recomputes through the reference
    jax formulation (jax.custom_vjp) — numerically identical, and XLA fuses
    the recompute well.
    """
    import jax
    import jax.numpy as jnp
    from ..parallel.ring_attention import attention as ref_attention

    b, t, h, d = q.shape
    sc = scale if scale is not None else 1.0 / math.sqrt(d)

    @jax.custom_vjp
    def _op(q, k, v):
        qt = q.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        kt = k.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        vt = v.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        call = _build_flash(t, d, causal, sc, _interpret_for(q))
        o = call(qt, kt, vt)
        return o.reshape(b, h, t, d).transpose(0, 2, 1, 3)

    def fwd(q, k, v):
        return _op(q, k, v), (q, k, v)

    def bwd(res, g):
        q, k, v = res
        _, vjp = jax.vjp(
            lambda q_, k_, v_: ref_attention(q_, k_, v_, causal=causal,
                                             scale=sc), q, k, v)
        return vjp(g)

    _op.defvjp(fwd, bwd)
    return _op(q, k, v)


@register("_contrib_flash_attention", aliases=("flash_attention",))
def _flash_attention_op(q, k, v, causal=False, scale=None):
    return flash_attention(q, k, v, causal=causal, scale=scale)


@register("_contrib_interleaved_matmul_selfatt_qk")
def _interleaved_qk(qkv, heads=1):
    """(ref: src/operator/contrib/transformer.cc interleaved matmul helpers)
    qkv: (T, B, 3*H*D) interleaved; returns (B*H, T, T) scores."""
    import jax.numpy as jnp
    t, b, three_hd = qkv.shape
    d = three_hd // (3 * heads)
    x = qkv.reshape(t, b, heads, 3, d)
    q = x[:, :, :, 0].transpose(1, 2, 0, 3).reshape(b * heads, t, d)
    k = x[:, :, :, 1].transpose(1, 2, 0, 3).reshape(b * heads, t, d)
    return jnp.matmul(q, k.transpose(0, 2, 1)) / math.sqrt(d)


@register("_contrib_interleaved_matmul_selfatt_valatt")
def _interleaved_valatt(qkv, att, heads=1):
    import jax.numpy as jnp
    t, b, three_hd = qkv.shape
    d = three_hd // (3 * heads)
    x = qkv.reshape(t, b, heads, 3, d)
    v = x[:, :, :, 2].transpose(1, 2, 0, 3).reshape(b * heads, t, d)
    out = jnp.matmul(att, v)  # (B*H, T, D)
    return out.reshape(b, heads, t, d).transpose(2, 0, 1, 3) \
        .reshape(t, b, heads * d)
