"""Fused optimizer-update ops.

Reference: src/operator/optimizer_op.cc (sgd_update :318, sgd_mom_update
:351, adam_update :506, multi_sgd :654 etc.) — device-side fused updates so
the frontend never materializes intermediate tensors.

TPU-native: each update is a small pure function; XLA fuses the whole
expression into one kernel. The reference mutates weight/state in place;
here the op *returns* (weight', state'...) and the Optimizer frontend rebinds
the NDArray handles (versioned-var discipline). Multi-tensor variants take
interleaved inputs and return all updated tensors so one jit call covers the
whole parameter group.
"""
from __future__ import annotations

from .registry import register


def _jnp():
    import jax.numpy as jnp
    return jnp


def _prep_grad(grad, rescale_grad, clip_gradient, wd, weight):
    jnp = _jnp()
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g + wd * weight


@register("sgd_update", dynamic_params=("lr",))
def _sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                clip_gradient=-1.0, lazy_update=True):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    return weight - lr * g


@register("sgd_mom_update", dynamic_params=("lr",), num_outputs=2)
def _sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom - lr * g
    return weight + new_mom, new_mom


@register("mp_sgd_update", dynamic_params=("lr",), num_outputs=2)
def _mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, lazy_update=True):
    g = _prep_grad(grad.astype(weight32.dtype), rescale_grad, clip_gradient,
                   wd, weight32)
    w32 = weight32 - lr * g
    return w32.astype(weight.dtype), w32


@register("mp_sgd_mom_update", dynamic_params=("lr",), num_outputs=3)
def _mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                       wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                       lazy_update=True):
    g = _prep_grad(grad.astype(weight32.dtype), rescale_grad, clip_gradient,
                   wd, weight32)
    new_mom = momentum * mom - lr * g
    w32 = weight32 + new_mom
    return w32.astype(weight.dtype), new_mom, w32


@register("nag_mom_update", dynamic_params=("lr",), num_outputs=2)
def _nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register("adam_update", dynamic_params=("lr",), num_outputs=3)
def _adam_update(weight, grad, mean, var, lr=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                 lazy_update=True):
    jnp = _jnp()
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    w = weight - lr * m / (jnp.sqrt(v) + epsilon)
    return w, m, v


@register("signsgd_update", dynamic_params=("lr",))
def _signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0):
    jnp = _jnp()
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register("signum_update", dynamic_params=("lr",), num_outputs=2)
def _signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    jnp = _jnp()
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mom = momentum * mom - (1 - momentum) * g
    w = (1 - lr * wd_lh) * weight + lr * jnp.sign(new_mom) \
        if wd_lh > 0 else weight + lr * jnp.sign(new_mom)
    return w - lr * wd * weight, new_mom


@register("rmsprop_update", dynamic_params=("lr",), num_outputs=2)
def _rmsprop_update(weight, grad, n, lr=0.01, gamma1=0.95, epsilon=1e-8,
                    wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                    clip_weights=-1.0):
    jnp = _jnp()
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    w = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, new_n


@register("rmspropalex_update", dynamic_params=("lr",), num_outputs=4)
def _rmspropalex_update(weight, grad, n, g_avg, delta, lr=0.01, gamma1=0.95,
                        gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                        clip_gradient=-1.0, clip_weights=-1.0):
    jnp = _jnp()
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    new_gavg = gamma1 * g_avg + (1 - gamma1) * g
    new_delta = gamma2 * delta - lr * g / jnp.sqrt(
        new_n - jnp.square(new_gavg) + epsilon)
    return weight + new_delta, new_n, new_gavg, new_delta


@register("ftrl_update", dynamic_params=("lr",), num_outputs=3)
def _ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                 rescale_grad=1.0, clip_gradient=-1.0):
    jnp = _jnp()
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    w = jnp.where(jnp.abs(new_z) > lamda1,
                  -(new_z - jnp.sign(new_z) * lamda1) /
                  ((beta + jnp.sqrt(new_n)) / lr + wd),
                  0.0)
    return w, new_z, new_n


@register("ftml_update", dynamic_params=("lr", "t"), num_outputs=3)
def _ftml_update(weight, grad, d, v, z, lr=0.01, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, t=1, wd=0.0, rescale_grad=1.0,
                 clip_grad=-1.0):
    jnp = _jnp()
    g = grad * rescale_grad + wd * weight
    if clip_grad is not None and clip_grad > 0:
        g = jnp.clip(g, -clip_grad, clip_grad)
    new_v = beta2 * v + (1 - beta2) * jnp.square(g)
    d_t = (1 - beta1 ** t) / lr * (jnp.sqrt(new_v / (1 - beta2 ** t)) + epsilon)
    sigma = d_t - beta1 * d
    new_z = beta1 * z + (1 - beta1) * g - sigma * weight
    return -new_z / d_t, d_t, new_v


@register("_adamw_update", dynamic_params=("lr",), aliases=("adamw_update",), num_outputs=3)
def _adamw_update(weight, grad, mean, var, rescale_grad_t, lr=0.01, beta1=0.9,
                  beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0,
                  clip_gradient=-1.0):
    jnp = _jnp()
    g = grad * rescale_grad_t
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    w = weight - eta * (lr * m / (jnp.sqrt(v) + epsilon) + wd * weight)
    # skip the update when the dynamic-loss-scale factor overflowed or is 0
    # (ref: adamw.cc:44 skips on !isfinite(scalef) || scalef == 0)
    ok = jnp.isfinite(rescale_grad_t).all() & (rescale_grad_t != 0).all()
    return (jnp.where(ok, w, weight), jnp.where(ok, m, mean),
            jnp.where(ok, v, var))


def _multi_sgd_nout(n_inputs, params):
    return int(params.get("num_weights", n_inputs // 2))


@register("multi_sgd_update", num_outputs=_multi_sgd_nout, variadic=True)
def _multi_sgd_update(*tensors, lrs=(), wds=(), rescale_grad=1.0,
                      clip_gradient=-1.0, num_weights=1):
    """Fused update of a whole parameter group in one XLA program
    (ref: optimizer_op.cc:654 multi_sgd_update)."""
    outs = []
    for i in range(num_weights):
        w, g = tensors[2 * i], tensors[2 * i + 1]
        outs.append(_sgd_update(w, g, lr=lrs[i], wd=wds[i],
                                rescale_grad=rescale_grad,
                                clip_gradient=clip_gradient))
    return tuple(outs)


def _multi_sgd_mom_nout(n_inputs, params):
    return 2 * int(params.get("num_weights", n_inputs // 3))


@register("multi_sgd_mom_update", num_outputs=_multi_sgd_mom_nout,
          variadic=True)
def _multi_sgd_mom_update(*tensors, lrs=(), wds=(), momentum=0.0,
                          rescale_grad=1.0, clip_gradient=-1.0,
                          num_weights=1):
    outs = []
    moms = []
    for i in range(num_weights):
        w, g, m = tensors[3 * i], tensors[3 * i + 1], tensors[3 * i + 2]
        nw, nm = _sgd_mom_update(w, g, m, lr=lrs[i], momentum=momentum,
                                 wd=wds[i], rescale_grad=rescale_grad,
                                 clip_gradient=clip_gradient)
        outs.append(nw)
        moms.append(nm)
    return tuple(outs) + tuple(moms)


def _multi_mp_sgd_nout(n_inputs, params):
    return 2 * int(params.get("num_weights", n_inputs // 3))


@register("multi_mp_sgd_update", num_outputs=_multi_mp_sgd_nout,
          variadic=True)
def _multi_mp_sgd_update(*tensors, lrs=(), wds=(), rescale_grad=1.0,
                         clip_gradient=-1.0, num_weights=1):
    """Multi-tensor multi-precision SGD (ref: optimizer_op.cc
    multi_mp_sgd_update): (weight, grad, weight32) triplets."""
    ws, w32s = [], []
    for i in range(num_weights):
        w, g, w32 = tensors[3 * i], tensors[3 * i + 1], tensors[3 * i + 2]
        nw, nw32 = _mp_sgd_update(w, g, w32, lr=lrs[i], wd=wds[i],
                                  rescale_grad=rescale_grad,
                                  clip_gradient=clip_gradient)
        ws.append(nw)
        w32s.append(nw32)
    return tuple(ws) + tuple(w32s)


def _multi_mp_sgd_mom_nout(n_inputs, params):
    return 3 * int(params.get("num_weights", n_inputs // 4))


@register("multi_mp_sgd_mom_update", num_outputs=_multi_mp_sgd_mom_nout,
          variadic=True)
def _multi_mp_sgd_mom_update(*tensors, lrs=(), wds=(), momentum=0.0,
                             rescale_grad=1.0, clip_gradient=-1.0,
                             num_weights=1):
    """Multi-tensor multi-precision SGD w/ momentum (ref: optimizer_op.cc
    multi_mp_sgd_mom_update): (weight, grad, mom, weight32) quadruplets."""
    ws, moms, w32s = [], [], []
    for i in range(num_weights):
        w, g, m, w32 = tensors[4 * i:4 * i + 4]
        nw, nm, nw32 = _mp_sgd_mom_update(
            w, g, m, w32, lr=lrs[i], momentum=momentum, wd=wds[i],
            rescale_grad=rescale_grad, clip_gradient=clip_gradient)
        ws.append(nw)
        moms.append(nm)
        w32s.append(nw32)
    return tuple(ws) + tuple(moms) + tuple(w32s)


def _multi_adam_nout(n_inputs, params):
    return 3 * int(params.get("num_weights", n_inputs // 4))


@register("multi_adam_update", num_outputs=_multi_adam_nout, variadic=True)
def _multi_adam_update(*tensors, lrs=(), wds=(), beta1=0.9, beta2=0.999,
                       epsilon=1e-8, rescale_grad=1.0, clip_gradient=-1.0,
                       num_weights=1):
    """Multi-tensor Adam over (weight, grad, mean, var) quadruplets
    (extends the reference's multi_sgd family — optimizer_op.cc:654 — to
    Adam; ``lrs`` arrive bias-corrected like the single-tensor op). The
    Trainer hot path uses the signature-cached pytree programs of
    optimizer/grouped.py built from the SAME per-param kernel; this op is
    the imperative/symbolic surface of the fused group update."""
    ws, ms, vs = [], [], []
    for i in range(num_weights):
        w, g, m, v = tensors[4 * i:4 * i + 4]
        nw, nm, nv = _adam_update(w, g, m, v, lr=lrs[i], beta1=beta1,
                                  beta2=beta2, epsilon=epsilon, wd=wds[i],
                                  rescale_grad=rescale_grad,
                                  clip_gradient=clip_gradient)
        ws.append(nw)
        ms.append(nm)
        vs.append(nv)
    return tuple(ws) + tuple(ms) + tuple(vs)


def _multi_nag_nout(n_inputs, params):
    return 2 * int(params.get("num_weights", n_inputs // 3))


@register("multi_nag_mom_update", num_outputs=_multi_nag_nout, variadic=True)
def _multi_nag_mom_update(*tensors, lrs=(), wds=(), momentum=0.0,
                          rescale_grad=1.0, clip_gradient=-1.0,
                          num_weights=1):
    """Multi-tensor Nesterov momentum over (weight, grad, mom) triplets
    (the reference ships preloaded_multi_sgd variants; NAG rides the same
    grouping here)."""
    ws, moms = [], []
    for i in range(num_weights):
        w, g, m = tensors[3 * i:3 * i + 3]
        nw, nm = _nag_mom_update(w, g, m, lr=lrs[i], momentum=momentum,
                                 wd=wds[i], rescale_grad=rescale_grad,
                                 clip_gradient=clip_gradient)
        ws.append(nw)
        moms.append(nm)
    return tuple(ws) + tuple(moms)


def _multi_rmsprop_nout(n_inputs, params):
    return 2 * int(params.get("num_weights", n_inputs // 3))


@register("multi_rmsprop_update", num_outputs=_multi_rmsprop_nout,
          variadic=True)
def _multi_rmsprop_update(*tensors, lrs=(), wds=(), gamma1=0.95,
                          epsilon=1e-8, rescale_grad=1.0,
                          clip_gradient=-1.0, clip_weights=-1.0,
                          num_weights=1):
    """Multi-tensor RMSProp over (weight, grad, n) triplets."""
    ws, ns = [], []
    for i in range(num_weights):
        w, g, n = tensors[3 * i:3 * i + 3]
        nw, nn = _rmsprop_update(w, g, n, lr=lrs[i], gamma1=gamma1,
                                 epsilon=epsilon, wd=wds[i],
                                 rescale_grad=rescale_grad,
                                 clip_gradient=clip_gradient,
                                 clip_weights=clip_weights)
        ws.append(nw)
        ns.append(nn)
    return tuple(ws) + tuple(ns)


@register("_contrib_group_adagrad_update", dynamic_params=("lr",),
          aliases=("group_adagrad_update",), num_outputs=2)
def _group_adagrad_update(weight, grad, history, lr=0.01, rescale_grad=1.0,
                          clip_gradient=-1.0, epsilon=1e-5):
    """Group AdaGrad: one shared accumulator per row
    (ref: src/operator/contrib/optimizer_op-inl.h GroupAdagradDnsRspKernel
    — history[row] += mean(g[row]^2); w -= lr*g/sqrt(history+eps))."""
    jnp = _jnp()
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    row_axes = tuple(range(1, g.ndim))
    new_hist = history + jnp.mean(jnp.square(g), axis=row_axes)
    denom = jnp.sqrt(new_hist + epsilon).reshape((-1,) + (1,) * len(row_axes))
    return weight - lr * g / denom, new_hist


@register("_sparse_adagrad_update", dynamic_params=("lr",), aliases=("sparse_adagrad_update",),
          num_outputs=2)
def _sparse_adagrad_update(weight, grad, history, lr=0.01, rescale_grad=1.0,
                           clip_gradient=-1.0, epsilon=1e-7, wd=0.0):
    """AdaGrad update (ref: optimizer_op-inl.h AdagradDnsRspDnsKernel:1994
    — history += g^2; w -= lr*g/sqrt(history+eps)). The reference kernel is
    row_sparse-gradient-only; rows with zero gradient are untouched here
    too since their g^2 contribution is zero."""
    jnp = _jnp()
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    if wd:
        g = g + wd * weight
    new_hist = history + jnp.square(g)
    return weight - lr * g / jnp.sqrt(new_hist + epsilon), new_hist


@register("_mp_adamw_update", dynamic_params=("lr",), aliases=("mp_adamw_update",), num_outputs=4)
def _mp_adamw_update(weight, grad, mean, var, weight32, rescale_grad_t,
                     lr=0.01, beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                     eta=1.0, clip_gradient=-1.0):
    """Multi-precision AdamW (ref: src/operator/contrib/adamw.cc
    _mp_adamw_update): fp32 master weights, decoupled weight decay,
    tensor-valued rescale_grad for dynamic loss scaling."""
    jnp = _jnp()
    g = grad.astype(weight32.dtype) * rescale_grad_t
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    w32 = weight32 - eta * (lr * m / (jnp.sqrt(v) + epsilon)
                            + wd * weight32)
    # dynamic loss scaling: a non-finite or zero rescale_grad means the
    # scaled loss overflowed — skip the whole update so training recovers
    # (ref: adamw.cc:44 skips on !isfinite(scalef) || scalef == 0)
    ok = jnp.isfinite(rescale_grad_t).all() & (rescale_grad_t != 0).all()
    return (jnp.where(ok, w32.astype(weight.dtype), weight),
            jnp.where(ok, m, mean), jnp.where(ok, v, var),
            jnp.where(ok, w32, weight32))
