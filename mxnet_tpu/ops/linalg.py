"""Linear-algebra operators (ref: src/operator/tensor/la_op.cc on LAPACK /
src/operator/c_lapack_api.h). XLA provides native lowerings for all of
these on TPU; names/semantics mirror the reference's _linalg_* family
(batch dims leading, lower-triangular convention)."""
from __future__ import annotations

import numpy as _np

from .registry import register


def _jnp():
    import jax.numpy as jnp
    return jnp


def _jsl():
    import jax.scipy.linalg as jsl
    return jsl


@register("_linalg_gemm2", aliases=("linalg_gemm2",))
def _gemm2(a, b, transpose_a=False, transpose_b=False, alpha=1.0, axis=-2):
    jnp = _jnp()
    x = jnp.swapaxes(a, -1, -2) if transpose_a else a
    y = jnp.swapaxes(b, -1, -2) if transpose_b else b
    return alpha * jnp.matmul(x, y)


@register("_linalg_gemm", aliases=("linalg_gemm",))
def _gemm(a, b, c, transpose_a=False, transpose_b=False, alpha=1.0,
          beta=1.0, axis=-2):
    return _gemm2(a, b, transpose_a, transpose_b, alpha) + beta * c


@register("_linalg_potrf", aliases=("linalg_potrf",))
def _potrf(a, lower=True):
    jnp = _jnp()
    l = jnp.linalg.cholesky(a)
    return l if lower else jnp.swapaxes(l, -1, -2)


@register("_linalg_potri", aliases=("linalg_potri",))
def _potri(l, lower=True):
    # inverse of A from its cholesky factor: A^-1 = (L L^T)^-1
    jnp = _jnp()
    eye = jnp.broadcast_to(jnp.eye(l.shape[-1], dtype=l.dtype), l.shape)
    linv = _jsl().solve_triangular(l, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)


@register("_linalg_trsm", aliases=("linalg_trsm",))
def _trsm(a, b, transpose=False, rightside=False, lower=True, alpha=1.0):
    jsl, jnp = _jsl(), _jnp()
    if rightside:
        # X A = alpha B  ->  A^T X^T = alpha B^T
        xt = jsl.solve_triangular(jnp.swapaxes(a, -1, -2),
                                  jnp.swapaxes(alpha * b, -1, -2),
                                  lower=not lower,
                                  trans=1 if transpose else 0)
        return jnp.swapaxes(xt, -1, -2)
    return jsl.solve_triangular(a, alpha * b, lower=lower,
                                trans=1 if transpose else 0)


@register("_linalg_trmm", aliases=("linalg_trmm",))
def _trmm(a, b, transpose=False, rightside=False, lower=True, alpha=1.0):
    jnp = _jnp()
    tri = jnp.tril(a) if lower else jnp.triu(a)
    if transpose:
        tri = jnp.swapaxes(tri, -1, -2)
    return alpha * (jnp.matmul(b, tri) if rightside else jnp.matmul(tri, b))


@register("_linalg_syrk", aliases=("linalg_syrk",))
def _syrk(a, transpose=False, alpha=1.0):
    jnp = _jnp()
    at = jnp.swapaxes(a, -1, -2)
    return alpha * (jnp.matmul(at, a) if transpose else jnp.matmul(a, at))


@register("_linalg_gelqf", aliases=("linalg_gelqf",), num_outputs=2)
def _gelqf(a):
    # LQ: A = L Q with Q orthonormal rows — via QR of A^T
    jnp = _jnp()
    q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2))
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@register("_linalg_syevd", aliases=("linalg_syevd",), num_outputs=2)
def _syevd(a):
    jnp = _jnp()
    w, v = jnp.linalg.eigh(a)
    return jnp.swapaxes(v, -1, -2), w


@register("_linalg_sumlogdiag", aliases=("linalg_sumlogdiag",))
def _sumlogdiag(a):
    jnp = _jnp()
    d = jnp.diagonal(a, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(d), axis=-1)


@register("_linalg_extractdiag", aliases=("linalg_extractdiag",))
def _extractdiag(a, offset=0):
    return _jnp().diagonal(a, offset=offset, axis1=-2, axis2=-1)


@register("_linalg_makediag", aliases=("linalg_makediag",))
def _makediag(a, offset=0):
    jnp = _jnp()
    n = a.shape[-1] + abs(offset)
    out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
    idx = jnp.arange(a.shape[-1])
    if offset >= 0:
        return out.at[..., idx, idx + offset].set(a)
    return out.at[..., idx - offset, idx].set(a)


@register("_linalg_extracttrian", aliases=("linalg_extracttrian",))
def _extracttrian(a, offset=0, lower=True):
    jnp = _jnp()
    n = a.shape[-1]
    rows, cols = jnp.tril_indices(n, k=offset) if lower \
        else jnp.triu_indices(n, k=offset)
    return a[..., rows, cols]


@register("_linalg_maketrian", aliases=("linalg_maketrian",))
def _maketrian(a, offset=0, lower=True):
    jnp = _jnp()
    # infer n from vector length: len = n(n+1)/2 for offset 0
    ln = a.shape[-1]
    n = int((_np.sqrt(8 * ln + 1) - 1) / 2) + abs(offset)
    rows, cols = jnp.tril_indices(n, k=offset) if lower \
        else jnp.triu_indices(n, k=offset)
    out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
    return out.at[..., rows, cols].set(a)


@register("_linalg_inverse", aliases=("linalg_inverse", "inverse"))
def _inverse(a):
    return _jnp().linalg.inv(a)


@register("_linalg_det", aliases=("linalg_det", "det"))
def _det(a):
    return _jnp().linalg.det(a)


@register("_linalg_slogdet", aliases=("linalg_slogdet", "slogdet"),
          num_outputs=2)
def _slogdet(a):
    sign, logdet = _jnp().linalg.slogdet(a)
    return sign, logdet


@register("moments", num_outputs=2)
def _moments(data, axes=None, keepdims=False):
    jnp = _jnp()
    ax = tuple(axes) if axes is not None else None
    return jnp.mean(data, axis=ax, keepdims=keepdims), \
        jnp.var(data, axis=ax, keepdims=keepdims)


@register("histogram", differentiable=False, num_outputs=2)
def _histogram(data, *maybe_bins, bin_cnt=None, range=None):
    jnp = _jnp()
    if maybe_bins:
        hist, edges = jnp.histogram(data.ravel(), bins=maybe_bins[0])
    else:
        lo, hi = range if range is not None else (float(data.min()),
                                                  float(data.max()))
        hist, edges = jnp.histogram(data.ravel(), bins=bin_cnt,
                                    range=(lo, hi))
    return hist, edges
