"""Storage-type-aware operator kernels (the FComputeEx layer).

Reference: include/mxnet/op_attr_types.h:122,282 — ops carry an
``FInferStorageType`` attribute plus an ``FComputeEx`` kernel operating on
NDArrays with non-default storage; src/operator/tensor/dot-inl.h implements
csr×dense and csrᵀ×dense→row_sparse; src/operator/tensor/indexing_op.cc
implements the row_sparse Embedding gradient.

TPU-native design
-----------------
XLA has no sparse tensor type, so every sparse kernel here is a *static-shape
gather/scatter program* over the compact (data, indices[, indptr]) arrays:

- ``dot(csr, dense)``: one gather of the rhs rows named by ``indices``, a
  broadcast multiply with ``data``, and a segment-sum scatter-add keyed by the
  expanded row ids. All three map directly onto TPU-friendly XLA HLO
  (Gather/Scatter with add-combiner); no densification of the lhs ever
  happens, so FLOPs and HBM traffic scale with nnz, not rows×cols.
- nnz is padded to power-of-two buckets so the jit cache sees a bounded set
  of shapes across batches with varying sparsity (padding rows multiply by
  zero data and scatter to row 0 — numerically inert).
- ``dot(csr.T, dense)`` returns **row_sparse** (ref: dot-inl.h forward_stype
  dispatch): the scatter target is the compact set of distinct columns, so
  output memory scales with the number of touched rows.
- The row_sparse Embedding/dot gradient is *never materialized dense*: the
  tape carries a (data, indices) cotangent (`autograd._RspGrad`) with
  duplicates allowed; unique-row compaction happens once at grad delivery.
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import numpy as _np

from ..base import MXNetError, check
from .registry import register, register_sparse


def _jnp():
    import jax.numpy as jnp
    return jnp


def _jax():
    import jax
    return jax


# ---------------------------------------------------------------------------
# nnz bucketing: bound the number of compiled kernel variants
# ---------------------------------------------------------------------------

def _nnz_bucket(nnz: int) -> int:
    """Round up to the next power of two (min 8) so batches with varying
    sparsity reuse compiled programs instead of recompiling per nnz."""
    b = 8
    while b < nnz:
        b <<= 1
    return b


def _padded_coords(csr) -> Tuple:
    """(data, cols, row_ids) padded to an nnz bucket, as jax arrays.

    Padding entries carry data=0 and scatter to row/col 0, contributing
    nothing to any product or sum. Coordinates come from the csr's cached
    host arrays — no device→host sync in the hot path.
    """
    jnp = _jnp()
    data = csr._data
    cols = csr._indices_np
    row_ids = csr._row_ids()
    nnz = int(data.shape[0])
    pad = _nnz_bucket(nnz) - nnz
    if pad:
        data = jnp.concatenate([data, jnp.zeros((pad,), data.dtype)])
        cols = _np.concatenate([cols, _np.zeros((pad,), _np.int32)])
        row_ids = _np.concatenate([row_ids, _np.zeros((pad,), _np.int32)])
    return data, jnp.asarray(cols), jnp.asarray(row_ids)


# ---------------------------------------------------------------------------
# compiled kernels (cached per shape by jax.jit)
# ---------------------------------------------------------------------------

def _csr_dot_kernel(n_rows: int):
    """out[r, :] = Σ_nnz∈row(r) data · rhs[col]  — csr × dense."""
    jax = _jax()

    @partial(jax.jit, static_argnums=())
    def kern(data, cols, row_ids, rhs):
        jnp = _jnp()
        contrib = data[:, None] * rhs[cols]
        out = jnp.zeros((n_rows, rhs.shape[1]), contrib.dtype)
        return out.at[row_ids].add(contrib)

    return kern


_CSR_DOT_CACHE = {}


def _csr_dot(csr, rhs_2d):
    """csr (M,K) × dense (K,N) → dense (M,N), fully on device."""
    kern = _CSR_DOT_CACHE.get(csr.shape[0])
    if kern is None:
        kern = _CSR_DOT_CACHE[csr.shape[0]] = _csr_dot_kernel(csr.shape[0])
    data, cols, row_ids = _padded_coords(csr)
    return kern(data, cols, row_ids, rhs_2d)


def _csr_t_dot_scatter(data, cols, row_ids, rhs, inv, n_uniq):
    """Compact csrᵀ × dense: scatter contributions straight into the
    unique-column slots (`inv` maps each nnz to its slot), so memory is
    O(touched_rows × N) — never O(K × N)."""
    jnp = _jnp()
    contrib = data[:, None] * rhs[row_ids]
    out = jnp.zeros((n_uniq, rhs.shape[1]), contrib.dtype)
    return out.at[inv].add(contrib)


_CSR_T_DOT_JIT = None


def _csr_t_dot(csr, rhs_2d):
    """csrᵀ (K,M) × dense (M,N) → row_sparse (K,N)."""
    global _CSR_T_DOT_JIT
    if _CSR_T_DOT_JIT is None:
        _CSR_T_DOT_JIT = _jax().jit(_csr_t_dot_scatter, static_argnums=(5,))
    data, cols, row_ids = _padded_coords(csr)
    # unique touched columns from the cached host indices (real nnz only);
    # padding entries carry zero data and are routed to slot 0,
    # contributing nothing
    nnz = int(csr._data.shape[0])
    uniq, inv = _np.unique(csr._indices_np, return_inverse=True)
    inv = _np.concatenate([inv, _np.zeros((int(cols.shape[0]) - nnz,),
                                          inv.dtype)])
    # bucket the slot count too, so varying touched-column counts across
    # batches reuse one compiled scatter (trailing slots stay zero)
    n_slots = _nnz_bucket(len(uniq))
    out_rows = _CSR_T_DOT_JIT(data, cols, row_ids,
                              rhs_2d, _jnp().asarray(inv, _np.int32),
                              n_slots)[:len(uniq)]
    return out_rows, uniq.astype(_np.int32)


# ---------------------------------------------------------------------------
# FComputeEx registrations (consumed by ndarray.register dispatch)
# ---------------------------------------------------------------------------

class _CsrDotBackward:
    """Tape hook for dot(csr, dense): grad wrt the dense rhs is row-sparse
    in the csr's column space (ref: dot-inl.h backward stype =
    csrᵀ×grad→row_sparse). The cotangent is shipped as a duplicate-tolerant
    (data, indices) pair over the REAL nnz (no padding — these are eager
    ops, and padded column ids would leak a spurious row 0 into the lazy
    optimizer update); compaction happens at delivery."""

    def __init__(self, csr, rhs_was_1d):
        self._csr = csr
        self._rhs_was_1d = rhs_was_1d

    def _run_backward(self, cotangents):
        from .. import autograd
        cot = cotangents[0]
        data, cols = self._csr._data, self._csr._indices_np
        row_ids = _jnp().asarray(self._csr._row_ids())
        K = self._csr.shape[1]
        if self._rhs_was_1d:
            # y = csr @ w with w (K,): grad rows are scalars
            contrib = data * cot[row_ids]
            return [autograd._RspGrad(contrib, cols, (K,))]
        contrib = data[:, None] * cot[row_ids]
        return [autograd._RspGrad(contrib, cols,
                                  (K,) + tuple(cot.shape[1:]))]


class _CsrTDotBackward:
    """Tape hook for dot(csr, dense, transpose_a=True): y = csrᵀ @ rhs, so
    grad wrt rhs = csr @ cot — a dense (M, N) result via the forward
    csr-dot kernel (ref: dot-inl.h backward of the transpose case)."""

    def __init__(self, csr, rhs_was_1d):
        self._csr = csr
        self._rhs_was_1d = rhs_was_1d

    def _run_backward(self, cotangents):
        cot = cotangents[0]
        if self._rhs_was_1d:
            out = _csr_dot(self._csr, cot[:, None])[:, 0]
        else:
            out = _csr_dot(self._csr, cot)
        return [out]


@register_sparse("dot", ("csr", "default"))
def _dot_csr_dense(lhs, rhs, transpose_a=False, transpose_b=False, **_ignored):
    """dot with a csr lhs (ref: src/operator/tensor/dot-inl.h DotCsrDnsDns /
    DotCsrDnsRspImpl)."""
    from ..ndarray import ndarray as _nd
    from ..ndarray import sparse as _sp
    from .. import autograd
    check(not transpose_b, "dot(csr, dense): transpose_b is not supported "
                           "(matches reference dot-inl.h)")
    rhs_data = rhs._data
    squeeze = rhs_data.ndim == 1
    if squeeze:
        rhs_data = rhs_data[:, None]
    recording = autograd.is_recording() and \
        getattr(rhs, "_tape_entry", None) is not None
    if transpose_a:
        out_rows, uniq = _csr_t_dot(lhs, rhs_data)
        if squeeze:
            out_rows = out_rows[:, 0]
            shape = (lhs.shape[1],)
        else:
            shape = (lhs.shape[1], rhs_data.shape[1])
        result = _sp.RowSparseNDArray(out_rows, uniq, shape, lhs._ctx)
        if recording:
            autograd._record_custom(_CsrTDotBackward(lhs, squeeze), [rhs],
                                    [result])
        return result
    out = _csr_dot(lhs, rhs_data)
    if squeeze:
        out = out[:, 0]
    result = _nd.NDArray(out, ctx=rhs.context)
    if recording:
        autograd._record_custom(_CsrDotBackward(lhs, squeeze), [rhs],
                                [result])
    return result


@register_sparse("elemwise_add", ("row_sparse", "row_sparse"))
def _add_rsp_rsp(lhs, rhs, **_ignored):
    """row_sparse + row_sparse → row_sparse (union of rows;
    ref: elemwise_binary_op_basic.cc sparse dispatch)."""
    from ..ndarray import sparse as _sp
    jnp = _jnp()
    idx = _np.concatenate([_np.asarray(lhs._indices),
                           _np.asarray(rhs._indices)])
    data = jnp.concatenate([lhs._data, rhs._data.astype(lhs._data.dtype)])
    return _sp.segment_sum_rows(data, idx, lhs.shape, lhs._ctx)


@register_sparse("cast_storage", ("*",))
def _cast_storage_any(data, stype="default", **_ignored):
    from ..ndarray import sparse as _sp
    return _sp.cast_storage(data, stype)


@register_sparse("sum", ("csr",))
def _sum_csr(data, axis=None, keepdims=False, **_ignored):
    """Σ over a csr without densifying (ref: square_sum/sum csr kernels)."""
    from ..ndarray import ndarray as _nd
    jnp = _jnp()
    vals, cols, row_ids = _padded_coords(data)
    if isinstance(axis, tuple):
        norm = {a % 2 for a in axis}
        axis = None if norm == {0, 1} else norm.pop()
    if axis is None:
        out = jnp.sum(vals)
        return _nd.NDArray(out if not keepdims else out.reshape(1, 1))
    if axis in (0, -2):
        out = jnp.zeros((data.shape[1],), vals.dtype).at[cols].add(vals)
        keep_shape = (1, data.shape[1])
    else:
        check(axis in (1, -1), "sum(csr): axis must be None, 0 or 1")
        out = jnp.zeros((data.shape[0],), vals.dtype).at[row_ids].add(vals)
        keep_shape = (data.shape[0], 1)
    return _nd.NDArray(out.reshape(keep_shape) if keepdims else out)


# ---------------------------------------------------------------------------
# lazy (row-sliced) optimizer update kernels
# (ref: src/operator/optimizer_op.cc row_sparse sgd/adam variants — the
#  consumers of sparse_grad; only rows present in the gradient are touched)
# ---------------------------------------------------------------------------

def _row_grad(gdata, rows, rescale_grad, clip_gradient, wd):
    jnp = _jnp()
    g = gdata * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g + wd * rows


@register("_sparse_sgd_update", dynamic_params=("lr",))
def _sparse_sgd_update(weight, gdata, gidx, lr=0.01, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0):
    rows = weight[gidx]
    g = _row_grad(gdata, rows, rescale_grad, clip_gradient, wd)
    return weight.at[gidx].set(rows - lr * g)


@register("_sparse_sgd_mom_update", dynamic_params=("lr",), num_outputs=2)
def _sparse_sgd_mom_update(weight, gdata, gidx, mom, lr=0.01, momentum=0.0,
                           wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    rows = weight[gidx]
    g = _row_grad(gdata, rows, rescale_grad, clip_gradient, wd)
    new_mom_rows = momentum * mom[gidx] - lr * g
    return (weight.at[gidx].set(rows + new_mom_rows),
            mom.at[gidx].set(new_mom_rows))


@register("_sparse_adam_update", dynamic_params=("lr",), num_outputs=3)
def _sparse_adam_update(weight, gdata, gidx, mean, var, lr=0.01, beta1=0.9,
                        beta2=0.999, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                        clip_gradient=-1.0):
    jnp = _jnp()
    rows = weight[gidx]
    g = _row_grad(gdata, rows, rescale_grad, clip_gradient, wd)
    m_rows = beta1 * mean[gidx] + (1 - beta1) * g
    v_rows = beta2 * var[gidx] + (1 - beta2) * jnp.square(g)
    w_rows = rows - lr * m_rows / (jnp.sqrt(v_rows) + epsilon)
    return (weight.at[gidx].set(w_rows), mean.at[gidx].set(m_rows),
            var.at[gidx].set(v_rows))
