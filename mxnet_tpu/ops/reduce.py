"""Reduction operators with reference axis semantics (axis/keepdims/exclude).

Reference: src/operator/tensor/broadcast_reduce_op_value.cc (+ the kernel
machinery in broadcast_reduce-inl.h, which XLA's reduce lowering replaces
wholesale on TPU).
"""
from __future__ import annotations

import numpy as _np

from .registry import register


def _jnp():
    import jax.numpy as jnp
    return jnp


def _axes(x, axis, exclude: bool):
    if axis is None or axis == ():
        ax = tuple(range(x.ndim))
    elif isinstance(axis, int):
        ax = (axis % x.ndim,)
    else:
        ax = tuple(a % x.ndim for a in axis)
    if exclude:
        ax = tuple(i for i in range(x.ndim) if i not in ax)
    return ax


def _safe_acc(x):
    """MXNET_SAFE_ACCUMULATION: accumulate low-precision reductions in
    f32 (ref: broadcast_reduce-inl.h AType promotion behind the same
    flag). Returns (maybe-upcast x, dtype to cast the result back to)."""
    from ..base import env
    jnp = _jnp()
    if env.get("MXNET_SAFE_ACCUMULATION") and \
            x.dtype in (jnp.float16, jnp.bfloat16):
        return x.astype(jnp.float32), x.dtype
    return x, None


def _reduce(jfn, accumulating: bool = True):
    def impl(x, axis=None, keepdims: bool = False, exclude: bool = False,
             **_):
        back = None
        if accumulating:
            x, back = _safe_acc(x)
        out = jfn(x, axis=_axes(x, axis, exclude), keepdims=keepdims)
        return out if back is None else out.astype(back)
    return impl


register("sum", aliases=("sum_axis",))(_reduce(lambda x, **k: _jnp().sum(x, **k)))
register("mean")(_reduce(lambda x, **k: _jnp().mean(x, **k)))
register("prod")(_reduce(lambda x, **k: _jnp().prod(x, **k)))
register("nansum")(_reduce(lambda x, **k: _jnp().nansum(x, **k)))
register("nanprod")(_reduce(lambda x, **k: _jnp().nanprod(x, **k)))
register("max", aliases=("max_axis",))(
    _reduce(lambda x, **k: _jnp().max(x, **k), accumulating=False))
register("min", aliases=("min_axis",))(
    _reduce(lambda x, **k: _jnp().min(x, **k), accumulating=False))


@register("norm")
def _norm(x, ord: int = 2, axis=None, keepdims: bool = False, **_):
    jnp = _jnp()
    x, back = _safe_acc(x)
    if axis is None:
        ax = tuple(range(x.ndim))
    elif isinstance(axis, int):
        ax = (axis,)
    else:
        ax = tuple(axis)
    if ord == 1:
        out = jnp.sum(jnp.abs(x), axis=ax, keepdims=keepdims)
    else:
        out = jnp.sqrt(jnp.sum(jnp.square(x), axis=ax, keepdims=keepdims))
    return out if back is None else out.astype(back)


@register("_square_sum", aliases=("square_sum",))
def _square_sum(data, axis=None, keepdims: bool = False, exclude=False,
                **_):
    """sum(data**2) — the reference's fused row-sparse kernel
    (src/operator/tensor/square_sum-inl.h); on TPU the dense fusion is
    XLA's, this registers the graph-level op so sym.* graphs and the
    partitioner can use it."""
    jnp = _jnp()
    x, back = _safe_acc(data)
    out = jnp.sum(jnp.square(x), axis=_axes(x, axis, bool(exclude)),
                  keepdims=keepdims)
    return out if back is None else out.astype(back)


@register("argmax", differentiable=False)
def _argmax(x, axis=None, keepdims: bool = False, **_):
    jnp = _jnp()
    out = jnp.argmax(x, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)


@register("argmin", differentiable=False)
def _argmin(x, axis=None, keepdims: bool = False, **_):
    jnp = _jnp()
    return jnp.argmin(x, axis=axis, keepdims=keepdims).astype(jnp.float32)


@register("argmax_channel", differentiable=False)
def _argmax_channel(x, **_):
    jnp = _jnp()
    return jnp.argmax(x, axis=1).astype(jnp.float32)
