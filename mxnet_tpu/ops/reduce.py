"""Reduction operators with reference axis semantics (axis/keepdims/exclude).

Reference: src/operator/tensor/broadcast_reduce_op_value.cc (+ the kernel
machinery in broadcast_reduce-inl.h, which XLA's reduce lowering replaces
wholesale on TPU).
"""
from __future__ import annotations

import numpy as _np

from .registry import register


def _jnp():
    import jax.numpy as jnp
    return jnp


def _axes(x, axis, exclude: bool):
    if axis is None or axis == ():
        ax = tuple(range(x.ndim))
    elif isinstance(axis, int):
        ax = (axis % x.ndim,)
    else:
        ax = tuple(a % x.ndim for a in axis)
    if exclude:
        ax = tuple(i for i in range(x.ndim) if i not in ax)
    return ax


def _reduce(jfn):
    def impl(x, axis=None, keepdims: bool = False, exclude: bool = False, **_):
        return jfn(x, axis=_axes(x, axis, exclude), keepdims=keepdims)
    return impl


register("sum", aliases=("sum_axis",))(_reduce(lambda x, **k: _jnp().sum(x, **k)))
register("mean")(_reduce(lambda x, **k: _jnp().mean(x, **k)))
register("prod")(_reduce(lambda x, **k: _jnp().prod(x, **k)))
register("nansum")(_reduce(lambda x, **k: _jnp().nansum(x, **k)))
register("nanprod")(_reduce(lambda x, **k: _jnp().nanprod(x, **k)))
register("max", aliases=("max_axis",))(_reduce(lambda x, **k: _jnp().max(x, **k)))
register("min", aliases=("min_axis",))(_reduce(lambda x, **k: _jnp().min(x, **k)))


@register("norm")
def _norm(x, ord: int = 2, axis=None, keepdims: bool = False, **_):
    jnp = _jnp()
    if axis is None:
        ax = tuple(range(x.ndim))
    elif isinstance(axis, int):
        ax = (axis,)
    else:
        ax = tuple(axis)
    if ord == 1:
        return jnp.sum(jnp.abs(x), axis=ax, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=ax, keepdims=keepdims))


@register("argmax", differentiable=False)
def _argmax(x, axis=None, keepdims: bool = False, **_):
    jnp = _jnp()
    out = jnp.argmax(x, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)


@register("argmin", differentiable=False)
def _argmin(x, axis=None, keepdims: bool = False, **_):
    jnp = _jnp()
    return jnp.argmin(x, axis=axis, keepdims=keepdims).astype(jnp.float32)


@register("argmax_channel", differentiable=False)
def _argmax_channel(x, **_):
    jnp = _jnp()
    return jnp.argmax(x, axis=1).astype(jnp.float32)
