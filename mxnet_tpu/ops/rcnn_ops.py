"""Region-proposal / region-CNN operators.

Reference: src/operator/contrib/proposal.cc (+ proposal-inl.h anchor
generation), multi_proposal.cc, psroi_pooling.cc,
deformable_psroi_pooling.cu (the reference's CPU path is unimplemented —
deformable_psroi_pooling.cc:54 "NOT_IMPLEMENTED"), and
bounding_box-inl.h:643 (bipartite matching).

TPU-native design notes:
- Everything is static-shape: NMS is a masked `lax.scan` over the sorted
  candidate list (no dynamic compaction), and the post-NMS output is
  filled by scatter-by-rank with the reference's cyclic padding
  (proposal.cc:404-419 fills slot i from keep[i % out_size]).
- PSROIPooling uses a summed-area table (2-D cumsum) so each bin's
  average is 4 gathers instead of a dynamic-extent loop — the classic
  TPU-friendly formulation of rectangle sums.
"""
from __future__ import annotations

import numpy as _np

from .registry import register


def _jnp():
    import jax.numpy as jnp
    return jnp


def _generate_anchors(stride, ratios, scales):
    """(ref: proposal-inl.h:184-223 GenerateAnchors/_Transform) ->
    (A, 4) numpy array, A = len(ratios) * len(scales)."""
    base_w = base_h = float(stride)
    x_ctr = 0.5 * (base_w - 1.0)
    y_ctr = 0.5 * (base_h - 1.0)
    size = base_w * base_h
    out = []
    for ratio in ratios:
        size_ratios = _np.floor(size / ratio)
        for scale in scales:
            new_w = _np.floor(_np.sqrt(size_ratios) + 0.5) * scale
            new_h = _np.floor(new_w / scale * ratio + 0.5) * scale
            out.append([x_ctr - 0.5 * (new_w - 1.0),
                        y_ctr - 0.5 * (new_h - 1.0),
                        x_ctr + 0.5 * (new_w - 1.0),
                        y_ctr + 0.5 * (new_h - 1.0)])
    return _np.asarray(out, _np.float32)


def _proposal_single(scores_fg, deltas, im_info, anchors, feature_stride,
                     pre_nms_top_n, post_nms_top_n, threshold, min_size,
                     iou_loss):
    """One image. scores_fg: (A, H, W) foreground scores; deltas:
    (4A, H, W); im_info: (3,) [height, width, scale]. Returns
    (rois (post, 4), roi_scores (post,))."""
    import jax
    jnp = _jnp()
    A = anchors.shape[0]
    H, W = scores_fg.shape[1], scores_fg.shape[2]
    K = H * W * A

    # shifted anchors in (h, w, a) order (ref: proposal.cc:347-359)
    shift_x = jnp.arange(W, dtype=jnp.float32) * feature_stride
    shift_y = jnp.arange(H, dtype=jnp.float32) * feature_stride
    shifts = jnp.stack(
        jnp.broadcast_arrays(shift_x[None, :, None], shift_y[:, None, None]),
        axis=-1)  # (H, W, 1, 2) -> [x, y]
    boxes = jnp.asarray(anchors)[None, None, :, :] + jnp.concatenate(
        [shifts, shifts], axis=-1)  # (H, W, A, 4)
    boxes = boxes.reshape(K, 4)
    scores = jnp.transpose(scores_fg, (1, 2, 0)).reshape(K)
    # deltas (4A, H, W) -> (H, W, A, 4)
    d = jnp.transpose(deltas.reshape(A, 4, H, W), (2, 3, 0, 1)).reshape(K, 4)

    im_h, im_w, im_scale = im_info[0], im_info[1], im_info[2]
    if iou_loss:
        # (ref: proposal.cc IoUTransformInv) corner offsets
        pred = boxes + d
    else:
        # (ref: proposal.cc:49-88 BBoxTransformInv)
        w = boxes[:, 2] - boxes[:, 0] + 1.0
        h = boxes[:, 3] - boxes[:, 1] + 1.0
        cx = boxes[:, 0] + 0.5 * (w - 1.0)
        cy = boxes[:, 1] + 0.5 * (h - 1.0)
        pcx = d[:, 0] * w + cx
        pcy = d[:, 1] * h + cy
        pw = jnp.exp(d[:, 2]) * w
        ph = jnp.exp(d[:, 3]) * h
        pred = jnp.stack([pcx - 0.5 * (pw - 1.0), pcy - 0.5 * (ph - 1.0),
                          pcx + 0.5 * (pw - 1.0), pcy + 0.5 * (ph - 1.0)],
                         axis=1)
    lo = jnp.zeros((), jnp.float32)
    pred = jnp.stack([jnp.clip(pred[:, 0], lo, im_w - 1.0),
                      jnp.clip(pred[:, 1], lo, im_h - 1.0),
                      jnp.clip(pred[:, 2], lo, im_w - 1.0),
                      jnp.clip(pred[:, 3], lo, im_h - 1.0)], axis=1)

    # mask anchors beyond the real (unpadded) feature extent
    # (ref: proposal.cc:362-365,83-85)
    real_h = jnp.floor(im_h / feature_stride)
    real_w = jnp.floor(im_w / feature_stride)
    hh = jnp.repeat(jnp.arange(H), W * A)
    ww = jnp.tile(jnp.repeat(jnp.arange(W), A), H)
    pad_mask = (hh >= real_h) | (ww >= real_w)
    scores = jnp.where(pad_mask, -1.0, scores)

    # FilterBox (ref: proposal.cc:145-157)
    msz = min_size * im_scale
    iw = pred[:, 2] - pred[:, 0] + 1.0
    ih = pred[:, 3] - pred[:, 1] + 1.0
    small = (iw < msz) | (ih < msz)
    pred = jnp.where(small[:, None],
                     pred + jnp.array([-0.5, -0.5, 0.5, 0.5]) * msz, pred)
    scores = jnp.where(small, -1.0, scores)

    # pre-NMS topk by score
    pre_n = min(pre_nms_top_n, K) if pre_nms_top_n > 0 else K
    order = jnp.argsort(-scores)[:pre_n]
    sboxes = pred[order]
    sscores = scores[order]

    # greedy NMS over the sorted list (masked scan; ref NonMaximumSuppression
    # proposal.cc:212-268 with +1 area convention)
    x1, y1, x2, y2 = sboxes[:, 0], sboxes[:, 1], sboxes[:, 2], sboxes[:, 3]
    area = (x2 - x1 + 1.0) * (y2 - y1 + 1.0)
    xx1 = jnp.maximum(x1[:, None], x1[None, :])
    yy1 = jnp.maximum(y1[:, None], y1[None, :])
    xx2 = jnp.minimum(x2[:, None], x2[None, :])
    yy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(xx2 - xx1 + 1.0, 0.0) * \
        jnp.maximum(yy2 - yy1 + 1.0, 0.0)
    iou = inter / (area[:, None] + area[None, :] - inter)

    def body(keep, i):
        sup = (iou[i] > threshold) & (jnp.arange(pre_n) > i) & keep[i]
        return jnp.where(sup, False, keep), None

    keep0 = jnp.ones((pre_n,), bool)
    keep, _ = jax.lax.scan(body, keep0, jnp.arange(pre_n))

    # take first post_n kept, cyclically padding when fewer
    # (ref: proposal.cc:404-419)
    rank = jnp.cumsum(keep.astype(jnp.int32)) - 1
    out_size = jnp.maximum(keep.sum(), 1)
    slots = jnp.zeros((post_nms_top_n,), jnp.int32)
    slots = slots.at[jnp.where(keep, rank, post_nms_top_n)].set(
        jnp.arange(pre_n, dtype=jnp.int32), mode="drop")
    pick = slots[jnp.mod(jnp.arange(post_nms_top_n), out_size)]
    return sboxes[pick], sscores[pick]


def _proposal_nout(n_inputs, params):
    return 2 if params.get("output_score", False) else 1


@register("_contrib_Proposal", aliases=("Proposal",),
          num_outputs=_proposal_nout, differentiable=False)
def _proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
              rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
              scales=(4, 8, 16, 32), ratios=(0.5, 1, 2), feature_stride=16,
              output_score=False, iou_loss=False):
    """RPN proposal generation, batch 1 (ref: proposal.cc _contrib_Proposal).
    cls_prob (1, 2A, H, W), bbox_pred (1, 4A, H, W), im_info (1, 3) ->
    rois (post, 5) [batch0, x1, y1, x2, y2] (+ scores (post, 1))."""
    jnp = _jnp()
    anchors = _generate_anchors(feature_stride, ratios, scales)
    A = anchors.shape[0]
    boxes, scores = _proposal_single(
        cls_prob[0, A:], bbox_pred[0], im_info[0], anchors, feature_stride,
        int(rpn_pre_nms_top_n), int(rpn_post_nms_top_n), threshold,
        float(rpn_min_size), iou_loss)
    rois = jnp.concatenate(
        [jnp.zeros((boxes.shape[0], 1), boxes.dtype), boxes], axis=1)
    if output_score:
        return rois, scores[:, None]
    return rois


@register("_contrib_MultiProposal", aliases=("MultiProposal",),
          num_outputs=_proposal_nout, differentiable=False)
def _multi_proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
                    rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
                    scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
                    feature_stride=16, output_score=False, iou_loss=False):
    """Batched Proposal (ref: multi_proposal.cc): rois (N*post, 5) with
    per-image batch indices."""
    import jax
    jnp = _jnp()
    anchors = _generate_anchors(feature_stride, ratios, scales)
    A = anchors.shape[0]
    N = cls_prob.shape[0]
    post = int(rpn_post_nms_top_n)

    def one(sc, dl, info):
        return _proposal_single(sc, dl, info, anchors, feature_stride,
                                int(rpn_pre_nms_top_n), post, threshold,
                                float(rpn_min_size), iou_loss)

    boxes, scores = jax.vmap(one)(cls_prob[:, A:], bbox_pred, im_info)
    bidx = jnp.repeat(jnp.arange(N, dtype=boxes.dtype), post)[:, None]
    rois = jnp.concatenate([bidx, boxes.reshape(N * post, 4)], axis=1)
    if output_score:
        return rois, scores.reshape(N * post, 1)
    return rois


def _integral_image(data):
    """(N, C, H, W) -> (N, C, H+1, W+1) summed-area table."""
    jnp = _jnp()
    s = jnp.cumsum(jnp.cumsum(data, axis=-1), axis=-2)
    return jnp.pad(s, ((0, 0), (0, 0), (1, 0), (1, 0)))


@register("_contrib_PSROIPooling", aliases=("PSROIPooling",))
def _psroi_pooling(data, rois, spatial_scale=1.0, output_dim=1,
                   pooled_size=1, group_size=0):
    """Position-sensitive ROI average pooling (ref: psroi_pooling.cc
    PSROIPoolForwardCPU). data (N, output_dim*group^2, H, W),
    rois (R, 5) -> (R, output_dim, pooled, pooled)."""
    import jax
    jnp = _jnp()
    pooled = int(pooled_size)
    group = int(group_size) if int(group_size) > 0 else pooled
    D = int(output_dim)
    H, W = data.shape[2], data.shape[3]
    sat = _integral_image(data)  # (N, C, H+1, W+1)

    # static channel index per (ctop, ph, pw) (ref: psroi_pooling.cc:94-98)
    phs = _np.arange(pooled)
    gh = _np.clip((phs * group) // pooled, 0, group - 1)
    c_idx = (_np.arange(D)[:, None, None] * group + gh[None, :, None]) \
        * group + gh[None, None, :]  # (D, pooled, pooled)
    c_idx = jnp.asarray(c_idx)

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1]) * spatial_scale
        y1 = jnp.round(roi[2]) * spatial_scale
        x2 = (jnp.round(roi[3]) + 1.0) * spatial_scale
        y2 = (jnp.round(roi[4]) + 1.0) * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_h = rh / pooled
        bin_w = rw / pooled
        ph = jnp.arange(pooled, dtype=jnp.float32)
        hstart = jnp.clip(jnp.floor(ph * bin_h + y1), 0, H).astype(jnp.int32)
        hend = jnp.clip(jnp.ceil((ph + 1.0) * bin_h + y1), 0, H) \
            .astype(jnp.int32)
        wstart = jnp.clip(jnp.floor(ph * bin_w + x1), 0, W).astype(jnp.int32)
        wend = jnp.clip(jnp.ceil((ph + 1.0) * bin_w + x1), 0, W) \
            .astype(jnp.int32)
        s = sat[b]  # (C, H+1, W+1)
        c = c_idx  # (D, p, p)
        hs = hstart[None, :, None]
        he = hend[None, :, None]
        ws = wstart[None, None, :]
        we = wend[None, None, :]
        rect = s[c, he, we] - s[c, hs, we] - s[c, he, ws] + s[c, hs, ws]
        bin_area = (hend[:, None] - hstart[:, None]) * (wend - wstart)[None]
        empty = bin_area <= 0
        return jnp.where(empty[None], 0.0,
                         rect / jnp.maximum(bin_area, 1)[None])

    return jax.vmap(one_roi)(rois)


@register("_contrib_DeformablePSROIPooling",
          aliases=("DeformablePSROIPooling",), num_outputs=2)
def _deformable_psroi_pooling(data, rois, *maybe_trans, spatial_scale=1.0,
                              output_dim=1, group_size=1, pooled_size=1,
                              part_size=0, sample_per_part=1, trans_std=0.0,
                              no_trans=False):
    """Deformable position-sensitive ROI pooling (ref:
    deformable_psroi_pooling.cu DeformablePSROIPoolForwardKernel; the
    reference's CPU forward is unimplemented). Returns (out, top_count)."""
    import jax
    jnp = _jnp()
    pooled = int(pooled_size)
    group = int(group_size)
    D = int(output_dim)
    spp = int(sample_per_part)
    part = int(part_size) if int(part_size) > 0 else pooled
    H, W = data.shape[2], data.shape[3]
    trans = maybe_trans[0] if (maybe_trans and not no_trans) else None
    if trans is not None:
        num_classes = trans.shape[1] // 2
    else:
        num_classes = 1
    ch_each = D // num_classes

    phs = _np.arange(pooled)
    gh = _np.clip((phs * group) // pooled, 0, group - 1)
    c_idx = (_np.arange(D)[:, None, None] * group + gh[None, :, None]) \
        * group + gh[None, None, :]
    c_idx = jnp.asarray(c_idx)  # (D, p, p)
    part_h = jnp.asarray((phs * part) // pooled)  # (p,)
    class_id = _np.arange(D) // ch_each  # (D,)

    def bilinear(img, y, x):
        # img (H, W); y, x scalars already clipped to [0, H-1]/[0, W-1]
        y0 = jnp.floor(y).astype(jnp.int32)
        x0 = jnp.floor(x).astype(jnp.int32)
        y1 = jnp.minimum(y0 + 1, H - 1)
        x1 = jnp.minimum(x0 + 1, W - 1)
        ly, lx = y - y0, x - x0
        return (img[y0, x0] * (1 - ly) * (1 - lx)
                + img[y0, x1] * (1 - ly) * lx
                + img[y1, x0] * ly * (1 - lx)
                + img[y1, x1] * ly * lx)

    def one_roi(roi, tr):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1]) * spatial_scale - 0.5
        y1 = jnp.round(roi[2]) * spatial_scale - 0.5
        x2 = (jnp.round(roi[3]) + 1.0) * spatial_scale - 0.5
        y2 = (jnp.round(roi[4]) + 1.0) * spatial_scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_h, bin_w = rh / pooled, rw / pooled
        sub_h, sub_w = bin_h / spp, bin_w / spp

        if tr is None:
            tx = jnp.zeros((D, pooled, pooled))
            ty = jnp.zeros((D, pooled, pooled))
        else:
            # tr (2*num_classes, part, part)
            tr2 = tr.reshape(num_classes, 2, part, part)
            cls = jnp.asarray(class_id)
            tx = tr2[cls][:, 0][:, part_h][:, :, part_h] * trans_std
            ty = tr2[cls][:, 1][:, part_h][:, :, part_h] * trans_std

        ph = jnp.arange(pooled, dtype=jnp.float32)
        wst = ph[None, None, :] * bin_w + x1 + tx * rw  # (D, p, p)
        hst = ph[None, :, None] * bin_h + y1 + ty * rh

        img_all = data[b]  # (C, H, W)

        def sample(ih, iw):
            y = hst + ih * sub_h
            x = wst + iw * sub_w
            valid = (x >= -0.5) & (x <= W - 0.5) & (y >= -0.5) & (y <= H - 0.5)
            yc = jnp.clip(y, 0.0, H - 1.0)
            xc = jnp.clip(x, 0.0, W - 1.0)
            val = jax.vmap(
                jax.vmap(jax.vmap(bilinear)))(img_all[c_idx], yc, xc)
            return jnp.where(valid, val, 0.0), valid

        total = jnp.zeros((D, pooled, pooled))
        count = jnp.zeros((D, pooled, pooled))
        for ih in range(spp):
            for iw in range(spp):
                v, ok = sample(float(ih), float(iw))
                total = total + v
                count = count + ok
        out = jnp.where(count > 0, total / jnp.maximum(count, 1), 0.0)
        return out, count

    if trans is None:
        out, cnt = jax.vmap(lambda r: one_roi(r, None))(rois)
    else:
        out, cnt = jax.vmap(one_roi)(rois, trans)
    return out, cnt


@register("_contrib_bipartite_matching", aliases=("bipartite_matching",),
          num_outputs=2, differentiable=False)
def _bipartite_matching(score, threshold=0.0, is_ascend=False, topk=-1):
    """Greedy bipartite matching (ref: bounding_box-inl.h:682
    bipartite_matching kernel). score (..., R, C) -> (row_match (..., R),
    col_match (..., C)); unmatched = -1. The reference's topk records one
    extra match past the limit (count > topk after assignment); here topk
    is exact."""
    import jax
    jnp = _jnp()
    shape = score.shape
    R, C = shape[-2], shape[-1]
    flat = score.reshape((-1, R, C))

    def per_batch(s):
        order = jnp.argsort(jnp.where(is_ascend, s, -s).reshape(-1))
        svals = s.reshape(-1)[order]
        rows = order // C
        cols = order % C

        def body(carry, j):
            rmark, cmark, cnt = carry
            r, c, v = rows[j], cols[j], svals[j]
            good = jnp.where(is_ascend, v < threshold, v > threshold)
            free = (rmark[r] == -1) & (cmark[c] == -1)
            can = good & free & ((topk <= 0) | (cnt < topk))
            rmark = rmark.at[r].set(jnp.where(can, c, rmark[r]))
            cmark = cmark.at[c].set(jnp.where(can, r, cmark[c]))
            return (rmark, cmark, cnt + can.astype(jnp.int32)), None

        init = (-jnp.ones((R,), s.dtype), -jnp.ones((C,), s.dtype),
                jnp.zeros((), jnp.int32))
        (rmark, cmark, _), _ = jax.lax.scan(body, init, jnp.arange(R * C))
        return rmark, cmark

    rm, cm = jax.vmap(per_batch)(flat)
    return rm.reshape(shape[:-1]), cm.reshape(shape[:-2] + (C,))
