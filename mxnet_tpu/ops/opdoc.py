"""Signature + docstring generation for frontend op functions.

The reference generates full Python signatures and numpydoc docstrings
from each op's C++ parameter struct (MXSymbolGetAtomicSymbolInfo +
dmlc/parameter.h __DOC__, consumed by python/mxnet/ndarray/register.py).
Here the registry op IS a Python function, so its signature carries the
same metadata: array inputs are the leading positional params, op params
are the keyword params with defaults. This module turns that into a
``inspect.Signature`` (so ``help(nd.Convolution)`` shows typed params and
IDEs autocomplete) and a numpydoc-style docstring.
"""
from __future__ import annotations

import inspect
from typing import Any, Dict, Tuple

__all__ = ["signature_and_doc"]

_HIDDEN = {"_key", "_training"}  # injected by the frontend wrapper


def _type_name(default: Any) -> str:
    if isinstance(default, bool):
        return "boolean"
    if isinstance(default, int):
        return "int"
    if isinstance(default, float):
        return "float"
    if isinstance(default, str):
        return "string"
    if isinstance(default, (tuple, list)):
        return "Shape(tuple)"
    return "any"


def _split_params(opdef) -> Tuple[list, list, bool]:
    """(array_inputs, [(param, default)], variadic) from the impl fn."""
    try:
        sig = inspect.signature(opdef.fn)
    except (TypeError, ValueError):
        return [], [], True
    inputs, params = [], []
    variadic = False
    for p in sig.parameters.values():
        if p.name in _HIDDEN:
            continue
        if p.kind == p.VAR_POSITIONAL:
            variadic = True
        elif p.kind == p.VAR_KEYWORD:
            continue
        elif p.default is p.empty:
            inputs.append(p.name)
        else:
            params.append((p.name, p.default))
    return inputs, params, variadic


def signature_and_doc(name: str, opdef, creation: bool = False,
                      symbol: bool = False):
    """Returns (inspect.Signature, docstring) for the frontend wrapper."""
    inputs, params, variadic = _split_params(opdef)
    kind_arr = "Symbol" if symbol else "NDArray"

    sig_params = []
    P = inspect.Parameter
    for n in inputs:
        sig_params.append(P(n, P.POSITIONAL_OR_KEYWORD))
    if variadic:
        var_name = "args" if "args" not in inputs else "more_args"
        sig_params.append(P(var_name, P.VAR_POSITIONAL))
    for n, d in params:
        sig_params.append(P(n, P.KEYWORD_ONLY, default=d))
    used = {p.name for p in sig_params}
    if creation and "ctx" not in used:
        sig_params.append(P("ctx", P.KEYWORD_ONLY, default=None))
    if not symbol and "out" not in used:
        sig_params.append(P("out", P.KEYWORD_ONLY, default=None))
    if "name" not in used:
        sig_params.append(P("name", P.KEYWORD_ONLY, default=None))
    signature = inspect.Signature(sig_params)

    lines = []
    body = (opdef.doc or "").strip()
    if body:
        lines.append(body)
        lines.append("")
    lines.append("Parameters")
    lines.append("----------")
    for n in inputs:
        lines.append(f"{n} : {kind_arr}")
        lines.append(f"    Input {kind_arr.lower()}.")
    if variadic:
        lines.append(f"*args : {kind_arr}(s)")
        lines.append("    Variadic input arrays.")
    for n, d in params:
        lines.append(f"{n} : {_type_name(d)}, optional, default={d!r}")
    if creation:
        lines.append("ctx : Context, optional")
        lines.append("    Device context of the output.")
    if not symbol:
        lines.append("out : NDArray, optional")
        lines.append("    Output buffer (written in place).")
    lines.append("name : string, optional")
    lines.append("    Name hint (symbolic graphs).")
    lines.append("")
    lines.append("Returns")
    lines.append("-------")
    n_out = opdef.num_outputs
    if callable(n_out) or (isinstance(n_out, int) and n_out > 1):
        lines.append(f"tuple of {kind_arr}")
    else:
        lines.append(f"out : {kind_arr}")
    return signature, "\n".join(lines)
